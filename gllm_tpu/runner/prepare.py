"""Host-side batch building: ScheduledBatch → StepBatch device arrays.

Mirrors the reference InputData.cal_input path
(/root/reference/gllm/input_data.py:338-533): flat token/position/slot
buffers, query-start offsets, per-seq kv lens and page tables, all padded to
*bucketed* static shapes so the jit cache stays small (the reference's
power-of-two CUDA-graph buckets → our compile-cache buckets).

Staging happens in numpy and ships to device as ONE batched
``jax.device_put`` of the whole StepBatch pytree — a dozen separate
per-array transfers each paid the dispatch (and, on a remote-attached
TPU, the network) round trip. The base fill is vectorized (flat scatters
over ragged rows — the reference's vectorized-fill war story,
input_data.py:436-476); only rare per-item features (seeds, mm splicing,
prompt-logprob targets) loop, and only over the items that use them.
~2 ms at a 256-seq decode bucket, amortized further by the fused
multi-step decode.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from gllm_tpu.batching import StepBatch
from gllm_tpu.config import EngineConfig
from gllm_tpu.ops.attention import AttentionMetadata
from gllm_tpu.ops.sampling import SamplingMetadata
from gllm_tpu.scheduler import ScheduledBatch
from gllm_tpu.utils import bucket_size, cdiv


class BatchBuilder:
    def __init__(self, config: EngineConfig, page_size: int,
                 vocab_size: int = 0, hidden_size: int = 0,
                 use_mm: bool = False, use_ssm: bool = False,
                 mm_embed_dim: int = 0):
        self.config = config
        self.page_size = page_size
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        # visual-row width: hidden_size, or (1+n_deepstack)*hidden for
        # Qwen3-VL stacked features
        self.mm_embed_dim = mm_embed_dim or hidden_size
        self.use_mm = use_mm
        self.use_ssm = use_ssm
        sc = config.scheduler
        # Upper bounds for the shape buckets. Speculative decoding adds up
        # to spec_k draft rows per decode seq.
        spec_rows = (config.spec_k if config.spec_decode else 0)
        self.max_tokens = (sc.max_prefill_tokens
                           + sc.max_decode_seqs * (1 + spec_rows))
        self.max_seqs = min(config.max_num_seqs,
                            sc.max_decode_seqs + sc.max_prefill_tokens)
        self.max_pages_per_seq = config.max_pages_per_seq
        # Unified mixed-batch step (--unified-step): ONE signature family
        # — max_q_len is pinned to the token bucket for every batch, so
        # the compile key collapses to (pow2 row bucket × pow2 token
        # bucket × pages) with no separate decode (q=1) population, and
        # pure decode (T == S) lands on the same family at t == s.
        # Inert for hybrid (GDN) models — the runner keeps the whole
        # flag legacy there (kernels, signatures, engine absorb path)
        # and warns.
        self.unified = (bool(getattr(config, "unified_step", False))
                        and not use_ssm)

    def shape_signature(self, batch: ScheduledBatch) -> Tuple[int, int, int,
                                                              int]:
        """(T_bucket, S_bucket, max_q_len, pages_bucket) — the compile key.

        pages_bucket bounds the page-table width (and thus the attention
        gather extent) by the *live* maximum context in this batch instead
        of max_model_len — decode cost tracks actual sequence lengths.
        """
        s = bucket_size(batch.num_seqs, 8, self.max_seqs)
        rows = [it.num_new_tokens + len(it.draft_tokens)
                for it in batch.items]
        max_q = max(rows)
        if self.unified:
            # ONE dispatch family (--unified-step): max_q rides the
            # token bucket (no separate q=1 population), and every
            # MIXED batch pads its token axis to the single schedulable
            # maximum — max_prefill_tokens + the decode-seq rows — the
            # natural geometry for token throttling to balance against.
            # This kills the per-workload token LADDER the legacy split
            # warms (each prefill composition its own compile): mixed
            # steps compile once per (row, pages) bucket, and chunked
            # prefill targets the budget anyway so the padding is small
            # exactly when mixed steps dominate. Pure decode pins t to
            # the seq bucket EXACTLY (one token per row — the fused
            # chains and the chained token splice live here), the t == s
            # point of the same q == t family.
            t = s if max_q == 1 else self.max_tokens
            q = t
        elif max_q == 1:
            t, q = s, 1          # pure decode: one token per seq
        else:
            t = bucket_size(sum(rows), 16, self.max_tokens)
            q = t
        # a seq's table can be LONGER than this step needs (a previous
        # speculative step allocated for drafts that were then rejected) —
        # the scatter writes whole table rows, so the bucket must cover
        # the real lengths
        max_pages = max(
            max(cdiv(it.computed_before + it.num_new_tokens
                     + len(it.draft_tokens), self.page_size),
                len(it.seq.page_table))
            for it in batch.items)
        p = bucket_size(max_pages, 4, self.max_pages_per_seq)
        return t, s, q, p

    def empty(self, signature, step_key, force_extras=frozenset(),
              force_bias_len=None):
        """An all-padding StepBatch of the given signature (idle DP
        replicas run these so every replica contributes the same jit
        signature — the TPU analogue of the reference's idle-replica dummy
        batches, worker.py:750-829). ``force_extras`` must match the live
        replicas' optional-field structure."""
        t_pad, s_pad, _, p_pad = signature
        bias_len = force_bias_len or 8
        return StepBatch(
            token_ids=np.zeros(t_pad, np.int32),
            positions=np.zeros(t_pad, np.int32),
            slot_mapping=np.zeros(t_pad, np.int32),
            logits_indices=np.zeros(s_pad, np.int32),
            attn=AttentionMetadata(
                cu_q_lens=np.zeros(s_pad + 1, np.int32),
                kv_lens=np.zeros(s_pad, np.int32),
                page_table=np.zeros((s_pad, p_pad), np.int32),
                num_seqs=np.asarray(0, np.int32)),
            sampling=SamplingMetadata(
                temperature=np.zeros(s_pad, np.float32),
                top_p=np.ones(s_pad, np.float32),
                top_k=np.full((s_pad,), -1, np.int32),
                repetition_penalty=np.ones(s_pad, np.float32),
                step_key=step_key,
                presence_penalty=(np.zeros(s_pad, np.float32)
                                  if "penalties" in force_extras else None),
                frequency_penalty=(np.zeros(s_pad, np.float32)
                                   if "penalties" in force_extras
                                   else None),
                seed=(np.full((s_pad,), -1, np.int32)
                      if "seed" in force_extras else None),
                out_step=(np.zeros(s_pad, np.int32)
                          if "seed" in force_extras else None),
                min_p=np.zeros(s_pad, np.float32),
                bias_ids=(np.zeros((s_pad, bias_len), np.int32)
                          if "bias" in force_extras else None),
                bias_vals=(np.zeros((s_pad, bias_len), np.float32)
                           if "bias" in force_extras else None)),
            spec_rows=(np.zeros(
                (s_pad, self.config.spec_k + 1), np.int32)
                if "spec" in force_extras else None),
            spec_drafts=(np.full(
                (s_pad, self.config.spec_k), -1, np.int32)
                if "spec" in force_extras else None),
            plp_targets=(np.zeros(t_pad, np.int32)
                         if "plp" in force_extras else None),
            ssm_slots=(np.zeros(s_pad, np.int32) if self.use_ssm
                       else None),
            mrope_positions=(np.zeros((3, t_pad), np.int32)
                             if self.use_mm else None),
            # mm_mask rides with mm_embeds (build's structure): both exist
            # iff a replica this step carries visual rows ("mm" forced)
            mm_mask=(np.zeros(t_pad, bool)
                     if self.use_mm and "mm" in force_extras else None),
            mm_embeds=(np.zeros((t_pad, self.mm_embed_dim), np.float32)
                       if self.use_mm and "mm" in force_extras else None),
        )

    @staticmethod
    def host_row_mask(host_rows, s_bucket: int) -> np.ndarray:
        """[S_bucket] bool slot map for chained-step token splicing: True
        rows (sequences that JOINED the persistent chain through a vacant
        slot) keep the host-built token value, False rows take the
        previous step's on-device sampled token. Padding rows stay False
        — their device token is garbage either way and their slot maps
        to the dummy page."""
        mask = np.zeros(s_bucket, bool)
        mask[np.asarray(host_rows, np.int64)] = True
        return mask

    def stop_sets(self, items, s_bucket: int, eos_token_ids,
                  absolute: bool = False):
        """On-device finish detection inputs for a fused multi-step
        block: ([S, E] padded per-row EOS/stop-token-id sets, [S] arming
        sub-step) for ``SamplingMetadata.stop_ids`` / ``stop_from``.

        ``items`` are the chain's FIRST batch items (their
        computed_before anchors the output-token indexing: the token
        committed by sub-step k is output number
        ``computed_before + k + 2 - prompt_len``, so min_tokens arms the
        check from sub-step ``min_tokens + prompt_len - computed_before
        - 2``). The id bucket E is pow2 (min 8) so the jit signature
        stays bounded; -1 padding never matches a sampled id. Returns
        (None, None) when no row carries any stop id (e.g. ignore_eos
        benchmarks) — the device program then skips the compare and
        on-device deaths come only from the active_until length bound.

        ``absolute=True`` (fused on-device speculation, whose carried
        frontier makes sub-step indices meaningless across blocks):
        ``stop_from`` becomes the ABSOLUTE position threshold
        ``min_tokens + prompt_len - 2`` — the device arms the check when
        the emitted token's feed position ``pos + j`` reaches it, which
        is the same inequality the relative form encodes (legacy:
        sub-step k at position cb + k armed when k >= mt + prompt - cb
        - 2 ⟺ cb + k >= mt + prompt - 2). Rows without min_tokens get
        a large negative threshold (always armed).
        """
        from gllm_tpu.sequence import HOLE_SEQ_ID
        from gllm_tpu.utils import next_pow2
        # HOLE rows (persistent-slot mode) are dead for the whole block
        # (alive count 0) — they must never contribute ids, or a finish
        # in an all-ignore_eos workload would widen the id bucket and
        # force a mid-run recompile
        sets = [([] if it.seq.seq_id == HOLE_SEQ_ID
                 else it.seq.device_stop_ids(eos_token_ids))
                for it in items]
        if not any(sets):
            return None, None
        E = max(8, next_pow2(max(len(s) for s in sets)))
        stop_ids = np.full((s_bucket, E), -1, np.int32)
        stop_from = np.full(s_bucket, -(1 << 30) if absolute else 0,
                            np.int32)
        for i, (it, ids) in enumerate(zip(items, sets)):
            stop_ids[i, :len(ids)] = ids
            mt = it.seq.sampling_params.min_tokens
            if absolute:
                stop_from[i] = (mt + it.seq.prompt_len - 2 if mt
                                else -(1 << 30))
            elif mt:
                stop_from[i] = max(0, mt + it.seq.prompt_len
                                   - it.computed_before - 2)
        return stop_ids, stop_from

    @staticmethod
    def penalty_len_bucket(lens) -> int:
        """Shared penalty id-list length bucket (build + dp wrapper must
        agree on the jit-signature L)."""
        from gllm_tpu.utils import next_pow2
        return max(16, next_pow2(max(lens))) if lens else 16

    @staticmethod
    def bias_len_bucket(ns) -> int:
        """Shared logit_bias entry-count bucket (build + dp wrapper must
        agree on the jit-signature B)."""
        from gllm_tpu.utils import next_pow2
        return max(8, next_pow2(max(ns))) if ns else 8

    @staticmethod
    def batch_extras(batch: ScheduledBatch) -> frozenset:
        """Which optional StepBatch fields this batch populates — DP
        replicas must agree on the union so stacked pytrees match."""
        extras = set()
        for it in batch.items:
            sp = it.seq.sampling_params
            if sp.seed is not None:
                extras.add("seed")
            if (sp.repetition_penalty != 1.0 or sp.presence_penalty != 0.0
                    or sp.frequency_penalty != 0.0):
                extras.add("penalties")
            if sp.logit_bias:
                extras.add("bias")
            if (sp.prompt_logprobs is not None
                    and it.computed_before < it.seq.prompt_len):
                extras.add("plp")
            mm = getattr(it.seq, "mm", None)
            if (mm is not None
                    and it.computed_before + it.num_new_tokens
                    <= it.seq.prompt_len
                    and (mm.vis_index[it.computed_before:
                                      it.computed_before
                                      + it.num_new_tokens] >= 0).any()):
                extras.add("mm")
            if it.draft_tokens:
                extras.add("spec")
        return frozenset(extras)

    def build(self, batch: ScheduledBatch, step_key,
              force_signature=None, force_extras=frozenset(),
              force_penalty_len=None, force_bias_len=None, device=True):
        """Returns (StepBatch, max_q_len, token_counts_or_None).

        ``force_signature`` overrides the computed shape buckets and
        ``force_extras`` forces optional fields to exist (DP replicas must
        agree on one signature + structure per step).

        ``device``: place the whole StepBatch with ONE batched
        ``jax.device_put`` (a dozen separate small `jnp.asarray` transfers
        per step would each pay the dispatch — and on the remote axon
        tunnel, the network — round trip). Callers that re-place the batch
        themselves (dp stacking with shardings, PP per-stage fan-out) pass
        ``device=False`` and receive host numpy leaves."""
        t_pad, s_pad, max_q, p_pad = (force_signature
                                      or self.shape_signature(batch))
        page = self.page_size
        force_seeded = "seed" in force_extras
        force_penalties = "penalties" in force_extras
        force_plp = "plp" in force_extras

        tokens = np.zeros(t_pad, np.int32)
        positions = np.zeros(t_pad, np.int32)
        slots = np.zeros(t_pad, np.int32)          # padding → dummy page slot
        cu = np.zeros(s_pad + 1, np.int32)
        kv_lens = np.zeros(s_pad, np.int32)
        page_table = np.zeros((s_pad, p_pad), np.int32)
        logits_idx = np.zeros(s_pad, np.int32)
        temperature = np.zeros(s_pad, np.float32)
        top_p = np.ones(s_pad, np.float32)
        top_k = np.full(s_pad, -1, np.int32)
        min_p = np.zeros(s_pad, np.float32)
        rep_penalty = np.ones(s_pad, np.float32)
        seeds = np.full(s_pad, -1, np.int32)
        out_steps = np.zeros(s_pad, np.int32)
        any_seeded = False
        # VL batches always carry mrope; the dense [T, H] visual-row
        # buffer is allocated lazily on first visual row so text-only /
        # decode steps (the common case) skip the host→device transfer
        # entirely (one extra jit variant).
        mm_embeds = None
        if self.use_mm:
            mrope = np.zeros((3, t_pad), np.int32)
            mm_mask = np.zeros(t_pad, bool)
            if "mm" in force_extras:
                # DP replicas must agree on the visual-row buffer's
                # presence even when this replica's batch has none
                mm_embeds = np.zeros((t_pad, self.mm_embed_dim),
                                     np.float32)
        if self.use_ssm:
            ssm_slots = np.zeros(s_pad, np.int32)   # padding → dummy slot 0

        want_plp = force_plp or any(
            it.seq.sampling_params.prompt_logprobs is not None
            and it.computed_before < it.seq.prompt_len
            for it in batch.items)
        plp_targets = np.zeros(t_pad, np.int32) if want_plp else None

        # Vectorized base fill: the per-item python loop cost ~8 ms at a
        # 256-seq decode bucket (numpy-op overhead × 15 ops × items); the
        # flat-scatter form is ~C-speed. Rare per-item features (seeds,
        # mm, plp) fall to targeted loops over only the items that use
        # them. Semantics byte-identical (engine identity tests).
        items = batch.items
        K = len(items)
        # Host-tier invariant (gllm_tpu/kvswap): a seq that reaches the
        # builder must have had its swap-in recorded at admission — its
        # restore intent drains before this batch's forward, so building
        # rows over still-host-resident KV here would read garbage.
        assert not any(it.seq.swap_host_pages for it in items), \
            "SWAPPED seq scheduled without a recorded swap-in"
        # speculative drafts add verify rows after each item's committed
        # chunk; everything downstream (positions, slots, kv_lens, causal
        # attention) treats them as ordinary chunk rows
        ns = np.fromiter(
            (it.num_new_tokens + len(it.draft_tokens) for it in items),
            np.int64, count=K)
        befores = np.fromiter((it.computed_before for it in items),
                              np.int64, count=K)
        ends = np.cumsum(ns)
        offs = ends - ns
        total = int(ends[-1]) if K else 0
        cu[1:K + 1] = ends
        cu[K + 1:] = total
        kv_lens[:K] = befores + ns
        logits_idx[:K] = ends - 1

        rows = np.repeat(np.arange(K), ns)            # item idx per token
        pos = (np.arange(total) - np.repeat(offs, ns)
               + np.repeat(befores, ns))              # absolute positions
        positions[:total] = pos

        # ragged page-table rows → one flat scatter; the np form of each
        # row is cached on the Sequence (rows only change on page alloc,
        # every page_size-th decode step)
        def _pt_arr(seq):
            pt = seq.page_table
            c = getattr(seq, "_pt_np", None)
            if c is None or len(c) != len(pt):
                c = np.asarray(pt, np.int32)
                seq._pt_np = c
            return c

        pt_lens = np.fromiter((len(it.seq.page_table) for it in items),
                              np.int64, count=K)
        if K:
            flat_pt = np.concatenate([_pt_arr(it.seq) for it in items])
            pt_rows = np.repeat(np.arange(K), pt_lens)
            pt_cols = (np.arange(int(pt_lens.sum()))
                       - np.repeat(np.cumsum(pt_lens) - pt_lens, pt_lens))
            page_table[pt_rows, pt_cols] = flat_pt
        slots[:total] = (page_table[rows, pos // page] * page
                         + pos % page)

        # token values; chained overlap-decode rows have no host-side
        # value yet (it lives on device; the runner splices it in) → 0s
        def _tok_vals(it):
            tid = it.seq.token_ids
            b, n = it.computed_before, it.num_new_tokens
            v = tid[b:b + n]
            if len(v) != n:
                v = list(v) + [0] * (n - len(v))
            if it.draft_tokens:
                v = list(v) + list(it.draft_tokens)
            return v

        tokens[:total] = np.fromiter(
            (t for it in items for t in _tok_vals(it)), np.int32,
            count=total)

        sps = [it.seq.sampling_params for it in items]
        temperature[:K] = np.fromiter((sp.temperature for sp in sps),
                                      np.float32, count=K)
        top_p[:K] = np.fromiter((sp.top_p for sp in sps), np.float32,
                                count=K)
        top_k[:K] = np.fromiter((sp.top_k for sp in sps), np.int32,
                                count=K)
        min_p[:K] = np.fromiter((sp.min_p for sp in sps), np.float32,
                                count=K)
        rep_penalty[:K] = np.fromiter((sp.repetition_penalty for sp in sps),
                                      np.float32, count=K)
        if self.use_ssm:
            ssm_slots[:K] = np.fromiter(
                (getattr(it.seq, "ssm_slot", None) or 0 for it in items),
                np.int32, count=K)

        for i, it in enumerate(items):
            sp = sps[i]
            if sp.seed is not None:
                any_seeded = True
                seeds[i] = sp.seed
                # index of the output token this step will sample
                out_steps[i] = int(befores[i] + ns[i]) - it.seq.prompt_len
            if want_plp and sp.prompt_logprobs is not None:
                seq, b, n = it.seq, int(befores[i]), int(ns[i])
                off = int(offs[i])
                # row at position p scores prompt token p+1
                nxt = np.asarray(
                    seq.token_ids[b + 1:min(b + n + 1, seq.prompt_len)],
                    np.int32)
                plp_targets[off:off + len(nxt)] = nxt

        if self.use_mm:
            # default: text rows use 1-D positions on all three axes
            mrope[:, :total] = pos[None, :]
            for i, it in enumerate(items):
                mm = it.seq.mm
                if mm is None:
                    continue
                seq, b, n = it.seq, int(befores[i]), int(ns[i])
                off = int(offs[i])
                p_i = pos[off:off + n]
                if b + n <= seq.prompt_len:
                    # prefill chunk: precomputed 3-D prompt positions +
                    # visual-row splicing
                    mrope[:, off:off + n] = mm.mrope_positions[:, b:b + n]
                    vis = mm.vis_index[b:b + n]
                    sel = vis >= 0
                    if sel.any():
                        if mm_embeds is None:
                            mm_embeds = np.zeros(
                                (t_pad, self.mm_embed_dim), np.float32)
                        mm_mask[off:off + n] = sel
                        mm_embeds[off:off + n][sel] = \
                            mm.vis_embeds[vis[sel]]
                else:
                    # decode: extrapolate all three axes with the prompt's
                    # mrope delta (reference get_next_input_positions)
                    mrope[:, off:off + n] = (p_i + mm.mrope_delta)[None, :]

        # Repetition/presence/frequency penalties need per-token occurrence
        # counts (reference keeps a persistent GPU mask pool,
        # memory_manager.py:723-828; we build counts host-side only for
        # batches that actually use a penalty).
        token_counts = None
        pres = freq = None

        def _uses_penalty(sp):
            return (sp.repetition_penalty != 1.0
                    or sp.presence_penalty != 0.0
                    or sp.frequency_penalty != 0.0)

        if self.vocab_size and (force_penalties or any(
                _uses_penalty(it.seq.sampling_params)
                for it in batch.items)):
            from gllm_tpu.ops.sampling import PenaltyTokens
            from gllm_tpu.utils import next_pow2
            lens = [len(it.seq.token_ids) for it in batch.items
                    if _uses_penalty(it.seq.sampling_params)]
            # DP replicas must agree on L (the stacked pytrees share one
            # jit signature) — the dp wrapper passes the cross-replica max
            L = force_penalty_len or self.penalty_len_bucket(lens)
            ids = np.zeros((s_pad, L), np.int32)
            mask = np.zeros((s_pad, L), bool)
            pres = np.zeros(s_pad, np.float32)
            freq = np.zeros(s_pad, np.float32)
            for i, it in enumerate(batch.items):
                sp = it.seq.sampling_params
                if _uses_penalty(sp):
                    row = np.asarray(it.seq.token_ids, np.int64)
                    # visual placeholder ids can sit past the LM vocab
                    # (Kimi's media pad) — they never appear in logits
                    row = row[row < self.vocab_size][:L]
                    ids[i, :len(row)] = row
                    mask[i, :len(row)] = True
                    pres[i] = sp.presence_penalty
                    freq[i] = sp.frequency_penalty
            token_counts = PenaltyTokens(ids, mask)

        # OpenAI logit_bias: sparse per-seq (id, bias) pairs, padded to a
        # shared bucket B (reference protocol.py logit_bias → sampler add).
        bias_ids = bias_vals = None
        if "bias" in force_extras or any(sp.logit_bias for sp in sps):
            B = force_bias_len or self.bias_len_bucket(
                [len(sp.logit_bias) for sp in sps if sp.logit_bias])
            bias_ids = np.zeros((s_pad, B), np.int32)
            bias_vals = np.zeros((s_pad, B), np.float32)
            for i, sp in enumerate(sps):
                if sp.logit_bias:
                    # ids past the bucket (or the LM vocab) are dropped;
                    # value 0 padding keeps the scatter-add a no-op
                    pairs = [(t, b) for t, b in sp.logit_bias.items()
                             if t < (self.vocab_size or 1 << 30)][:B]
                    for j, (t, b) in enumerate(pairs):
                        bias_ids[i, j] = t
                        bias_vals[i, j] = b

        spec_rows_arr = spec_drafts_arr = None
        if any(it.draft_tokens for it in items) or "spec" in force_extras:
            kmax = self.config.spec_k
            spec_rows = np.zeros((s_pad, kmax + 1), np.int32)
            spec_drafts = np.full((s_pad, kmax), -1, np.int32)
            for i, it in enumerate(items):
                d = len(it.draft_tokens)
                # verify rows: the item's LAST committed row + its draft
                # rows (row r predicts the token at r's position + 1);
                # no-draft / padded entries point at row 0 with -1 drafts
                # (never accepted, argmax there unused)
                if d:
                    base = int(offs[i]) + it.num_new_tokens - 1
                    spec_rows[i, :d + 1] = base + np.arange(d + 1)
                    spec_drafts[i, :d] = it.draft_tokens
            spec_rows_arr = spec_rows
            spec_drafts_arr = spec_drafts

        step_batch = StepBatch(
            token_ids=tokens,
            positions=positions,
            slot_mapping=slots,
            logits_indices=logits_idx,
            attn=AttentionMetadata(
                cu_q_lens=cu,
                kv_lens=kv_lens,
                page_table=page_table,
                num_seqs=np.asarray(batch.num_seqs, np.int32)),
            sampling=SamplingMetadata(
                temperature=temperature,
                top_p=top_p,
                top_k=top_k,
                repetition_penalty=rep_penalty,
                step_key=step_key,
                presence_penalty=pres,
                frequency_penalty=freq,
                # None keeps the fused single-draw gumbel path (the common
                # all-unseeded case); per-row keys only when a request
                # actually asked for a seed (one extra jit variant).
                seed=(seeds if any_seeded or force_seeded else None),
                out_step=(out_steps
                          if any_seeded or force_seeded else None),
                min_p=min_p,
                bias_ids=bias_ids,
                bias_vals=bias_vals),
            mrope_positions=mrope if self.use_mm else None,
            mm_embeds=mm_embeds,
            mm_mask=(mm_mask
                     if self.use_mm and mm_embeds is not None else None),
            ssm_slots=ssm_slots if self.use_ssm else None,
            plp_targets=plp_targets,
            spec_rows=spec_rows_arr,
            spec_drafts=spec_drafts_arr,
        )
        if device:
            # one batched transfer for the whole step batch (token_counts
            # rides separately: its bucketed L changes more often)
            step_batch = jax.device_put(step_batch)
            if token_counts is not None:
                token_counts = jax.device_put(token_counts)
        return step_batch, max_q, token_counts
