"""Tool-call parser tests (reference tokenizers/tool_parsers.py surface)."""

import json

from gllm_tpu.entrypoints.tool_parsers import (DeepSeekToolParser,
                                               QwenToolParser,
                                               coerce_arguments,
                                               get_tool_parser,
                                               schemas_from_tools)


def test_qwen_single_call_with_content():
    text = ('Let me check the weather.\n<tool_call>\n'
            '{"name": "get_weather", "arguments": {"city": "Paris"}}\n'
            '</tool_call>')
    content, calls = QwenToolParser().parse(text)
    assert content == "Let me check the weather."
    assert len(calls) == 1
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Paris"}


def test_qwen_multiple_calls():
    text = ('<tool_call>\n{"name": "a", "arguments": {}}\n</tool_call>\n'
            '<tool_call>\n{"name": "b", "arguments": {"x": 1}}\n</tool_call>')
    content, calls = QwenToolParser().parse(text)
    assert content == ""
    assert [c.name for c in calls] == ["a", "b"]


def test_qwen_malformed_json_left_as_content():
    text = "<tool_call>\n{not json}\n</tool_call>"
    content, calls = QwenToolParser().parse(text)
    assert calls == []
    assert "not json" in content


def test_schema_coercion():
    schema = {"properties": {"n": {"type": "integer"},
                             "f": {"type": "number"},
                             "b": {"type": "boolean"},
                             "o": {"type": "object"}}}
    args = coerce_arguments(
        {"n": "42", "f": "3.5", "b": "true", "o": '{"k": 1}', "s": "x"},
        schema)
    assert args == {"n": 42, "f": 3.5, "b": True, "o": {"k": 1}, "s": "x"}


def test_qwen_coercion_via_schemas():
    tools = [{"type": "function", "function": {
        "name": "add", "parameters": {
            "properties": {"x": {"type": "integer"}}}}}]
    text = ('<tool_call>\n{"name": "add", "arguments": {"x": "7"}}\n'
            '</tool_call>')
    _, calls = QwenToolParser().parse(text, schemas_from_tools(tools))
    assert json.loads(calls[0].arguments) == {"x": 7}


def test_deepseek_format():
    text = ("thinking...<｜tool▁calls▁begin｜><｜tool▁call▁begin｜>"
            "get_time<｜tool▁sep｜>{\"tz\": \"UTC\"}"
            "<｜tool▁call▁end｜><｜tool▁calls▁end｜>")
    content, calls = DeepSeekToolParser().parse(text)
    assert content == "thinking..."
    assert calls[0].name == "get_time"
    assert json.loads(calls[0].arguments) == {"tz": "UTC"}


def test_autodetect():
    assert isinstance(get_tool_parser(None, "Qwen/Qwen3-8B"),
                      QwenToolParser)
    assert isinstance(get_tool_parser(None, "deepseek-ai/DeepSeek-V3"),
                      DeepSeekToolParser)
    assert get_tool_parser(None, "meta-llama/Llama-3").parse(
        "plain") == ("plain", [])
    assert isinstance(get_tool_parser("hermes", ""), QwenToolParser)


def test_openai_wire_format():
    _, calls = QwenToolParser().parse(
        '<tool_call>{"name": "f", "arguments": {}}</tool_call>')
    d = calls[0].to_openai()
    assert d["type"] == "function" and d["id"].startswith("call_")
    assert d["function"] == {"name": "f", "arguments": "{}"}


def test_deepseek_v3_stock_template_format():
    # the actual V3/R1 chat-template layout:
    # function<sep>NAME\n```json\nARGS\n```
    text = ("<｜tool▁calls▁begin｜><｜tool▁call▁begin｜>function"
            "<｜tool▁sep｜>get_weather\n```json\n{\"city\": \"Paris\"}\n```"
            "<｜tool▁call▁end｜><｜tool▁calls▁end｜>")
    content, calls = DeepSeekToolParser().parse(text)
    assert content == ""
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Paris"}


# ---- incremental streaming adapter ----------------------------------------

def _feed_chunks(stream, text, n=7):
    """Feed in n-char chunks; collect (text, deltas)."""
    out_text, out_deltas = "", []
    for i in range(0, len(text), n):
        t, ds = stream.feed(text[i:i + n])
        out_text += t
        out_deltas += ds
    t, ds = stream.finish()
    return out_text + t, out_deltas


def test_streaming_qwen_text_then_calls():
    from gllm_tpu.entrypoints.tool_parsers import StreamingToolCalls
    text = ('Checking now.\n<tool_call>\n'
            '{"name": "a", "arguments": {"x": 1}}\n</tool_call>'
            '<tool_call>\n{"name": "b", "arguments": {}}\n</tool_call>')
    s = StreamingToolCalls(QwenToolParser())
    got_text, deltas = _feed_chunks(s, text, n=5)
    assert got_text.strip() == "Checking now."
    # two calls × (header delta + arguments delta), indices 0 and 1
    assert [d["index"] for d in deltas] == [0, 0, 1, 1]
    assert deltas[0]["function"]["name"] == "a"
    assert json.loads(deltas[1]["function"]["arguments"]) == {"x": 1}
    assert deltas[2]["function"]["name"] == "b"
    assert s.saw_tool_calls


def test_streaming_text_passthrough_is_incremental():
    """Plain text streams through immediately — nothing held except a
    potential marker prefix."""
    from gllm_tpu.entrypoints.tool_parsers import StreamingToolCalls
    s = StreamingToolCalls(QwenToolParser())
    t1, d1 = s.feed("hello wor")
    assert t1 == "hello wor" and d1 == []
    t2, _ = s.feed("ld <tool")          # "<tool" could start a marker
    assert t2 == "ld "
    t3, _ = s.feed("box> done")         # not a marker after all
    assert t3 == "<toolbox> done"
    t4, _ = s.finish()
    assert t4 == ""


def test_streaming_deepseek_unterminated_section():
    """Length-capped mid-section: completed call units still come back."""
    from gllm_tpu.entrypoints.tool_parsers import StreamingToolCalls
    text = ("<｜tool▁calls▁begin｜><｜tool▁call▁begin｜>get_weather"
            "<｜tool▁sep｜>{\"city\": \"Paris\"}<｜tool▁call▁end｜>")
    s = StreamingToolCalls(DeepSeekToolParser())
    got_text, deltas = _feed_chunks(s, text, n=9)
    assert got_text == ""
    assert [d["index"] for d in deltas] == [0, 0]
    assert deltas[0]["function"]["name"] == "get_weather"
    assert json.loads(deltas[1]["function"]["arguments"]) == \
        {"city": "Paris"}


def test_streaming_malformed_markup_returns_as_content():
    from gllm_tpu.entrypoints.tool_parsers import StreamingToolCalls
    text = "a <tool_call>{not json}</tool_call>"
    s = StreamingToolCalls(QwenToolParser())
    got_text, deltas = _feed_chunks(s, text, n=4)
    assert deltas == []
    assert "not json" in got_text and got_text.startswith("a ")


def test_streaming_trailing_content_after_calls_survives():
    """Content following well-formed tool markup must still reach the
    client (regression: finish() used to drop it)."""
    from gllm_tpu.entrypoints.tool_parsers import StreamingToolCalls
    text = ('<tool_call>\n{"name": "a", "arguments": {}}\n</tool_call>\n'
            'I called the tool for you.')
    s = StreamingToolCalls(QwenToolParser())
    got_text, deltas = _feed_chunks(s, text, n=6)
    assert [d["index"] for d in deltas] == [0, 0]
    assert "I called the tool for you." in got_text


# ---- Qwen3.5 XML form (reference tool_parsers.py:346-425) -----------------

_XML_CALL = ("Let me compute.\n<tool_call>\n<function=add>\n"
             "<parameter=x>\n7\n</parameter>\n<parameter=note>\n"
             "keep as text\n</parameter>\n</function>\n</tool_call>")
_ADD_TOOLS = [{"type": "function", "function": {
    "name": "add", "parameters": {
        "properties": {"x": {"type": "integer"},
                       "note": {"type": "string"}}}}}]


def test_qwen3_xml_parse_with_schema_coercion():
    from gllm_tpu.entrypoints.tool_parsers import Qwen3XmlToolParser
    content, calls = Qwen3XmlToolParser().parse(
        _XML_CALL, schemas_from_tools(_ADD_TOOLS))
    assert content == "Let me compute."
    assert len(calls) == 1 and calls[0].name == "add"
    # int param coerced, string param stays a string (BFCL string
    # categories break if values are json.loads'd unconditionally)
    assert json.loads(calls[0].arguments) == {"x": 7,
                                              "note": "keep as text"}


def test_qwen3_xml_schemaless_values_stay_strings():
    from gllm_tpu.entrypoints.tool_parsers import Qwen3XmlToolParser
    _, calls = Qwen3XmlToolParser().parse(_XML_CALL)
    assert json.loads(calls[0].arguments) == {"x": "7",
                                              "note": "keep as text"}


def test_qwen3_xml_multiple_calls_and_missing_closers():
    """Dropped </parameter> and </tool_call> tags must not hide calls."""
    from gllm_tpu.entrypoints.tool_parsers import Qwen3XmlToolParser
    text = ("<tool_call>\n<function=a>\n<parameter=p>\nv1\n"
            "<parameter=q>\nv2\n</function>\n"          # no </parameter>s
            "<function=b>\n</function>")                 # no </tool_call>
    content, calls = Qwen3XmlToolParser().parse(text)
    assert content == ""
    assert [c.name for c in calls] == ["a", "b"]
    assert json.loads(calls[0].arguments) == {"p": "v1", "q": "v2"}
    assert json.loads(calls[1].arguments) == {}


def test_qwen3_xml_streaming_incremental():
    from gllm_tpu.entrypoints.tool_parsers import (Qwen3XmlToolParser,
                                                   StreamingToolCalls)
    s = StreamingToolCalls(Qwen3XmlToolParser(),
                           schemas_from_tools(_ADD_TOOLS))
    got_text, deltas = _feed_chunks(s, _XML_CALL, n=6)
    assert got_text.strip() == "Let me compute."
    assert [d["index"] for d in deltas] == [0, 0]
    assert deltas[0]["function"]["name"] == "add"
    assert json.loads(deltas[1]["function"]["arguments"]) == \
        {"x": 7, "note": "keep as text"}
    assert s.saw_tool_calls


def test_qwen3_xml_streaming_emits_before_tool_call_close():
    """A call unit completes at </function>; the delta must not wait for
    the trailing </tool_call> (which a length-capped stream never sends)."""
    from gllm_tpu.entrypoints.tool_parsers import (Qwen3XmlToolParser,
                                                   StreamingToolCalls)
    s = StreamingToolCalls(Qwen3XmlToolParser())
    _, d1 = s.feed("<tool_call>\n<function=go>\n</function>")
    assert [d["index"] for d in d1] == [0, 0]
    assert d1[0]["function"]["name"] == "go"
    text, d2 = s.finish()
    assert text == "" and d2 == []


def test_qwen3_xml_autodetect_and_explicit_names():
    from gllm_tpu.entrypoints.tool_parsers import Qwen3XmlToolParser
    # by architecture (the hybrid checkpoints' id often lacks "3.5")
    assert isinstance(
        get_tool_parser(None, "some/checkpoint",
                        architecture="Qwen3_5ForCausalLM"),
        Qwen3XmlToolParser)
    # qwen-family explicit name defers to the architecture (ref
    # tool_parsers.py:616-623)
    assert isinstance(
        get_tool_parser("qwen", "", architecture="Qwen3_5MoeForCausalLM"),
        Qwen3XmlToolParser)
    # hermes still forces the JSON form even on a 3.5 arch
    assert isinstance(
        get_tool_parser("hermes", "", architecture="Qwen3_5ForCausalLM"),
        QwenToolParser)
    for name in ("qwen3.5", "qwen3_5", "qwen_xml"):
        assert isinstance(get_tool_parser(name, ""), Qwen3XmlToolParser)
    # older qwen stays hermes
    assert isinstance(get_tool_parser(None, "Qwen/Qwen3-8B"),
                      QwenToolParser)


def test_qwen3_xml_prose_mentioning_markup_passes_through():
    """Text that merely mentions '<function=' without a complete call must
    not be truncated (regression: parse used to cut at the marker)."""
    from gllm_tpu.entrypoints.tool_parsers import Qwen3XmlToolParser
    text = "Use the syntax <function=name> like this, then stop."
    content, calls = Qwen3XmlToolParser().parse(text)
    assert calls == [] and content == text


def test_qwen3_xml_trailing_and_interleaved_content_survives():
    from gllm_tpu.entrypoints.tool_parsers import Qwen3XmlToolParser
    text = ("before\n<tool_call>\n<function=a>\n</function>\n</tool_call>\n"
            "middle <function=b>\n</function> after")
    content, calls = Qwen3XmlToolParser().parse(text)
    assert [c.name for c in calls] == ["a", "b"]
    for piece in ("before", "middle", "after"):
        assert piece in content, content


def test_qwen3_xml_interleaved_text_no_orphan_closer():
    """Text between <tool_call> and <function=..> must not leak an
    orphaned </tool_call> tag into content."""
    from gllm_tpu.entrypoints.tool_parsers import Qwen3XmlToolParser
    text = ("<tool_call>\nnote to self\n<function=a>\n</function>\n"
            "</tool_call>")
    content, calls = Qwen3XmlToolParser().parse(text)
    assert [c.name for c in calls] == ["a"]
    assert "</tool_call>" not in content and "<tool_call>" not in content
    assert "note to self" in content
