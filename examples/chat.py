"""Interactive offline chat REPL (reference examples/chat.py).

Usage: python examples/chat.py --model <dir> [--temperature 0.7]
"""

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True)
    ap.add_argument("--temperature", type=float, default=0.7)
    ap.add_argument("--max-tokens", type=int, default=512)
    ap.add_argument("--system", default=None)
    args = ap.parse_args()

    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams

    llm = LLM(args.model)
    if llm.tokenizer is None:
        raise SystemExit("chat REPL needs a tokenizer in the model dir")
    sp = SamplingParams(temperature=args.temperature,
                        max_tokens=args.max_tokens)
    messages = []
    if args.system:
        messages.append({"role": "system", "content": args.system})
    print("(/exit to quit, /reset to clear history)")
    while True:
        try:
            user = input("you> ").strip()
        except (EOFError, KeyboardInterrupt):
            break
        if user == "/exit":
            break
        if user == "/reset":
            messages = messages[:1] if args.system else []
            continue
        if not user:
            continue
        messages.append({"role": "user", "content": user})
        out = llm.chat(messages, sampling_params=sp)
        print(f"bot> {out.text}")
        messages.append({"role": "assistant", "content": out.text})


if __name__ == "__main__":
    main()
