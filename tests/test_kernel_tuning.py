"""Pallas block-size tuning table (VERDICT r03 missing #4).

The attention dispatch reads block sizes from
``gllm_tpu/ops/pallas/tuning.py`` (analogue of the reference's
``fused_moe_triton/configs/`` autotune tables); the table is layered:
BUILTIN defaults < committed tables.json < GLLM_TPU_TUNE_TABLE override.
"""

import json

from gllm_tpu.ops.pallas import tuning


def _reset_caches():
    tuning._table.cache_clear()
    tuning.device_tag.cache_clear()


def test_builtin_defaults():
    _reset_caches()
    assert tuning.get("ragged") == {"q_block": 128, "kv_block": 256}
    assert tuning.get("decode") == {"kv_block": 256}


def test_env_override_layering(tmp_path, monkeypatch):
    _reset_caches()
    # device-specific beats default; partial override keeps other params
    table = {"default": {"ragged": {"kv_block": 512}},
             tuning.device_tag(): {"decode": {"kv_block": 128}}}
    p = tmp_path / "tune.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv("GLLM_TPU_TUNE_TABLE", str(p))
    tuning._table.cache_clear()
    assert tuning.get("ragged") == {"q_block": 128, "kv_block": 512}
    assert tuning.get("decode") == {"kv_block": 128}
    monkeypatch.delenv("GLLM_TPU_TUNE_TABLE")
    tuning._table.cache_clear()


def test_malformed_table_ignored(tmp_path, monkeypatch):
    _reset_caches()
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    monkeypatch.setenv("GLLM_TPU_TUNE_TABLE", str(p))
    tuning._table.cache_clear()
    assert tuning.get("ragged") == {"q_block": 128, "kv_block": 256}
    monkeypatch.delenv("GLLM_TPU_TUNE_TABLE")
    tuning._table.cache_clear()


def test_device_tag_cpu():
    _reset_caches()
    # on the CPU test backend this resolves to some non-empty tag and the
    # lookup falls back to default cleanly
    assert tuning.device_tag()
    assert tuning.get("nonexistent_kernel") == {}
