"""Dependency-free observability layer (metrics + step traces).

Two pillars, both pure-host bookkeeping (no jax import, no device work,
no effect on jit cache keys):

- ``gllm_tpu.obs.metrics``: a Prometheus-style registry (Counter / Gauge /
  Histogram with fixed buckets, thread-safe, text-exposition renderer)
  served by the api_server's ``GET /metrics``.
- ``gllm_tpu.obs.steptrace``: a ring buffer of per-step records (kind,
  batch size, token counts, wall ms, ...) dumped by ``GET /steptrace``
  and summarized into bench.py's metrics snapshot. ``python -m
  gllm_tpu.obs.dump trace.jsonl`` pretty-prints a saved trace.

Every round-5 finding (unfused decode steps at 8x the fused latency, the
sampled-path sort, the tuning-table regression) had to be excavated from
ad-hoc stderr logs; this layer makes the same questions one HTTP GET or
one JSON blob.
"""

from gllm_tpu.obs import metrics, steptrace  # noqa: F401
