"""TieredPrefixManager: the probe-order owner of the prefix KV store.

The prefix hierarchy is four levels, probed strictly in order of cost::

    HBM (PrefixMemoryManager maps)          ~0, page already resident
     └─ host RAM (HostKVPool)               one queued scatter
         └─ disk (DiskPrefixStore)          one file read + scatter
             └─ peers (PrefixClient)        one bounded RPC + scatter

This class owns levels three and four and the demotion edge between two
and three. It deliberately does NOT own a new restore path to the
device: a disk or peer hit is **staged into the host pool** and returned
as a host page id, so the existing ``KVSwapManager.restore_prefix``
intent queue — and with it every device-ordering guarantee the runner's
dispatch-time drain provides (docs/kv_offload.md) — carries the page the
rest of the way. Lower tiers extend the hierarchy; they never add a
second way to touch the device.

Demotion mirrors promotion: the host pool's LRU eviction (which used to
discard) now hands the evicted page's bytes to ``_on_host_evict``, which
writes it to the disk tier — eviction becomes a demotion all the way
down, and only the disk tier's own LRU ever discards for good.

The peer-serving side (``serve``) runs on a server handler thread and
reads the host pool under its lock, then falls back to the disk tier;
payloads ship unverified (the fetching replica verifies digest + canary
against its own geometry before trusting a byte).
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import numpy as np

from gllm_tpu.kvstore import stats
from gllm_tpu.kvstore.disk import DiskPrefixStore
from gllm_tpu.kvstore.pagefmt import (pack_page, pool_geometry,
                                      verify_payload)
from gllm_tpu.kvstore.peer import PeerPrefixServer, PrefixClient

logger = logging.getLogger(__name__)


class TieredPrefixManager:
    def __init__(self, pool, page_size: int,
                 disk: Optional[DiskPrefixStore] = None,
                 client: Optional[PrefixClient] = None):
        self.pool = pool
        self.geometry = pool_geometry(pool.page_shapes, page_size)
        self.disk = disk
        self.client = client
        self.server: Optional[PeerPrefixServer] = None
        # demotion hook: host-tier LRU eviction hands the page here
        # instead of discarding it
        pool.on_evict = self._on_host_evict

    # ---- probe (engine thread; called by KVSwapManager on host miss) ------

    def probe(self, digest: bytes, tokens
              ) -> Optional[Tuple[int, str]]:
        """Probe disk then peers for ``digest``. On a hit, stage the
        page into the host pool (allocating, possibly demoting older
        host pages to disk) and return ``(host_page, tier)`` — the
        caller restores host→device through the normal intent queue.
        None = every lower tier missed; the prefix walk stops and the
        tokens recompute."""
        got, tier = None, None
        if self.disk is not None:
            got = self.disk.get(digest, tokens)
            if got is not None:
                tier = "disk"
        if got is None and self.client is not None:
            got = self.client.fetch(digest, tokens)
            if got is not None:
                tier = "peer"
        if got is None:
            return None
        leaves, parent = got
        host = self.pool.allocate(1)
        if host is None:
            return None                  # pool full of pinned pages
        page = host[0]
        with self.pool.lock:
            for store, leaf in zip(self.pool.store, leaves):
                store[page] = leaf
            self.pool.put_prefix(page, digest,
                                 tuple(int(t) for t in
                                       tokens[:self._canary_len()]),
                                 parent=parent)
        return page, tier

    def _canary_len(self) -> int:
        from gllm_tpu.kvswap.host_pool import CANARY_TOKENS
        return CANARY_TOKENS

    # ---- demotion (engine thread, inside HostKVPool eviction) -------------

    def _on_host_evict(self, digest: bytes, canary, parent,
                       leaves) -> None:
        if self.disk is not None:
            self.disk.put(digest, canary, parent, leaves)

    def flush_host_to_disk(self, drop: bool = False) -> int:
        """Demote every unpinned host-resident prefix page to the disk
        tier NOW (graceful shutdown / bench lever: the warm cache
        survives a restart). ``drop=True`` additionally forgets the host
        entries, forcing subsequent probes through the disk tier.
        Returns the number of pages demoted; blocks until the writes
        land."""
        if self.disk is None:
            return 0
        # snapshot copies under the lock; serialization + writes happen
        # outside it so peer serving never blocks on a flush
        with self.pool.lock:
            items = [(page, meta) for page, meta
                     in self.pool.page_meta.items()
                     if self.pool.hash_to_page.get(meta[0]) == page
                     and not self.pool.is_pinned(page)]
            snap = [(meta, [s[page].copy() for s in self.pool.store])
                    for page, meta in items]
            if drop:
                for page, _ in items:
                    self.pool.drop_prefix(page)
        for (digest, canary, parent), leaves in snap:
            self.disk.put(digest, canary, parent, leaves)
        self.disk.flush()
        return len(snap)

    # ---- peer serving (server handler thread) -----------------------------

    def serve(self, digest: bytes) -> Optional[bytes]:
        """Packed payload for a peer's fetch, or None. Host pool first
        (locked copy), then the disk tier's raw file bytes."""
        exported = self.pool.export_prefix(digest)
        if exported is not None:
            canary, parent, leaves = exported
            return pack_page(digest, canary, parent, leaves,
                             self.geometry)
        if self.disk is not None:
            return self.disk.get_payload(digest)
        return None

    def accept_push(self, digest: bytes, tokens, payload: bytes) -> bool:
        """Server-side sink of the peer ``push`` op (pd-pool KV
        handoff): verify the payload against LOCAL geometry + digest +
        canary, then stage it into the host pool exactly like a lower-
        tier probe hit — the next ``match_prefix`` walk hits host tier
        and restores through the normal intent queue, zero re-prefill.
        Runs on a server handler thread; staging holds the pool lock.
        False = rejected (corrupt, pool full) — the pusher's problem is
        never this replica's problem."""
        try:
            leaves, parent = verify_payload(payload, self.geometry,
                                            digest, tokens)
        except (ValueError, KeyError):
            stats.POISON.inc(tier="peer")
            return False
        # the whole stage runs under the pool RLock: accept runs on a
        # server handler thread while the engine thread allocates from
        # the same free list / LRU
        with self.pool.lock:
            if digest in self.pool.hash_to_page:
                return True              # already resident: idempotent
            host = self.pool.allocate(1)
            if host is None:
                return False             # pool full of pinned pages
            page = host[0]
            for store, leaf in zip(self.pool.store, leaves):
                store[page] = leaf
            self.pool.put_prefix(page, digest,
                                 tuple(int(t) for t in
                                       tokens[:self._canary_len()]),
                                 parent=parent)
        return True

    def contains(self, digest: bytes) -> bool:
        """Cheap membership for the peer ``has`` placement probe: index
        lookups only — no page export, no pack, no disk read (the probe
        sits on the router's request-placement path)."""
        with self.pool.lock:
            if digest in self.pool.hash_to_page:
                return True
        return self.disk is not None and self.disk.contains(digest)

    def start_server(self, host: str = "0.0.0.0",
                     port: int = 0) -> "PeerPrefixServer":
        self.server = PeerPrefixServer(self.serve, self.geometry,
                                       host=host, port=port,
                                       contains=self.contains,
                                       accept=self.accept_push)
        return self.server

    # ---- lifecycle --------------------------------------------------------

    def close(self) -> None:
        if self.server is not None:
            self.server.close()
            self.server = None
        if self.client is not None:
            self.client.close()
        if self.disk is not None:
            self.disk.close()
