"""Pipelined engine loop (--pipelined-loop) correctness.

The contract (docs/overlap_scheduling.md#pipelined-loop): with the flag
ON, greedy and seeded token streams are byte-identical to the flag-off
loop under arrival / finish / preemption churn — speculative re-forms
off promised token counts never change what commits, only when the
schedule/build/dispatch work happens; promised-vs-actual divergence
(EOS/stop the host could not predict) invalidates and rebuilds exactly
the speculated entries. With the flag OFF the engine is today's loop,
byte for byte (the existing overlap identity tests cover that arm
unmodified).
"""

import numpy as np
import pytest

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.obs.steptrace import TRACE, summarize
from gllm_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def model_cfg():
    # dummy-weight tiny Llama: deterministic (seeded init), no HF/torch
    return ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=512, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, max_position=256)


def make_llm(model_cfg, *, pipelined, num_pages=256, max_model_len=128,
             max_num_seqs=8, eos=(), **kw):
    cfg = EngineConfig(
        load_format="dummy", dtype="float32",
        max_model_len=max_model_len, max_num_seqs=max_num_seqs,
        overlap_scheduling=True, pipelined_loop=pipelined,
        scheduler=SchedulerConfig(max_prefill_tokens=32,
                                  max_decode_seqs=max_num_seqs),
        cache=CacheConfig(page_size=4, num_pages=num_pages), **kw)
    llm = LLM(config=cfg, model_cfg=model_cfg)
    if eos:
        llm.eos_token_ids = frozenset(eos)
    return llm


def check_no_leak(llm):
    assert llm.memory_manager.num_free_pages == \
        llm.memory_manager.allocator.num_total


def run(model_cfg, pipelined, prompts, sps, **kw):
    llm = make_llm(model_cfg, pipelined=pipelined, **kw)
    outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                        sampling_params=sps)
    check_no_leak(llm)
    assert not llm._in_flight
    return [(o.output_token_ids, o.finish_reason) for o in outs], llm


def staggered_workload(rng, n=6, vocab=500):
    prompts = [[int(x) for x in rng.integers(2, vocab, size=int(m))]
               for m in rng.integers(3, 14, size=n)]
    sps = [SamplingParams(temperature=0.0, max_tokens=int(m),
                          ignore_eos=True)
           for m in rng.integers(4, 24, size=n)]
    return prompts, sps


def test_pipelined_matches_sync_staggered_lengths(model_cfg):
    """Staggered max_tokens: every finish breaks the chain; the
    speculative re-form must commit exactly the sync loop's tokens
    (length deaths are host-predicted — no divergence possible)."""
    prompts, sps = staggered_workload(np.random.default_rng(3))
    base, _ = run(model_cfg, False, prompts, sps)
    pip, llm = run(model_cfg, True, prompts, sps)
    assert base == pip
    assert llm.futures.rebuilds == 0       # predicted deaths never diverge


def test_pipelined_matches_sync_with_eos(model_cfg):
    """Natural (host-detected) EOS mid-pipeline: divergence may
    invalidate speculated entries; committed streams stay identical."""
    rng = np.random.default_rng(5)
    prompts = [[int(x) for x in rng.integers(2, 60, size=int(m))]
               for m in rng.integers(3, 12, size=6)]
    sps = [SamplingParams(temperature=0.0, max_tokens=40)
           for _ in range(6)]
    # an organically common greedy token as EOS → finishes mid-stream
    probe, _ = run(model_cfg, False, prompts, sps)
    toks = [t for o, _ in probe for t in o]
    eos = max(set(toks), key=toks.count)
    base, _ = run(model_cfg, False, prompts, sps, eos=[eos])
    pip, _ = run(model_cfg, True, prompts, sps, eos=[eos])
    assert base == pip
    assert any(r == "stop" for _, r in pip)      # EOS actually fired


def test_pipelined_matches_sync_fused_slots_odf(model_cfg):
    """Pipelined loop composed with fused blocks + persistent slots +
    on-device finish — the full-profile bench stack."""
    prompts, sps = staggered_workload(np.random.default_rng(7))
    kw = dict(multi_step_decode=4, decode_slot_batching=True,
              ondevice_finish=True)
    base, _ = run(model_cfg, False, prompts, sps, **kw)
    pip, _ = run(model_cfg, True, prompts, sps, **kw)
    assert base == pip


def test_pipelined_matches_sync_seeded(model_cfg):
    """Seeded sampling: draws are a pure function of (seed, out_step),
    which the promised frontier advances exactly — byte-identical even
    across speculative re-forms and rebuilds."""
    rng = np.random.default_rng(9)
    prompts = [[int(x) for x in rng.integers(2, 500, size=int(m))]
               for m in rng.integers(3, 12, size=4)]
    sps = [SamplingParams(temperature=0.8, seed=100 + i,
                          max_tokens=int(m), ignore_eos=True)
           for i, m in enumerate(rng.integers(6, 20, size=4))]
    base, _ = run(model_cfg, False, prompts, sps)
    pip, _ = run(model_cfg, True, prompts, sps)
    assert base == pip


def churn_run(model_cfg, pipelined, *, num_pages=256, seeded=False,
              msd=1, slots=False):
    """Drive step() by hand with staggered arrivals (and optional page
    pressure) — the chain-yield, admission, and preemption paths all
    fire while speculative entries are in flight."""
    llm = make_llm(model_cfg, pipelined=pipelined, num_pages=num_pages,
                   max_model_len=64, eos=[7], multi_step_decode=msd,
                   decode_slot_batching=slots, ondevice_finish=slots)
    rng = np.random.default_rng(11)
    seqs, nseq, it = [], 0, 0
    arrivals = {0: 3, 2: 2, 5: 2, 9: 1}
    while nseq < 8 or llm.has_unfinished:
        for _ in range(arrivals.get(it, 0)):
            ids = [int(x) for x in
                   rng.integers(2, 250, size=int(rng.integers(3, 20)))]
            sp = (SamplingParams(temperature=0.8, seed=100 + nseq,
                                 max_tokens=int(rng.integers(4, 24)))
                  if seeded else
                  SamplingParams(temperature=0.0,
                                 max_tokens=int(rng.integers(4, 24))))
            s = llm._allocate_seq(ids, sp)
            seqs.append(s)
            llm.add_seq(s)
            nseq += 1
        llm.step()
        it += 1
        assert it < 2000, "engine stopped making progress"
    check_no_leak(llm)
    return [(s.token_ids[:], s.finish_reason) for s in seqs], llm


@pytest.mark.parametrize("kw", [
    {},                                    # arrivals only
    {"num_pages": 24},                     # + preemption pressure
    {"seeded": True},
    {"msd": 4, "slots": True},             # fused + persistent slots
    {"num_pages": 24, "msd": 4},           # fused + preemption
])
def test_pipelined_matches_sync_under_churn(model_cfg, kw):
    base, _ = churn_run(model_cfg, False, **kw)
    pip, llm = churn_run(model_cfg, True, **kw)
    assert base == pip
    if kw.get("num_pages"):
        # the pressure arm must actually exercise preemption
        assert llm.scheduler.num_preemptions > 0


def test_reconciliation_rebuilds_exactly_the_speculated_step(model_cfg):
    """Deterministic promised-vs-actual divergence: seq A finishes by a
    stop token at output index 1, seq B at index 2 — A's finish breaks
    the chain, the engine speculates [B] off promised counts, and B's
    finish (committing from an entry already in flight) invalidates
    exactly that speculated entry. Tokens stay identical to sync and
    the invalidated work is the only discarded dispatch."""
    pa, pb = [5, 17, 93], [9, 41, 3, 77]
    probe, _ = run(model_cfg, False, [pa, pb],
                   [SamplingParams(temperature=0.0, max_tokens=8,
                                   ignore_eos=True)] * 2)
    ca, cb = probe[0][0], probe[1][0]
    assume = (ca[0] != ca[1] and cb[2] not in (cb[0], cb[1]))
    assert assume, "probe continuations degenerate; pick other prompts"
    sps = [SamplingParams(temperature=0.0, max_tokens=20,
                          stop_token_ids=[ca[1]]),
           SamplingParams(temperature=0.0, max_tokens=20,
                          stop_token_ids=[cb[2]])]
    base, _ = run(model_cfg, False, [pa, pb], sps)

    llm = make_llm(model_cfg, pipelined=True)
    discarded = []
    orig_discard = llm.scheduler.discard_batch
    llm.scheduler.discard_batch = lambda b: (discarded.append(b),
                                             orig_discard(b))[1]
    mark = TRACE.mark()
    outs = llm.generate(prompt_token_ids=[list(pa), list(pb)],
                        sampling_params=sps)
    check_no_leak(llm)
    pip = [(o.output_token_ids, o.finish_reason) for o in outs]
    assert pip == base
    assert llm.futures.divergences == 1
    assert llm.futures.rebuilds == 1
    # exactly the speculated entry was discarded: one batch, carrying a
    # promise splice map (src_rows), holding only B's row
    assert len(discarded) == 1
    b = discarded[0]
    b = b[0] if isinstance(b, list) else b
    assert b.src_rows is not None
    assert [it.seq.seq_id for it in b.items] == [outs[1].seq_id]
    stalls = summarize(TRACE.events(since=mark))["loop_stalls_by_reason"]
    assert stalls.get("rebuild") == 1


def test_invalidated_entry_never_becomes_a_chain_tip(model_cfg):
    """Regression: an invalidated speculative entry still holds
    RUNNING sequences (only ONE of its promises died); chaining or
    re-forming off it would build on a discarded frontier and commit
    streams that skip a token. With a third long-running sequence
    riding in the speculated batch, the rebuild must re-derive its
    tokens from committed state — byte-identical to sync."""
    pa, pb, pc = [5, 17, 93], [9, 41, 3, 77], [22, 8, 51]
    probe, _ = run(model_cfg, False, [pa, pb, pc],
                   [SamplingParams(temperature=0.0, max_tokens=8,
                                   ignore_eos=True)] * 3)
    ca, cb = probe[0][0], probe[1][0]
    assert ca[0] != ca[1] and cb[2] not in (cb[0], cb[1])
    sps = [SamplingParams(temperature=0.0, max_tokens=20,
                          stop_token_ids=[ca[1]]),
           SamplingParams(temperature=0.0, max_tokens=20,
                          stop_token_ids=[cb[2]]),
           SamplingParams(temperature=0.0, max_tokens=16,
                          ignore_eos=True)]
    base, _ = run(model_cfg, False, [pa, pb, pc], sps)
    pip, llm = run(model_cfg, True, [pa, pb, pc], sps)
    assert pip == base
    assert llm.futures.rebuilds >= 1      # the divergence actually fired


def test_sync_loop_records_no_stall_events(model_cfg):
    """loop_stall is a pipelined-only vocabulary: the flag-off loop must
    not emit it (flag-off == today's engine, observability included)."""
    prompts, sps = staggered_workload(np.random.default_rng(13))
    mark = TRACE.mark()
    run(model_cfg, False, prompts, sps)
    assert not TRACE.events(since=mark, kinds=["loop_stall"])


def test_reform_batches_splice_from_device(model_cfg):
    """Structural: the pipelined arm actually schedules speculative
    re-forms (src_rows batches) across finish-driven chain breaks
    instead of draining — and every one of them commits or is
    reconciled, never silently dropped."""
    prompts, sps = staggered_workload(np.random.default_rng(17))
    llm = make_llm(model_cfg, pipelined=True)
    reforms = []
    orig = llm.scheduler.schedule_reform
    def spy(prev, allow_prefill=False):
        b = orig(prev, allow_prefill=allow_prefill)
        if b is not None:
            reforms.append(b)
        return b
    llm.scheduler.schedule_reform = spy
    llm.generate(prompt_token_ids=[list(p) for p in prompts],
                 sampling_params=sps)
    check_no_leak(llm)
    assert reforms, "staggered finishes never triggered a re-form"
    assert all(b.src_rows is not None for b in reforms)


@pytest.mark.parametrize("msd", [1, 4])
def test_bubble_frac_drops_at_decode_saturation(model_cfg, msd):
    """Acceptance (ISSUE 11): on a decode-saturated CPU workload with
    staggered finishes, the pipelined loop measurably lowers
    bubble_frac and raises overlap_efficiency vs the flag-off loop in
    the same process — the re-form keeps the device fed across breaks
    the sync loop drains on."""
    rng = np.random.default_rng(0)
    prompts = [[int(x) for x in rng.integers(1, 500, size=int(m))]
               for m in rng.integers(8, 32, size=12)]
    mts = rng.integers(16, 64, size=12)

    def arm(pipelined):
        sps = [SamplingParams(temperature=0.0, max_tokens=int(m),
                              ignore_eos=True) for m in mts]
        llm = make_llm(model_cfg, pipelined=pipelined,
                       max_model_len=256, num_pages=1024,
                       max_num_seqs=16, multi_step_decode=msd)
        warm = [SamplingParams(temperature=0.0, max_tokens=int(m),
                               ignore_eos=True) for m in mts]
        llm.generate(prompt_token_ids=[list(p) for p in prompts],
                     sampling_params=warm)          # compile every bucket
        mark = TRACE.mark()
        outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                            sampling_params=sps)
        s = summarize(TRACE.events(since=mark))
        return s, [o.output_token_ids for o in outs]

    s_sync, toks_sync = arm(False)
    s_pip, toks_pip = arm(True)
    assert toks_sync == toks_pip
    assert s_sync["bubble_frac"] is not None \
        and s_pip["bubble_frac"] is not None
    # "measurably": strictly lower, by more than timing jitter
    assert s_pip["bubble_frac"] < s_sync["bubble_frac"] - 0.02, \
        (s_pip["bubble_frac"], s_sync["bubble_frac"])
    assert s_pip["overlap_efficiency"] >= s_sync["overlap_efficiency"]
    assert s_pip["mean_inflight_depth"] > s_sync["mean_inflight_depth"]


def test_reconcile_cascade_stops_at_a_valid_sync_root():
    """FutureMap unit: a chained entry descending from a LATER valid
    sync-rooted batch must survive an earlier entry's invalidation —
    the cascade models chain parentage, not deque order."""
    from gllm_tpu.engine.pipeline import FutureMap, InFlight

    def e(**kw):
        return InFlight(None, object(), 0.0, None, **kw)

    fm = FutureMap()
    reform = e(chained=True, promises=frozenset({7}))
    prefill = e()                               # interleaved, no root
    root = e(roots=True)                        # fresh sync decode root
    chain_off_root = e(chained=True)
    entries = [reform, prefill, root, chain_off_root]
    assert fm.reconcile(entries, frozenset({7})) == 1
    assert reform.invalid
    assert not prefill.invalid and not root.invalid
    assert not chain_off_root.invalid           # parent is the valid root
    # without a root in between, the cascade takes the chained entry
    fm2 = FutureMap()
    r2, c2 = (e(chained=True, promises=frozenset({7})),
              e(chained=True))
    assert fm2.reconcile([r2, e(), c2], frozenset({7})) == 2
    assert r2.invalid and c2.invalid


def test_reform_budget_skip_beats_penalty_refusal(model_cfg):
    """Scheduler unit: a penalized decode-ready candidate BEYOND the
    decode budget must not refuse the whole re-form (the sync path
    could not seat it either); under budget it still refuses so the
    sync pass can seat it."""
    from gllm_tpu.memory_manager import make_memory_manager
    from gllm_tpu.scheduler import ScheduledBatch, ScheduledSeq, Scheduler
    from gllm_tpu.sequence import Sequence, SequenceStatus

    def setup(budget):
        cfg = EngineConfig(
            load_format="dummy", max_model_len=128, max_num_seqs=8,
            overlap_scheduling=True, pipelined_loop=True,
            scheduler=SchedulerConfig(max_prefill_tokens=32,
                                      max_decode_seqs=budget),
            cache=CacheConfig(page_size=4, num_pages=64))
        mm = make_memory_manager(64, 4, False)
        sched = Scheduler(cfg, mm)
        # one in-flight decode row (the chain tip's item)
        a = Sequence(0, [1] * 6, SamplingParams(temperature=0.0,
                                                max_tokens=20,
                                                ignore_eos=True))
        a.status = SequenceStatus.RUNNING
        a.num_computed_tokens = 5
        mm.allocate_seq_pages(a, 1)
        a.num_in_flight = 1
        sched.running.append(a)
        # a decode-ready PENALIZED candidate (not in flight)
        b = Sequence(1, [1] * 5, SamplingParams(temperature=0.0,
                                                max_tokens=20,
                                                repetition_penalty=1.3,
                                                ignore_eos=True))
        b.status = SequenceStatus.RUNNING
        b.num_computed_tokens = 4
        mm.allocate_seq_pages(b, 1)
        sched.running.append(b)
        prev = ScheduledBatch([ScheduledSeq(a, 1, 5)])
        return sched, prev

    sched, prev = setup(budget=1)      # batch already at budget
    batch = sched.schedule_reform(prev)
    assert batch is not None, sched.reform_fail_reason
    assert [it.seq.seq_id for it in batch.items] == [0]
    sched2, prev2 = setup(budget=2)    # room for the penalized seq
    assert sched2.schedule_reform(prev2) is None
    assert sched2.reform_fail_reason == "shape"


def test_pipelined_flag_lifts_overlap(model_cfg):
    cfg = EngineConfig(load_format="dummy", pipelined_loop=True)
    cfg.validate()
    assert cfg.overlap_scheduling
    cfg2 = EngineConfig(load_format="dummy", pipelined_loop=True,
                        enforce_eager=True)
    cfg2.validate()
    assert not cfg2.pipelined_loop and not cfg2.overlap_scheduling


def test_quarantine_clears_speculative_entries(model_cfg):
    """A step exception with speculative entries in flight: quarantine
    must drop them (pages freed, no dangling promises) and the engine
    must idle clean — the PR-7 fault-isolation contract extends to the
    pipelined loop."""
    from gllm_tpu import faults
    llm = make_llm(model_cfg, pipelined=True)
    prompts, sps = staggered_workload(np.random.default_rng(23), n=4)
    for ids, sp in zip(prompts, sps):
        llm.add_seq(llm._allocate_seq(list(ids), sp))
    # let the pipeline fill + run a few steps, then poison one step
    for _ in range(4):
        llm.step()
    faults.FAULTS.arm("step_exception:0:1")
    try:
        with pytest.raises(faults.InjectedFault):
            for _ in range(50):
                llm.step()
    finally:
        faults.FAULTS.reset()
    dropped = llm.quarantine_step_failure()
    assert dropped
    assert not llm._in_flight and llm._chain_tip is None
    check_no_leak(llm)
    assert not llm.has_unfinished
