"""Long-context retrieval eval (RULER-style needle-in-a-haystack).

Counterpart of the reference's evaluate_ruler.py long-context eval — but
fully offline: the haystack/needle data is synthesized locally (this
environment has zero egress), so it doubles as an e2e long-context
correctness check of chunked prefill + paged KV.

Drives the OpenAI endpoint of a running server OR an in-process LLM
(--model), reports exact-match retrieval accuracy per context length.
"""

import argparse
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def build_case(rng, tokenizer, context_tokens):
    key = rng.randrange(10000, 99999)
    val = rng.randrange(10000, 99999)
    needle = f" The secret code for {key} is {val}. "
    filler_unit = ("The sky is blue and the grass grows slowly in spring. ")
    n_units = max(1, context_tokens // max(
        1, len(tokenizer.encode(filler_unit))))
    pos = rng.randrange(max(1, n_units))
    text = (filler_unit * pos) + needle + (filler_unit * (n_units - pos))
    question = (f"\nQuestion: What is the secret code for {key}? "
                f"Answer with the number only.\nAnswer:")
    return text + question, str(val)


def run_inprocess(args, cases):
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams
    llm = LLM(args.model, max_model_len=args.max_model_len)
    prompts = [llm.encode(p)[-(args.max_model_len - 32):]
               for p, _ in cases]
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=16))
    return [o.text for o in outs]


def run_server(args, cases):
    from eval_client import map_concurrent, post_json

    def ask(case):
        d = post_json(args.host, args.port, "/v1/completions",
                      {"prompt": case[0], "max_tokens": 16,
                       "temperature": 0.0})
        return d["choices"][0]["text"]

    return map_concurrent(ask, cases, concurrency=args.concurrency,
                          label="ruler")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None, help="in-process mode")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None, help="server mode")
    ap.add_argument("--context-lens", default="1024,2048,4096")
    ap.add_argument("--num-cases", type=int, default=10)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-model-len", type=int, default=8192)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    rng = random.Random(args.seed)
    if args.model:
        from transformers import AutoTokenizer
        tokenizer = AutoTokenizer.from_pretrained(args.model,
                                                  local_files_only=True)
    else:
        class Approx:  # server mode: approximate token counting
            def encode(self, s):
                return s.split()
        tokenizer = Approx()

    report = {}
    for ctx in [int(c) for c in args.context_lens.split(",")]:
        cases = [build_case(rng, tokenizer, ctx)
                 for _ in range(args.num_cases)]
        if args.model:
            answers = run_inprocess(args, cases)
        elif args.port:
            answers = run_server(args, cases)
        else:
            raise SystemExit("pass --model (in-process) or --port (server)")
        correct = sum(1 for (_, want), got in zip(cases, answers)
                      if want in got)
        report[ctx] = correct / len(cases)
        print(f"context {ctx}: {correct}/{len(cases)} "
              f"({report[ctx]:.0%})", file=sys.stderr)
    print(json.dumps({"metric": "ruler_niah_accuracy", "by_context": report}))


if __name__ == "__main__":
    main()
