"""Per-device block-size tuning for the Pallas kernels.

TPU analogue of the reference's per-device Triton autotune tables
(/root/reference/gllm/layers/moe/fused_moe_triton/configs/, ~150 JSON
files keyed by device name): the attention kernels' block sizes are looked
up by (device kind, kernel) instead of being hard-coded at the call site
(VERDICT r03 missing #4).

Resolution order, most specific wins:
1. a JSON table named by ``GLLM_TPU_TUNE_TABLE`` (operator override),
2. the committed ``tables.json`` next to this module (written by
   ``benchmarks/kernel_tune.py --write`` after an on-chip sweep),
3. the BUILTIN defaults (the empirically safe 128/256 from rounds 1-3).

Table shape: {device_tag: {kernel: {param: value}}}; ``default`` applies
to every device. device_tag is ``jax.devices()[0].device_kind`` lowercased
with spaces collapsed (e.g. ``tpu_v5_lite``).
"""

from __future__ import annotations

import functools
import json
import logging
import os

logger = logging.getLogger(__name__)

BUILTIN = {
    "default": {
        "ragged": {"q_block": 128, "kv_block": 256},
        "decode": {"kv_block": 256},
        # the unified mixed-batch kernel (--unified-step): one geometry
        # for every paged step; ``group`` is the decode-class DMA
        # interleave depth (the analogue of the decode kernel's group)
        "unified": {"q_block": 128, "kv_block": 256, "group": 4},
        # f32-score-tile VMEM budget for effective_q_block(); per-device
        # entries are HAND-maintained from kernel_tune.py --vmem-probe's
        # informational output (never auto-written — see the probe's
        # comment on why the score tile is a poor proxy)
        "vmem": {"tile_limit_mb": 6.0},
    },
}

_TABLES_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tables.json")


def _merge(dst: dict, src: dict) -> None:
    for dev, kernels in src.items():
        d = dst.setdefault(dev, {})
        for kern, params in kernels.items():
            d.setdefault(kern, {}).update(params)


@functools.lru_cache()
def _table() -> dict:
    t = {dev: {k: dict(p) for k, p in kernels.items()}
         for dev, kernels in BUILTIN.items()}
    for path in (_TABLES_PATH, os.environ.get("GLLM_TPU_TUNE_TABLE")):
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    _merge(t, json.load(f))
            except (OSError, ValueError) as e:
                logger.warning("ignoring tuning table %s: %s", path, e)
    return t


@functools.lru_cache()
def device_tag() -> str:
    import jax
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        return "default"
    return "_".join(kind.lower().split())


def get(kernel: str) -> dict:
    """Tuned params for ``kernel`` on the current device (device-specific
    entries layered over ``default``). ``comment`` entries are provenance
    annotations (which sweep artifact produced the value) — stripped here
    so they never reach kernel kwargs."""
    t = _table()
    out = dict(t.get("default", {}).get(kernel, {}))
    out.update(t.get(device_tag(), {}).get(kernel, {}))
    out.pop("comment", None)
    return out
