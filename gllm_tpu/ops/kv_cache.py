"""Paged KV cache device arrays + write path.

TPU-native equivalent of the reference's reshape_and_cache_flash Triton kernel
(/root/reference/gllm/layers/ops/cache_kernels.py): new K/V rows are scattered
into the paged cache at per-token flat slot indices. Under jit with buffer
donation the scatter lowers to an in-place dynamic-update — no cache copy
(SURVEY.md §7 hard part 4).

Layout: [num_pages, page_size, num_kv_heads, head_dim] per layer per K/V.
Flat slot = page_id * page_size + offset; slot 0..page_size-1 live in the
dummy page (page 0) and absorb writes from padded tokens.
"""

from __future__ import annotations

import jax.numpy as jnp


def write_kv(k_cache: jnp.ndarray, v_cache: jnp.ndarray,
             k: jnp.ndarray, v: jnp.ndarray,
             slot_mapping: jnp.ndarray):
    """Scatter new K/V rows into the paged cache.

    k_cache/v_cache: [num_pages, page_size, Hkv, D]
    k/v:             [T, Hkv, D] (this step's projected keys/values, post-rope)
    slot_mapping:    [T] int32 flat slots (padding → dummy-page slots)
    """
    num_pages, page_size, hkv, d = k_cache.shape
    # Packed lane layout (runner kv_pack>1: cache is [P, ps, Hkv/pack,
    # D*pack] so Mosaic's 128-lane tiling holds for head_dim<128): the new
    # rows fold into the cache's trailing shape — row-major contiguity
    # makes the reshape exact.
    T = k.shape[0]
    flat_k = k_cache.reshape(num_pages * page_size, hkv, d)
    flat_v = v_cache.reshape(num_pages * page_size, hkv, d)
    flat_k = flat_k.at[slot_mapping].set(
        k.reshape(T, hkv, d).astype(flat_k.dtype))
    flat_v = flat_v.at[slot_mapping].set(
        v.reshape(T, hkv, d).astype(flat_v.dtype))
    return (flat_k.reshape(k_cache.shape), flat_v.reshape(v_cache.shape))
