"""Per-request sampling parameters.

Mirrors the parameter surface accepted by the reference engine
(/root/reference/gllm/llm_engine.py:610-645 and entrypoints/protocol.py):
temperature / top_p / top_k / repetition_penalty / max_tokens / ignore_eos /
stop token ids / logprobs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class SamplingParams:
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = -1                      # -1 = disabled
    min_p: float = 0.0                   # 0 = disabled (prob floor vs max)
    # OpenAI logit_bias: token id -> additive bias in [-100, 100]
    logit_bias: Optional[Dict[int, float]] = None
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0        # OpenAI additive penalties
    frequency_penalty: float = 0.0
    max_tokens: int = 16
    min_tokens: int = 0
    ignore_eos: bool = False
    stop_token_ids: List[int] = dataclasses.field(default_factory=list)
    stop: List[str] = dataclasses.field(default_factory=list)  # stop strings
    logprobs: Optional[int] = None       # top-N logprobs per output token
    prompt_logprobs: Optional[int] = None
    seed: Optional[int] = None
    # Wall-clock budget in seconds from submit: the serving engine aborts
    # the request with finish reason "deadline" once it expires, whether
    # it is still waiting for admission or mid-generation. None defers to
    # the engine-wide TTL (config.request_deadline_s; docs/robustness.md).
    deadline_s: Optional[float] = None

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0

    def validate(self) -> None:
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if not 0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if self.top_k == 0 or self.top_k < -1:
            raise ValueError("top_k must be -1 (disabled) or >= 1")
        if not 0.0 <= self.min_p <= 1.0:
            raise ValueError("min_p must be in [0, 1]")
        if self.logit_bias is not None:
            if len(self.logit_bias) > 300:
                # OpenAI caps logit_bias entries; the cap also bounds the
                # device bias-bucket width (a client must not control jit
                # signature growth)
                raise ValueError("logit_bias supports at most 300 entries")
            for t, b in self.logit_bias.items():
                if not isinstance(t, int) or t < 0:
                    raise ValueError("logit_bias keys must be token ids")
                if not -100.0 <= b <= 100.0:
                    raise ValueError("logit_bias values must be in "
                                     "[-100, 100]")
        if self.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if self.repetition_penalty <= 0:
            raise ValueError("repetition_penalty must be > 0")
        if not -2.0 <= self.presence_penalty <= 2.0:
            raise ValueError("presence_penalty must be in [-2, 2]")
        if not -2.0 <= self.frequency_penalty <= 2.0:
            raise ValueError("frequency_penalty must be in [-2, 2]")
        if self.logprobs is not None and not 0 <= self.logprobs <= 20:
            raise ValueError("logprobs must be in [0, 20]")
        if self.prompt_logprobs is not None \
                and not 0 <= self.prompt_logprobs <= 20:
            raise ValueError("prompt_logprobs must be in [0, 20]")
        if any(not s for s in self.stop):
            raise ValueError("stop strings must be non-empty")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        if self.seed is not None:
            if self.seed < 0:
                raise ValueError("seed must be >= 0")
            # 64-bit client seeds (vLLM-style) are folded into the 31-bit
            # device key space up front so the request is deterministic and
            # the int32 batch arrays can't overflow mid-step.
            self.seed &= 0x7FFFFFFF
