"""Embedding transfer: a host-RAM slot pool with a TCP write endpoint.

The NIXL/UCX stand-in (/root/reference/gllm/transfer/nixl_transfer.py):
same register/write/notify contract, different landing zone. The reference
RDMA-writes GPU→GPU because its model consumes embeddings from device
memory; our batch builder splices visual rows host-side and ships them
with the per-step fused H2D transfer (gllm_tpu/runner/prepare.py), so the
right destination is pinned host memory — a TCP stream into a numpy pool.
On multi-NIC hosts this rides DCN exactly like the reference's UCX path.

LM side: ``SlotPool`` — ``[num_slots, max_tokens, feat_dim]`` float32 pool
+ a server accepting WRITE frames. Encoder side: ``TransferClient`` —
connect once per LM, stream (header, raw bytes) per item.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from gllm_tpu.disagg.wire import MsgServer, connect, recv_raw, send_msg


class SlotPool:
    """Pre-registered receive slots + free-list (reference DisaggReceiver
    slot pool, lm_manager.py:156-254)."""

    def __init__(self, num_slots: int, max_tokens: int, feat_dim: int,
                 host: str = "0.0.0.0", port: int = 0):
        self.num_slots = num_slots
        self.max_tokens = max_tokens
        self.feat_dim = feat_dim
        self.pool = np.zeros((num_slots, max_tokens, feat_dim), np.float32)
        self._free: List[int] = list(range(num_slots))
        self._lock = threading.Lock()
        # (seq_id, item_idx) → (slot_id, num_tokens) writes that landed
        self._landed: Dict[Tuple[int, int], Tuple[int, int]] = {}
        # (seq_id, item_idx) → slot_id reservations; writes that don't
        # match are dropped (guards a freed-and-reused slot against a late
        # write from a redispatch-superseded encoder)
        self._expected: Dict[Tuple[int, int], int] = {}
        self._server = MsgServer(host, port, self._handle)
        self.port = self._server.port
        self._server.start()

    @property
    def num_free(self) -> int:
        with self._lock:
            return len(self._free)

    def alloc(self) -> Optional[int]:
        with self._lock:
            return self._free.pop() if self._free else None

    def free(self, slot_id: int) -> None:
        with self._lock:
            assert slot_id not in self._free, f"double free of {slot_id}"
            self._free.append(slot_id)
            for key, sid in list(self._expected.items()):
                if sid == slot_id:
                    del self._expected[key]

    def expect(self, seq_id: int, item_idx: int, slot_id: int) -> None:
        with self._lock:
            self._expected[(seq_id, item_idx)] = slot_id

    def _handle(self, msg, sock) -> None:
        kind = msg[0]
        if kind == "write":
            # ("write", seq_id, item_idx, slot_id, num_tokens) + raw f32
            _, seq_id, item_idx, slot_id, n = msg
            raw = recv_raw(sock)
            if raw is None:
                return
            # check + copy + record under one lock: a write racing a
            # free/re-alloc of the same slot (redispatch-superseded
            # encoder) must not land after the reservation moved on
            with self._lock:
                ok = self._expected.get((seq_id, item_idx)) == slot_id
                if ok:
                    arr = np.frombuffer(raw, np.float32).reshape(
                        n, self.feat_dim)
                    self.pool[slot_id, :n] = arr
                    self._landed[(seq_id, item_idx)] = (slot_id, n)
            send_msg(sock, ("ok",) if ok else ("stale",))
        else:
            send_msg(sock, ("error", f"unknown request {kind!r}"))

    def drain_landed(self) -> Dict[Tuple[int, int], Tuple[int, int]]:
        """Landed writes since the last drain (the notification channel —
        the write ack IS the notif, so 'notified' implies bytes visible)."""
        with self._lock:
            out, self._landed = self._landed, {}
        return out

    def clone(self, slot_id: int, num_tokens: int) -> np.ndarray:
        return self.pool[slot_id, :num_tokens].copy()

    def close(self) -> None:
        self._server.stop()


class TransferClient:
    """Encoder-side writer: one persistent connection per LM endpoint."""

    def __init__(self, addr: str):
        host, _, port = addr.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._sock = None
        self._lock = threading.Lock()

    def write(self, seq_id: int, item_idx: int, slot_id: int,
              embedding: np.ndarray) -> None:
        """Blocking write + ack; raises on connection failure (the caller
        retries / redispatches)."""
        emb = np.ascontiguousarray(embedding, np.float32)
        with self._lock:
            if self._sock is None:
                self._sock = connect(self._addr)
            from gllm_tpu.disagg.wire import recv_msg, send_msg as _send
            try:
                _send(self._sock,
                      ("write", seq_id, item_idx, slot_id, emb.shape[0]),
                      raw=emb.tobytes())
                out = recv_msg(self._sock)
            except (ConnectionError, OSError):
                self._sock.close()
                self._sock = None
                raise
            # "stale" = the reservation moved on (redispatch superseded
            # this write); nothing more for the encoder to do.
            if not out or out[0] not in ("ok", "stale"):
                raise ConnectionError(f"transfer write failed: {out!r}")

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
