"""OpenAI-compatible HTTP server (stdlib, dependency-free).

Serves the same route surface as the reference FastAPI server
(/root/reference/gllm/entrypoints/api_server.py:41-207):
``/v1/chat/completions``, ``/v1/completions``, ``/v1/models``, ``/health``,
``/version``, ``/server_info``, ``/start_profile``, ``/stop_profile`` —
with SSE streaming, client-disconnect abort, and the reference's CLI flag
surface (:267-508) mapped onto EngineConfig.

Implementation note: this image ships neither fastapi nor uvicorn, so the
server is a ThreadingHTTPServer — one OS thread per in-flight request,
blocking on the ServingEngine's per-sequence queues. The engine itself is
single-threaded continuous batching; HTTP concurrency is intake concurrency.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import gllm_tpu
from gllm_tpu import faults
from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                             SchedulerConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.engine.serving_engine import RequestRejected, ServingEngine
from gllm_tpu.entrypoints import protocol as proto

logger = logging.getLogger(__name__)


class ServerState:
    def __init__(self, llm: LLM, served_model: str,
                 tool_parser: Optional[str] = None, engine=None,
                 pin_dp: Optional[int] = None,
                 replica_id: Optional[str] = None):
        from gllm_tpu.entrypoints.tool_parsers import get_tool_parser
        self._llm = llm
        self.engine = engine if engine is not None else ServingEngine(llm)
        self.served_model = served_model
        # per-DP-replica endpoint: every request this state admits is
        # pinned to replica ``pin_dp`` (reference --endpoint-per-dp)
        self.pin_dp = pin_dp
        self.start_time = time.time()
        # fleet identity (docs/robustness.md#fleet-topology--failover):
        # replica_id is stable for the life of THIS process; together
        # with start_time + the supervised-recovery engine generation it
        # lets a front router detect a silent restart (same address, new
        # process) explicitly instead of inferring it from lost streams
        self.replica_id = (replica_id
                           or os.environ.get("GLLM_REPLICA_ID")
                           or uuid.uuid4().hex[:12])
        # jax.profiler state: _profile_mu makes every check+transition
        # atomic across the legacy /start_profile//stop_profile pair
        # and the POST /profile one-shot; _profiling_oneshot marks a
        # capture /stop_profile must not truncate; _profile_lock
        # serializes whole one-shot captures.
        self._profiling = False
        self._profiling_oneshot = False
        self._profile_mu = threading.Lock()
        self._profile_lock = threading.Lock()   # POST /profile one-shot
        self.tool_parser = get_tool_parser(
            tool_parser, llm.config.model or served_model,
            architecture=getattr(llm.model_cfg, "architecture", "") or "")

    @property
    def llm(self):
        """The engine's CURRENT LLM: a supervised in-process rebuild
        (docs/robustness.md#recovery-lifecycle) swaps ServingEngine.llm,
        and every HTTP route must follow the swap instead of serving a
        torn-down engine's state."""
        return getattr(self.engine, "llm", self._llm)

    # ---- request handling -------------------------------------------------

    def encode_chat(self, req: proto.ChatCompletionRequest):
        """Returns (token_ids, mm_input_or_None)."""
        kwargs = dict(req.chat_template_kwargs)
        if req.tools:
            kwargs["tools"] = req.tools
        if self.llm.model_cfg.use_mm:
            messages = _normalize_mm_messages(req.messages)
            try:
                if self.llm.disagg_coordinator is not None:
                    # disagg LM node: text-only skeleton; pixels never
                    # opened here — items ship raw to the encoder fleet
                    ids, items = self.llm.encode_skeleton(messages,
                                                          **kwargs)
                    return ids, ({"disagg_items": items} if items
                                 else None)
                return self.llm.process_mm_messages(messages, **kwargs)
            except proto.ProtocolError:
                raise
            except Exception as e:
                raise proto.ProtocolError(f"multimodal encode failed: {e}")
        # Text-only model: media parts must be rejected, not silently
        # dropped — the caller would believe the model saw the image.
        for m in req.messages:
            c = m.get("content")
            if isinstance(c, list) and any(
                    isinstance(p, dict)
                    and p.get("type") in ("image_url", "image", "video",
                                          "video_url")
                    for p in c):
                raise proto.ProtocolError(
                    "this model is not multimodal; image/video content "
                    "parts are not supported")
        tok = self.llm.tokenizer
        if tok is None:
            raise proto.ProtocolError("server has no tokenizer loaded")
        # render_chat_ids prefers the checkpoint's bundled DSv3.2 message
        # encoder (model-native DSML markup) over the generic template
        return self.llm.render_chat_ids(req.messages, **kwargs), None

    def encode_completion(self, req: proto.CompletionRequest):
        if isinstance(req.prompt, list):
            return list(req.prompt)
        if self.llm.tokenizer is None:
            raise proto.ProtocolError(
                "server has no tokenizer; send token-array prompts")
        return self.llm.tokenizer.encode(req.prompt)


def _split_disagg(mm_input):
    """(mm_input, disagg_items): disagg skeleton requests carry raw items
    under "disagg_items" instead of processor outputs."""
    if mm_input and "disagg_items" in mm_input:
        return None, mm_input["disagg_items"]
    return mm_input, None


def _normalize_mm_messages(messages):
    """OpenAI image content → HF-processor image entries.

    ``image_url`` parts (data: URLs decoded to PIL — the serving host is
    zero-egress, remote URLs are left for the processor to resolve) become
    ``{"type": "image", "image": ...}`` like the reference's
    extract_modify_mm (model_runner.py:663-690)."""
    import base64
    import copy
    import io

    out = copy.deepcopy(messages)
    for message in out:
        contents = message.get("content")
        if not isinstance(contents, list):
            continue
        for content in contents:
            if content.get("type") not in ("image_url", "video_url"):
                continue
            kind = content["type"][:-4]                  # image | video
            data = content.pop(content["type"])
            if isinstance(data, dict):
                data = data.get("url")
            content["type"] = kind
            if isinstance(data, str) and data.startswith("data:"):
                header, _, b64 = data.partition(",")
                raw = base64.b64decode(b64)
                if kind == "image":
                    from PIL import Image
                    data = Image.open(io.BytesIO(raw))
                    data.load()
                else:
                    data = raw
            content[kind] = data
    return out


class Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    state: ServerState = None  # injected

    # quiet default logging; route through logging module
    def log_message(self, fmt, *args):
        logger.debug("%s " + fmt, self.address_string(), *args)

    # ---- helpers ----------------------------------------------------------

    def _json(self, obj, code=200, headers=None):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _text(self, body: str, content_type: str, code=200):
        raw = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _read_json(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b"{}"
        try:
            d = json.loads(raw)
        except json.JSONDecodeError as e:
            raise proto.ProtocolError(f"invalid JSON body: {e}") from e
        if not isinstance(d, dict):
            raise proto.ProtocolError("request body must be a JSON object")
        return d

    def _sse_start(self):
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()

    def _sse(self, obj) -> None:
        self.wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
        self.wfile.flush()

    # ---- routes -----------------------------------------------------------

    def do_GET(self):
        st = self.state
        if self.path in ("/health", "/healthz"):
            # LIVENESS (docs/robustness.md): 200 while the engine thread
            # runs — even when unhealthy/draining (the supervisor
            # restarts on liveness, the balancer routes on readiness).
            # Replaces the static always-ok /health stub.
            eng = st.engine
            alive = bool(getattr(eng, "is_alive", True))
            body = {"status": "ok" if alive else "dead"}
            health = getattr(eng, "health", None)
            if callable(health):
                body.update(health())
            self._json(body, code=200 if alive else 503)
        elif self.path == "/readyz":
            # READINESS: may this instance be sent new requests? The
            # body carries the latch reason CLASS (step_failures /
            # stall / loop_death / crash_loop — also the
            # gllm_engine_unhealthy_reason info metric) + human detail,
            # so a router can tell a recovering replica (come back
            # after Retry-After) from a crash-looped one (reschedule).
            eng = st.engine
            readiness = getattr(eng, "readiness", None)
            ready, why = readiness() if callable(readiness) \
                else (True, "ok")
            if ready:
                self._json({"status": "ok"})
            else:
                body = {"status": "unavailable", "reason": why}
                cls = getattr(eng, "_unhealthy_class", "")
                if cls:
                    body["unhealthy_reason"] = cls
                    body["detail"] = getattr(eng, "_unhealthy_reason",
                                             "")
                retry_fn = getattr(eng, "retry_after_s", None)
                retry = retry_fn() if callable(retry_fn) else 5.0
                self._json(body, code=503, headers={
                    "Retry-After": str(max(1, int(round(retry))))})
        elif self.path == "/metrics":
            # Prometheus text exposition (gllm_tpu/obs/metrics.py):
            # request-latency histograms (TTFT/TPOT/ITL/e2e/queue),
            # per-step-kind counters, scheduler/KV gauges. Pure host
            # state — scraping never touches the device.
            from gllm_tpu.obs import metrics as obs_metrics
            self._text(obs_metrics.render(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif self.path.split("?", 1)[0] == "/steptrace":
            # JSON dump of the step-trace ring (pipe into
            # ``python -m gllm_tpu.obs.dump -`` for a readable table);
            # ?since=N resumes from a previous dump's last seq and
            # ?kind=a,b filters by event kind.
            from urllib.parse import parse_qs, urlparse
            from gllm_tpu.obs.steptrace import TRACE, summarize
            q = parse_qs(urlparse(self.path).query)
            try:
                since = int(q.get("since", ["0"])[0])
            except ValueError:
                self._json(proto.error_response(
                    "since must be an integer"), code=400)
                return
            kinds = [k for part in q.get("kind", [])
                     for k in part.split(",") if k]
            events = TRACE.events(since=since, kinds=kinds or None)
            self._json({"events": events,
                        "dropped": TRACE.dropped,
                        "next_since": TRACE.mark(),
                        "summary": summarize(events)})
        elif self.path.split("?", 1)[0] == "/trace":
            # Chrome trace-event JSON (Perfetto / chrome://tracing
            # loadable): one track per engine phase + the device track,
            # one track per request (this engine's span ring — spans
            # are per-LLM; seq_ids restart per engine). ?since=N limits
            # the step events like /steptrace.
            from urllib.parse import parse_qs, urlparse
            from gllm_tpu.obs.spans import SPANS, chrome_trace
            from gllm_tpu.obs.steptrace import TRACE
            q = parse_qs(urlparse(self.path).query)
            try:
                since = int(q.get("since", ["0"])[0])
            except ValueError:
                self._json(proto.error_response(
                    "since must be an integer"), code=400)
                return
            spans = getattr(st.llm, "spans", SPANS)
            self._json(chrome_trace(
                TRACE.events(since=since),
                spans.spans() + spans.open_spans(),
                span_t0=TRACE.t0))
        elif self.path == "/version":
            self._json({"version": gllm_tpu.__version__})
        elif self.path == "/v1/models":
            self._json({"object": "list", "data": [{
                "id": st.served_model, "object": "model",
                "created": int(st.start_time), "owned_by": "gllm-tpu"}]})
        elif self.path == "/server_info":
            cfg = st.llm.config
            eng = st.engine
            sup = getattr(eng, "supervisor", None)
            self._json({
                "model": cfg.model,
                "uptime_s": round(time.time() - st.start_time, 1),
                # explicit restart detection for the front router
                # (docs/robustness.md#fleet-topology--failover): a new
                # replica_id or start_time at the same address is a
                # process restart (journaled streams are gone); a bumped
                # engine_generation is a SUPERVISED in-process recovery
                # (streams replay locally, the router need not act)
                "replica": {
                    "replica_id": st.replica_id,
                    "start_time": round(st.start_time, 3),
                    "engine_generation": getattr(eng, "_gen", 0),
                    "recoveries": (sup.recoveries
                                   if sup is not None else 0),
                },
                "max_model_len": cfg.max_model_len,
                "schedule_method": cfg.scheduler.schedule_method,
                # pd-pool topology (docs/pd_pools.md): the router's
                # placement layer keys on this role
                "pool_role": cfg.scheduler.pool_role,
                "page_size": cfg.cache.page_size,
                "num_pages": st.llm.runner.num_pages,
                "prefix_caching": cfg.cache.enable_prefix_caching,
                # tiered prefix store (docs/kv_offload.md): which lower
                # tiers are live, and the peer-server address peers
                # should put in their --prefix-peers
                "prefix_store": {
                    "host_pool": cfg.cache.host_pool_configured,
                    "disk_path": cfg.cache.kv_disk_path,
                    "peers": cfg.cache.prefix_peers,
                    "serve_port": (
                        st.llm.prefix_tiers.server.port
                        if getattr(st.llm, "prefix_tiers", None)
                        is not None
                        and st.llm.prefix_tiers.server is not None
                        else None),
                    # per-peer circuit-breaker health (state / trips /
                    # failure counters, docs/robustness.md)
                    "peer_health": (
                        st.llm.prefix_tiers.client.peer_health()
                        if getattr(st.llm, "prefix_tiers", None)
                        is not None
                        and st.llm.prefix_tiers.client is not None
                        else None),
                },
                "parallel": {
                    "tp": cfg.parallel.tp, "dp": cfg.parallel.dp,
                    "pp": cfg.parallel.pp,
                    # per-stage [first, last) layer assignment — None on
                    # the single-runner (pp == 1)
                    "stage_layers": ([list(b) for b in getattr(
                        st.llm.runner, "stage_bounds", [])] or None),
                    # which fast-path flags this topology actually runs
                    # (docs/overlap_scheduling.md#topology-matrix) — the
                    # router/operator sees the lifted combinations, not
                    # just the raw grid
                    "fast_path": {
                        "overlap_scheduling": cfg.overlap_scheduling,
                        "pipelined_loop": cfg.pipelined_loop,
                        "unified_step": cfg.unified_step,
                        "spec_fused": cfg.spec_fused,
                    },
                },
                "attention_impl": st.llm.runner.attn_impl,
                "waiting": len(st.llm.scheduler.waiting),
                "running": len(st.llm.scheduler.running),
            })
        else:
            self._json(proto.error_response("not found", 404), code=404)

    def do_POST(self):
        try:
            if self.path == "/v1/chat/completions":
                self._chat()
            elif self.path == "/v1/completions":
                self._completion()
            elif self.path == "/start_profile":
                self._profile(True)
            elif self.path == "/stop_profile":
                self._profile(False)
            elif self.path.split("?", 1)[0] == "/profile":
                self._profile_oneshot()
            elif self.path == "/fault_inject":
                self._fault_inject()
            else:
                self._json(proto.error_response("not found", 404), code=404)
        except proto.ProtocolError as e:
            self._json(proto.error_response(str(e)), code=400)
        except RequestRejected as e:
            # admission control (docs/robustness.md): 429 over-capacity /
            # 503 unavailable, always with a Retry-After hint
            self._json(
                proto.error_response(str(e), e.status), code=e.status,
                headers={"Retry-After":
                         str(max(1, int(round(e.retry_after))))})
        except BrokenPipeError:
            pass  # client went away mid-write; abort handled in stream loop
        except Exception as e:  # pragma: no cover
            logger.exception("request failed")
            try:
                self._json(proto.error_response(f"internal error: {e}", 500),
                           code=500)
            except Exception:
                pass

    # ---- chat / completions ----------------------------------------------

    def _submit_choices(self, req, ids, mm_input, disagg_items,
                        count=None, rank_logprobs=False):
        """Submit ``count`` (default ``n``) independent sequences for one
        request (explicit seeds step per choice so seeded requests still
        differ); ``rank_logprobs`` forces chosen-logprob collection for
        best_of ranking."""
        import dataclasses as dc
        st = self.state
        handles = []
        try:
            for i in range(count if count is not None else req.n):
                sp = dc.replace(req.sampling)
                if sp.seed is not None:
                    sp.seed = sp.seed + i
                if rank_logprobs and sp.logprobs is None:
                    sp.logprobs = 0      # chosen-logprob only, for ranking
                handles.append(st.engine.submit(list(ids), sp,
                                                mm_input=mm_input,
                                                disagg_items=disagg_items,
                                                target_dp=st.pin_dp))
        except Exception:
            # partial submit must not leak running sequences: abort the
            # choices already admitted before re-raising
            for h in handles:
                st.engine.abort(h.seq_id)
            raise
        return handles

    def _sse_open(self, handles, *chunks) -> bool:
        """Send the SSE preamble (headers + any role chunks). A client
        that disconnected in the submit→stream window otherwise escapes
        every downstream abort handler and leaves the admitted sequences
        generating with no consumer — abort them here instead."""
        try:
            self._sse_start()
            for c in chunks:
                self._sse(c)
            return True
        except (BrokenPipeError, ConnectionResetError):
            for h in handles:
                self.state.engine.abort(h.seq_id)
            return False

    def _stream_many(self, handles, make_chunk):
        """Interleave n request streams into one SSE stream with
        per-choice indices (OpenAI ``stream`` + ``n > 1`` semantics —
        VERDICT r2 parity closure; each handle drains on its own thread
        into a merged queue, so a slow choice never stalls the others)."""
        import queue as _q
        import threading
        merged: "_q.Queue" = _q.Queue()

        def pump(i, h):
            # the sentinel MUST go up even if the handle iterator raises,
            # or the merge loop below waits forever on a dead choice; the
            # error rides along so the consumer can abort the siblings
            err = None
            try:
                for c in h:
                    merged.put((i, c))
            except Exception as e:       # noqa: BLE001 — surfaced below
                err = e
            merged.put((i, (None, err)))

        for i, h in enumerate(handles):
            threading.Thread(target=pump, args=(i, h),
                             daemon=True).start()
        done, first_err = 0, None
        try:
            while done < len(handles):
                i, c = merged.get()
                if isinstance(c, tuple):
                    done += 1
                    first_err = first_err or c[1]
                    continue
                self._sse(make_chunk(c.text or "", c.finish_reason, i))
                if c.finish_reason in ("error", "abort", "deadline") \
                        and (c.error or c.retry_after is not None):
                    self._sse(proto.stream_error_event(
                        c.error, c.finish_reason, c.retry_after))
            if first_err is not None:
                # a choice died mid-stream: abort the rest and close the
                # connection without [DONE] so the client sees a broken
                # stream, matching the single-choice path's behavior
                raise first_err
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            for h in handles:
                self.state.engine.abort(h.seq_id)
        except Exception:
            for h in handles:
                self.state.engine.abort(h.seq_id)
            raise

    def _run_choices(self, req, ids, mm_input=None):
        """Submit best_of sequences, collect all, rank by mean logprob when
        best_of > n, return the top n collected dicts (reference n/best_of
        semantics, protocol.py:170-203). Logprobs now flow under dp/pp
        too, so ranking works under every parallel mode."""
        rank = req.best_of > req.n
        mm_input, disagg_items = _split_disagg(mm_input)
        handles = self._submit_choices(req, ids, mm_input, disagg_items,
                                       count=req.best_of,
                                       rank_logprobs=rank)
        results = [self._collect(h) for h in handles]
        if rank:
            def score(r):
                lps = [e[1][0] for e in r["lp"] or [] if e[1] is not None]
                return sum(lps) / len(lps) if lps else float("-inf")
            results.sort(key=score, reverse=True)
        results = results[:req.n]
        prompt_tokens = results[0]["usage"]["prompt_tokens"] if results \
            else 0
        completion = sum(r["usage"]["completion_tokens"] for r in results)
        return results, proto.usage_dict(prompt_tokens, completion)

    def _decode_one(self, token_id: int) -> str:
        tok = self.state.llm.tokenizer
        return tok.decode([token_id]) if tok is not None else str(token_id)

    def _fault_inject(self):
        """Admin fault arming over the wire (chaos harnesses / soak
        rigs only): POST {"spec": "point[:after_n[:count]]"} arms
        gllm_tpu.faults points on this live server, {"reset": true}
        disarms everything. 404 unless GLLM_FAULT_INJECT_HTTP=1 — a
        production server must not expose a self-sabotage endpoint."""
        if os.environ.get("GLLM_FAULT_INJECT_HTTP", "0") in ("", "0"):
            self._json(proto.error_response("not found", 404), code=404)
            return
        body = self._read_json()
        if body.get("reset"):
            faults.FAULTS.reset()
        spec = body.get("spec", "")
        if spec:
            try:
                faults.FAULTS.arm(spec)
            except ValueError as e:
                raise proto.ProtocolError(str(e))
        self._json({"status": "ok",
                    "armed": {p: list(v) for p, v in
                              faults.FAULTS.armed_state().items()},
                    "hits": dict(faults.FAULTS.hits)})

    def _router_preamble(self, rid, ids, sp, mm, disagg):
        """First SSE event of a router-proxied stream
        (docs/robustness.md#fleet-topology--failover): the prompt token
        ids the router needs to journal the stream for cross-replica
        continuation, this replica's identity, and the PR 14 replay-
        safety verdict (None = the stream may fail over mid-flight)."""
        from gllm_tpu.engine.recovery import JournalEntry
        entry = JournalEntry(seq_id=0, prompt=tuple(ids), sampling=sp,
                             mm=mm, disagg=disagg)
        return {"gllm": {
            "prompt_token_ids": [int(t) for t in ids],
            "request_id": rid,
            "replica_id": self.state.replica_id,
            "unsafe_reason": entry.unsafe_reason(),
        }}

    def _chat(self):
        st = self.state
        body = self._read_json()
        # internal front-router extension (gllm_tpu/router/): never set
        # by OpenAI clients; asks for the journaling preamble +
        # per-token ids, and carries the committed prefix when this
        # request CONTINUES a stream a dead replica started
        router = body.pop("gllm_router", None)
        cont = (router or {}).get("continuation")
        req = proto.ChatCompletionRequest.from_dict(
            body, default_max_tokens=256)
        if cont is not None:
            # continuation prompts arrive as the original token ids —
            # re-encoding (and multimodal processing) is skipped; the
            # safety predicate already vetoed mm/disagg router-side
            ids, mm_input = [int(t) for t in
                             cont.get("prompt_token_ids", [])], None
            if not ids:
                raise proto.ProtocolError(
                    "gllm_router.continuation needs prompt_token_ids")
        else:
            ids, mm_input = st.encode_chat(req)
        if cont is not None and (not req.stream or req.n != 1):
            # the n>1 path would silently drop committed_token_ids and
            # stream fresh generations off the bare continuation prompt
            raise proto.ProtocolError(
                "gllm_router.continuation requires stream=true, n=1")
        if not req.stream:
            results, usage = self._run_choices(req, ids, mm_input)
            choices = []
            for r in results:
                text, tool_calls = r["text"], None
                if req.tools and req.tool_choice != "none":
                    from gllm_tpu.entrypoints.tool_parsers import (
                        schemas_from_tools)
                    text, calls = st.tool_parser.parse(
                        text, schemas_from_tools(req.tools))
                    tool_calls = [c.to_openai() for c in calls] or None
                lp = None
                if req.sampling.logprobs is not None:
                    lp = proto.chat_logprobs_content(r["lp"],
                                                     self._decode_one)
                choices.append({"text": text,
                                "finish_reason": r["finish"],
                                "tool_calls": tool_calls, "logprobs": lp})
            self._json(proto.chat_completion_response(req.model, choices,
                                                      usage))
            return
        mm_input, disagg_items = _split_disagg(mm_input)
        parse_tools = bool(req.tools) and req.tool_choice != "none"
        if req.n > 1:
            if parse_tools:
                raise proto.ProtocolError(
                    "stream with n > 1 and tool parsing is not supported")
            rid = proto.new_request_id(chat=True)
            # submit BEFORE the SSE headers go out: a submit-time
            # validation error (e.g. prompt > max_model_len) must still
            # surface as a clean JSON error, not a dead 200 stream
            handles = self._submit_choices(req, ids, mm_input,
                                           disagg_items)
            if not self._sse_open(handles, *[
                    proto.chat_completion_chunk(rid, req.model, None, None,
                                                role=True, index=i)
                    for i in range(req.n)]):
                return
            self._stream_many(handles, lambda text, fin, i: proto.
                              chat_completion_chunk(rid, req.model, text,
                                                    fin, index=i))
            return
        if cont is not None:
            handle = st.engine.submit_continuation(
                ids, cont.get("committed_token_ids", []), req.sampling,
                target_dp=st.pin_dp)
        else:
            handle = st.engine.submit(list(ids), req.sampling,
                                      mm_input=mm_input,
                                      disagg_items=disagg_items,
                                      target_dp=st.pin_dp)
        if req.stream and parse_tools:
            # Incremental tool streaming (reference streams tool deltas):
            # text deltas flow through live; only potential-markup suffixes
            # are held back; completed calls emit OpenAI tool_call deltas.
            from gllm_tpu.entrypoints.tool_parsers import (
                StreamingToolCalls, schemas_from_tools)
            stream = StreamingToolCalls(st.tool_parser,
                                        schemas_from_tools(req.tools))
            rid = proto.new_request_id(chat=True)
            if not self._sse_open(
                    [handle], proto.chat_completion_chunk(
                        rid, req.model, None, None, role=True)):
                return

            def emit(text, deltas):
                if text:
                    self._sse(proto.chat_completion_chunk(rid, req.model,
                                                          text, None))
                for d in deltas:
                    # a structured tool-call delta is on the wire: this
                    # stream can no longer replay across a supervised
                    # engine rebuild (docs/robustness.md#replay-safety)
                    handle.replay_safe = False
                    chunk = proto.chat_completion_chunk(rid, req.model,
                                                        None, None)
                    chunk["choices"][0]["delta"]["tool_calls"] = [d]
                    self._sse(chunk)

            fin = None
            err_ev = None
            try:
                for chunk_out in handle:
                    emit(*stream.feed(chunk_out.text or ""))
                    fin = chunk_out.finish_reason or fin
                    if fin in ("error", "abort", "deadline") and (
                            chunk_out.error
                            or chunk_out.retry_after is not None):
                        err_ev = proto.stream_error_event(
                            chunk_out.error, fin, chunk_out.retry_after)
                emit(*stream.finish())
                if stream.saw_tool_calls:
                    fin = "tool_calls"
                self._sse(proto.chat_completion_chunk(rid, req.model, None,
                                                      fin))
                if err_ev is not None:
                    self._sse(err_ev)
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                st.engine.abort(handle.seq_id)
        elif req.stream:
            rid = ((router or {}).get("request_id")
                   or proto.new_request_id(chat=True))
            preamble = []
            if router is not None:
                preamble.append(self._router_preamble(
                    rid, ids, req.sampling, mm_input is not None,
                    disagg_items is not None))
            if cont is None:
                # a continuation's client already holds the role chunk
                # from the replica that started the stream
                preamble.append(proto.chat_completion_chunk(
                    rid, req.model, None, None, role=True))
            if not self._sse_open([handle], *preamble):
                return
            self._stream(handle, lambda text, fin: proto.
                         chat_completion_chunk(rid, req.model, text, fin),
                         router=router is not None,
                         push_to=(None if cont is not None else
                                  (router or {}).get("push_to")),
                         prompt_ids=ids)

    def _completion(self):
        st = self.state
        body = self._read_json()
        router = body.pop("gllm_router", None)
        cont = (router or {}).get("continuation")
        req = proto.CompletionRequest.from_dict(
            body, default_max_tokens=256)
        if cont is not None:
            if not req.stream or req.n != 1:
                raise proto.ProtocolError(
                    "gllm_router.continuation requires stream=true, n=1")
            ids = [int(t) for t in cont.get("prompt_token_ids", [])]
            if not ids:
                raise proto.ProtocolError(
                    "gllm_router.continuation needs prompt_token_ids")
        else:
            ids = st.encode_completion(req)
        if req.stream:
            rid = ((router or {}).get("request_id")
                   or proto.new_request_id(chat=False))
            # submit before the SSE headers (see _chat): submit errors
            # still get a JSON error response
            if req.n > 1:
                handles = self._submit_choices(req, ids, None, None)
                if not self._sse_open(handles):
                    return
                self._stream_many(handles, lambda text, fin, i: proto.
                                  completion_chunk(rid, req.model,
                                                   text or "", fin,
                                                   index=i))
                return
            if cont is not None:
                handle = st.engine.submit_continuation(
                    ids, cont.get("committed_token_ids", []),
                    req.sampling, target_dp=st.pin_dp)
            else:
                handle = st.engine.submit(ids, req.sampling,
                                          target_dp=st.pin_dp)
            preamble = []
            if router is not None:
                preamble.append(self._router_preamble(
                    rid, ids, req.sampling, False, False))
            if not self._sse_open([handle], *preamble):
                return
            self._stream(handle, lambda text, fin: proto.completion_chunk(
                rid, req.model, text or "", fin),
                router=router is not None,
                push_to=(None if cont is not None else
                         (router or {}).get("push_to")),
                prompt_ids=ids)
            return
        results, usage = self._run_choices(req, ids)
        choices = []
        for r in results:
            text = r["text"]
            lp = None
            if req.sampling.logprobs is not None \
                    or req.sampling.prompt_logprobs is not None:
                entries = []
                offset0 = 0
                if req.echo and r["plp"] is not None:
                    entries.extend(
                        (tid, e) for tid, e in zip(ids, r["plp"]))
                lp_list = r["lp"] or []
                entries.extend(lp_list)
                lp = proto.completion_logprobs(entries, self._decode_one,
                                               offset0)
            if req.echo and isinstance(req.prompt, str):
                text = req.prompt + text
            choices.append({"text": text, "finish_reason": r["finish"],
                            "logprobs": lp})
        self._json(proto.completion_response(req.model, choices, usage))

    def _collect(self, handle):
        """Drain one request's stream → {"text", "finish", "usage", "lp"
        [(token_id, entry)], "plp"}."""
        text_parts, finish = [], "stop"
        usage = proto.usage_dict(0, 0)
        lp, plp, final_text = [], None, None
        for chunk in handle:
            if chunk.text:
                text_parts.append(chunk.text)
            if chunk.token_id is not None and chunk.logprob is not None:
                lp.append((chunk.token_id, chunk.logprob))
            if chunk.finish_reason is not None:
                finish = chunk.finish_reason
                usage = proto.usage_dict(chunk.num_prompt_tokens,
                                         chunk.num_output_tokens)
                plp = chunk.prompt_logprobs
                final_text = chunk.final_text
        text = final_text if final_text is not None \
            else "".join(text_parts)
        return {"text": text, "finish": finish,
                "usage": usage, "lp": lp or None, "plp": plp}

    def _stream(self, handle, make_chunk, router: bool = False,
                push_to=None, prompt_ids=None):
        pushed_pages = None
        try:
            for chunk in handle:
                # chaos points (docs/robustness.md#fleet): replica_kill
                # hard-closes the connection mid-stream — from a front
                # router's side this is the serving process dying;
                # replica_hang stalls before the next chunk (the wedged
                # replica the router's idle timeout must catch)
                if faults.FAULTS.fire("replica_kill"):
                    self.state.engine.abort(handle.seq_id)
                    try:
                        self.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self.close_connection = True
                    return
                faults.FAULTS.maybe_stall("replica_hang")
                # one SSE event per generated token (even when incremental
                # detokenization held text back) — clients measure ITL from
                # event arrivals
                ev = make_chunk(chunk.text or "", chunk.finish_reason)
                if router and chunk.token_id is not None:
                    # per-token ids for the front router's stream
                    # journal (stripped before the client sees them)
                    ev["gllm"] = {"token_id": int(chunk.token_id)}
                    if push_to and pushed_pages is None:
                        # pd-pool handoff (docs/pd_pools.md): the first
                        # sampled token means prefill is done — ship the
                        # prompt's prefix KV chain to the router-picked
                        # decode replica and report the accepted count
                        # so the router can migrate with zero re-prefill
                        pushed_pages = self.state.engine.push_prefix(
                            prompt_ids or [], push_to)
                        ev["gllm"]["pushed_pages"] = int(pushed_pages)
                self._sse(ev)
                if chunk.finish_reason in ("error", "abort", "deadline") \
                        and (chunk.error
                             or chunk.retry_after is not None):
                    self._sse(proto.stream_error_event(
                        chunk.error, chunk.finish_reason,
                        chunk.retry_after))
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            # client disconnect → abort the sequence
            # (reference async_llm_engine.py:113-126)
            self.state.engine.abort(handle.seq_id)

    # ---- profiler (reference profiler_mixin.py:12-117) --------------------

    def _profile(self, start: bool):
        import jax
        st = self.state
        with st._profile_mu:
            if start and not st._profiling:
                import os
                trace_dir = os.environ.get("GLLM_PROFILE_DIR",
                                           "/tmp/gllm_tpu_profile")
                jax.profiler.start_trace(trace_dir)
                st._profiling = True
                self._json({"status": "profiling started",
                            "trace_dir": trace_dir})
            elif not start and st._profiling_oneshot:
                # a POST /profile capture owns the profiler right now —
                # stopping it here would truncate that capture and make
                # its own stop_trace raise
                self._json(proto.error_response(
                    "a one-shot /profile capture is in progress", 409),
                    code=409)
            elif not start and st._profiling:
                jax.profiler.stop_trace()
                st._profiling = False
                self._json({"status": "profiling stopped"})
            else:
                self._json({"status": "noop"})

    def _profile_oneshot(self):
        """POST /profile?seconds=N — one-shot jax.profiler capture:
        start, sleep N seconds (serving continues; the engine thread is
        untouched), stop, return the artifact directory. The
        start/stop pair above remains for manual bracketing; this is
        the capture-and-return call a bench/ops script wants."""
        import os
        import time as _time
        from urllib.parse import parse_qs, urlparse
        import jax
        st = self.state
        q = parse_qs(urlparse(self.path).query)
        try:
            seconds = float(q.get("seconds", ["3"])[0])
        except ValueError:
            self._json(proto.error_response("seconds must be a number"),
                       code=400)
            return
        if not 0 < seconds <= 120:
            self._json(proto.error_response(
                "seconds must be in (0, 120]"), code=400)
            return
        if not st._profile_lock.acquire(blocking=False):
            self._json(proto.error_response(
                "a profile capture is already running", 409), code=409)
            return
        try:
            trace_dir = os.environ.get("GLLM_PROFILE_DIR",
                                       "/tmp/gllm_tpu_profile")
            # check + start atomically vs /start_profile (_profile_mu):
            # a racing manual start must not double-start the profiler
            with st._profile_mu:
                if st._profiling:
                    self._json(proto.error_response(
                        "profiler already started via /start_profile",
                        409), code=409)
                    return
                st._profiling = True
                st._profiling_oneshot = True
                jax.profiler.start_trace(trace_dir)
            try:
                _time.sleep(seconds)
            finally:
                with st._profile_mu:
                    try:
                        jax.profiler.stop_trace()
                    finally:
                        st._profiling = False
                        st._profiling_oneshot = False
            self._json({"status": "ok", "seconds": seconds,
                        "trace_dir": trace_dir})
        finally:
            st._profile_lock.release()


def build_engine_config(args) -> EngineConfig:
    return EngineConfig(
        model=args.model,
        tokenizer=args.tokenizer,
        dtype=args.dtype,
        seed=args.seed,
        max_model_len=args.max_model_len,
        max_num_seqs=args.max_num_seqs,
        load_format=args.load_format,
        allow_hub_download=args.allow_hub_download,
        attention_impl=args.attention_impl,
        overlap_scheduling=args.overlap_scheduling,
        pipelined_loop=args.pipelined_loop,
        unified_step=args.unified_step,
        overlap_depth=args.inflight_depth,
        decode_slot_batching=args.decode_slot_batching,
        chain_under_prefill=args.chain_under_prefill,
        decode_chain_len=args.decode_chain_len,
        ondevice_finish=args.ondevice_finish,
        spec_decode=args.spec_decode,
        spec_k=args.spec_k,
        spec_ngram=args.spec_ngram,
        spec_fused=args.spec_fused,
        quantization=args.quantization,
        sp_ring_threshold=args.sp_ring_threshold,
        mm_processor_min_pixels=args.mm_processor_min_pixels,
        mm_processor_max_pixels=args.mm_processor_max_pixels,
        tracing=not args.no_tracing,
        max_queued_requests=args.max_queued_requests,
        max_resident_requests=args.max_resident_requests,
        request_deadline_s=args.request_deadline_s,
        max_step_failures=args.max_step_failures,
        watchdog_stall_s=args.watchdog_stall_s,
        drain_timeout_s=args.drain_timeout_s,
        engine_recovery=args.engine_recovery,
        max_rebuilds=args.max_rebuilds,
        rebuild_window_s=args.rebuild_window_s,
        rebuild_backoff_s=args.rebuild_backoff_s,
        rebuild_backoff_max_s=args.rebuild_backoff_max_s,
        watchdog_hard_stall_s=args.watchdog_hard_stall_s,
        fault_inject=args.fault_inject,
        scheduler=SchedulerConfig(
            schedule_method=args.schedule_method,
            max_decode_seqs=args.maxd,
            max_prefill_tokens=args.maxp,
            min_prefill_tokens=args.minp,
            iter_smooth=args.iterp,
            init_new_token_ratio=args.init_new_token_ratio,
            min_new_token_ratio=args.min_new_token_ratio,
            pool_role=args.pool_role,
        ),
        enforce_eager=args.enforce_eager,
        cache=CacheConfig(
            page_size=args.page_size,
            memory_util=args.memory_util,
            num_pages=args.num_pages,
            kv_cache_dtype=args.kv_cache_dtype,
            enable_prefix_caching=args.enable_prefix_caching,
            kv_host_pool_gb=args.kv_host_pool_gb,
            swap_policy=args.swap_policy,
            kv_disk_path=args.kv_disk_path,
            kv_disk_gb=args.kv_disk_gb,
            prefix_peers=args.prefix_peers,
            prefix_serve_port=args.prefix_serve_port,
        ),
        parallel=ParallelConfig(
            pp=args.pp, tp=args.tp, dp=args.dp,
            sp=args.sp, enable_ep=args.enable_ep,
            assigned_layers=([int(x) for x in
                              args.assigned_layers.split(",") if x]
                             if args.assigned_layers else None)),
    )


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="gllm-tpu OpenAI-compatible API server")
    p.add_argument("--model", required=True)
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--served-model-name", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-model-len", type=int, default=4096)
    p.add_argument("--max-num-seqs", type=int, default=256)
    p.add_argument("--load-format", default="auto",
                   choices=["auto", "dummy"])
    p.add_argument("--attention-impl", default="auto",
                   choices=["auto", "pallas", "xla"])
    # scheduler (reference --schedule-method/--maxd/--maxp/--minp/--iterp)
    p.add_argument("--schedule-method", default="chunked_prefill",
                   choices=["chunked_prefill", "token_throttling",
                            "split_pd"])
    p.add_argument("--maxd", type=int, default=256)
    p.add_argument("--maxp", type=int, default=2048)
    p.add_argument("--minp", type=int, default=128)
    p.add_argument("--iterp", type=int, default=16)
    p.add_argument("--pool-role", default="mixed",
                   choices=["prefill", "decode", "mixed"],
                   help="pd-pool role advertised on /server_info "
                        "(docs/pd_pools.md): the front router places "
                        "new prompts on prefill replicas and migrates "
                        "each stream to a decode replica at first "
                        "token, pushing the prefix KV chain ahead of "
                        "it; mixed (default) serves both phases")
    p.add_argument("--init-new-token-ratio", type=float, default=0.7,
                   help="adaptive KV admission ramp start (reference "
                        "--init-new-token-ratio)")
    p.add_argument("--min-new-token-ratio", type=float, default=0.1,
                   help="admission ramp floor")
    p.add_argument("--enforce-eager", action="store_true",
                   help="disable donation/async dispatch tricks (debug; "
                        "the reference's --disable-cuda-graph analogue)")
    # cache
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--memory-util", type=float, default=0.9,
                   help="fraction of device memory for the KV cache")
    p.add_argument("--num-pages", type=int, default=None)
    p.add_argument("--kv-cache-dtype", default="auto",
                   choices=("auto", "bfloat16", "float16", "float32",
                            "fp8", "int8"),
                   help="paged-KV storage dtype; int8 stores quantized "
                        "K/V with per-page per-head scales dequantized "
                        "in-kernel (halves KV reads, ~2x page capacity; "
                        "docs/kv_quantization.md). auto = model dtype")
    p.add_argument("--quantization", default=None,
                   choices=["int8", "fp8", "int4", "w8a8", "fp8_block"],
                   help="weight-only quantization")
    p.add_argument("--enable-prefix-caching", action="store_true")
    p.add_argument("--kv-host-pool-gb", type=float, default=0.0,
                   help="host-RAM KV tier size in GiB (gllm_tpu/kvswap):"
                        " preemption victims swap out instead of "
                        "recomputing, evicted prefix pages spill here; "
                        "0 disables the tier (docs/kv_offload.md)")
    p.add_argument("--swap-policy", default="auto",
                   choices=["auto", "swap", "recompute"],
                   help="auto: swap iff a host pool is configured; "
                        "swap: require the pool; recompute: legacy "
                        "free-and-recompute preemption")
    p.add_argument("--kv-disk-path", default=None,
                   help="disk prefix tier behind the host pool: "
                        "content-addressed page files under this "
                        "directory, written on host-tier eviction, "
                        "probed on host miss (needs "
                        "--enable-prefix-caching and --kv-host-pool-gb; "
                        "docs/kv_offload.md)")
    p.add_argument("--kv-disk-gb", type=float, default=4.0,
                   help="byte budget of the disk prefix tier "
                        "(LRU-evicted above it)")
    p.add_argument("--prefix-peers", default=None,
                   help="comma-separated host:port of peer replicas' "
                        "prefix servers — match_prefix restores "
                        "digest-addressed pages another replica "
                        "computed (docs/kv_offload.md)")
    p.add_argument("--prefix-serve-port", type=int, default=None,
                   help="serve this replica's prefix pages to peers on "
                        "this port (0 = ephemeral; omit to not serve)")
    p.add_argument("--allow-hub-download", action="store_true",
                   help="resolve a non-local model id via HF-hub snapshot "
                        "download (file-lock serialized); default is "
                        "local-path-only")
    p.add_argument("--overlap-scheduling", action="store_true",
                   help="chain decode steps on-device (no host round trip "
                        "between decode iterations)")
    p.add_argument("--pipelined-loop", action="store_true",
                   help="bubble-zero engine loop: speculatively re-form "
                        "the next decode batch off promised token counts "
                        "when a chain breaks (finish, compaction, "
                        "membership growth) instead of draining the "
                        "pipeline; divergence is reconciled at collect "
                        "time (implies --overlap-scheduling; "
                        "docs/overlap_scheduling.md#pipelined-loop)")
    p.add_argument("--unified-step", action="store_true",
                   help="one ragged kernel, one dispatch: serve every "
                        "paged step as a unified mixed batch (decode "
                        "rows are q_len=1 rows of the ragged batch), "
                        "collapse the shape-signature space to (row "
                        "bucket × token bucket), and let decode chains "
                        "ABSORB prefill chunks through mixed re-formed "
                        "batches instead of yielding (retires the "
                        "'waiting' break class and --chain-under-"
                        "prefill; docs/overlap_scheduling.md#unified-"
                        "step). Off = byte-identical legacy dispatch")
    p.add_argument("--inflight-depth", type=int, default=2,
                   help="max dispatched-but-uncollected engine entries "
                        "under --overlap-scheduling (the pipelined "
                        "loop's run-ahead bound; depth 2 hides host "
                        "batch building, deeper also hides the "
                        "remote-dispatch round trip)")
    p.add_argument("--decode-slot-batching", action="store_true",
                   help="persistent-slot decode chains (needs "
                        "--overlap-scheduling): finished rows become "
                        "masked holes instead of breaking the fused "
                        "chain, decode-ready seqs join vacant slots at "
                        "chain boundaries (docs/overlap_scheduling.md)")
    p.add_argument("--chain-under-prefill", type=int, default=0,
                   help="with prefill work waiting, chain up to K decode "
                        "steps before yielding one sync pass to prefill; "
                        "0 = legacy, any waiting arrival unfuses every "
                        "step until the queue drains")
    p.add_argument("--decode-chain-len", type=int, default=None,
                   help="fused decode chain length: K decode steps per "
                        "device dispatch (needs --overlap-scheduling); "
                        "default 1, or 16 with --ondevice-finish")
    p.add_argument("--ondevice-finish", action="store_true",
                   help="detect EOS/stop-token finishes INSIDE fused "
                        "decode blocks (carried alive mask + early block "
                        "exit) instead of burning dead sub-steps until "
                        "the host notices; token streams are identical "
                        "(docs/overlap_scheduling.md)")
    p.add_argument("--spec-decode", default=None, choices=["ngram"],
                   help="prompt-lookup speculative decoding: verify up to "
                        "--spec-k n-gram drafts per decode step (greedy "
                        "requests only; byte-identical outputs)")
    p.add_argument("--spec-k", type=int, default=4)
    p.add_argument("--spec-ngram", type=int, default=2)
    p.add_argument("--spec-fused", action="store_true",
                   help="fuse draft+verify into the chained multi-step "
                        "dispatch (requires --spec-decode ngram): the "
                        "device drafts from a carried recent-token ring "
                        "and one dispatch emits up to K*(spec_k+1) "
                        "tokens; greedy streams byte-identical, chains "
                        "and speculation compose "
                        "(docs/speculative_decoding.md)")
    p.add_argument("--mm-processor-min-pixels", type=int, default=None,
                   help="lower bound on image/video resolution fed to the "
                        "multimodal processor (reference "
                        "api_server.py:488-494)")
    p.add_argument("--mm-processor-max-pixels", type=int, default=None,
                   help="upper bound on image/video resolution — the "
                        "lever that keeps large-image workloads inside "
                        "HBM")
    p.add_argument("--endpoint-per-dp", action="store_true",
                   help="one HTTP listener per DP replica, each pinning "
                        "its requests to that replica (session affinity "
                        "keeps a conversation's prefix cache on one "
                        "replica; reference --endpoint-per-dp)")
    p.add_argument("--endpoint-per-dp-ports", default=None,
                   help="comma-separated ports, one per replica in "
                        "DP-rank order (default: port, port+1, ...)")
    p.add_argument("--tool-call-parser", default=None,
                   choices=["qwen", "hermes", "deepseek", "none"],
                   help="tool-call markup parser (default: auto-detect "
                        "from model name)")
    # request-lifecycle robustness (docs/robustness.md)
    p.add_argument("--max-queued-requests", type=int, default=0,
                   help="admission bound on the intake queue; over-limit "
                        "submits get HTTP 429 + Retry-After instead of "
                        "queueing unboundedly (0 = unbounded)")
    p.add_argument("--max-resident-requests", type=int, default=0,
                   help="cap on concurrently open request streams; "
                        "beyond it submits get HTTP 429 (0 = unbounded)")
    p.add_argument("--request-deadline-s", type=float, default=0.0,
                   help="default wall-clock TTL per request: waiting or "
                        "overrunning requests are aborted with finish "
                        "reason 'deadline' (0 = none; per-request "
                        "deadline_s overrides)")
    p.add_argument("--max-step-failures", type=int, default=3,
                   help="consecutive failed engine steps before the "
                        "engine latches unhealthy (readiness 503); "
                        "individual failures only abort their own batch")
    p.add_argument("--watchdog-stall-s", type=float, default=0.0,
                   help="flip /readyz to 503 while the engine heartbeat "
                        "is staler than this (hung device dispatch); "
                        "must exceed the longest legitimate compile "
                        "(0 = watchdog off)")
    p.add_argument("--drain-timeout-s", type=float, default=5.0,
                   help="graceful-shutdown budget for in-flight requests "
                        "before they are aborted with terminal chunks")
    p.add_argument("--engine-recovery", action="store_true",
                   help="supervised in-process recovery "
                        "(docs/robustness.md): an unhealthy latch / "
                        "engine-loop death / watchdog hard stall tears "
                        "the engine down and rebuilds it in-process — "
                        "/readyz reports 'recovering' with Retry-After "
                        "and retry-safe (seeded or greedy) requests "
                        "replay from their committed prefix")
    p.add_argument("--max-rebuilds", type=int, default=3,
                   help="crash-loop latch: this many FAILED rebuilds "
                        "within --rebuild-window-s latch the permanent "
                        "unhealthy state (never an infinite rebuild "
                        "loop)")
    p.add_argument("--rebuild-window-s", type=float, default=300.0)
    p.add_argument("--rebuild-backoff-s", type=float, default=0.25,
                   help="first-retry rebuild backoff; doubles per "
                        "failure up to --rebuild-backoff-max-s")
    p.add_argument("--rebuild-backoff-max-s", type=float, default=30.0)
    p.add_argument("--watchdog-hard-stall-s", type=float, default=0.0,
                   help="heartbeat age that ESCALATES a watchdog stall "
                        "to a supervised rebuild (abandons the wedged "
                        "engine thread; needs --engine-recovery and "
                        "--watchdog-stall-s; 0 = soft readiness flips "
                        "only)")
    p.add_argument("--replica-id", default=None,
                   help="stable fleet identity advertised on "
                        "/server_info (with start_time + engine "
                        "generation) so a front router detects silent "
                        "process restarts; default: random per process "
                        "(env GLLM_REPLICA_ID)")
    p.add_argument("--fault-inject", default="",
                   help="deterministic fault injection spec "
                        "'point[:after_n[:count]][,...]' "
                        "(gllm_tpu/faults.py; chaos testing only)")
    p.add_argument("--no-tracing", action="store_true",
                   help="disable the request-span tracing layer "
                        "(GET /trace request tracks; the engine-phase "
                        "attribution on /steptrace stays on). Token "
                        "streams are byte-identical either way "
                        "(docs/observability.md#tracing)")
    p.add_argument("--skip-warmup", action="store_true",
                   help="don't pre-compile decode buckets before serving "
                        "(first requests pay compile latency instead)")
    # parallelism / multi-host (reference --launch-mode master|slave →
    # jax.distributed coordinator/worker)
    p.add_argument("--coordinator-address", default=None,
                   help="host:port of host 0 for multi-host serving")
    p.add_argument("--blob-advertise-host", default=None,
                   help="address followers use to reach host 0's bulk-"
                        "payload (MM pixel) server; default resolves "
                        "gethostname(), which is wrong on hosts whose "
                        "/etc/hosts maps the hostname to loopback")
    p.add_argument("--num-hosts", type=int, default=1)
    p.add_argument("--host-id", type=int, default=None)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--assigned-layers", default=None,
                   help="comma-separated per-stage layer counts for pp "
                        "(reference --assigned-layers)")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1,
                   help="sequence parallelism: long prefill chunks run "
                        "ring attention over an sp mesh axis (beyond the "
                        "reference); requires pp=dp=1")
    p.add_argument("--sp-ring-threshold", type=int, default=1024)
    p.add_argument("--enable-ep", action="store_true")
    return p


def serve(llm: LLM, host: str, port: int,
          served_model: Optional[str] = None,
          tool_parser: Optional[str] = None,
          pin_dp: Optional[int] = None,
          engine=None,
          replica_id: Optional[str] = None) -> ThreadingHTTPServer:
    """Build the HTTP server (caller decides foreground vs thread)."""
    state = ServerState(llm, served_model or llm.config.model, tool_parser,
                        engine=engine, pin_dp=pin_dp,
                        replica_id=replica_id)
    handler = type("BoundHandler", (Handler,), {"state": state})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.state = state
    return httpd


def serve_per_dp(llm: LLM, host: str, ports: List[int],
                 served_model: Optional[str] = None,
                 tool_parser: Optional[str] = None
                 ) -> List[ThreadingHTTPServer]:
    """One HTTP listener per DP replica, all sharing ONE engine: listener
    d pins its requests to replica d, so a client holding a conversation
    on one endpoint keeps its prefix cache (and KV) on one replica
    (reference --endpoint-per-dp, api_server.py run_server +
    llm_engine.py:121-133 pinning)."""
    assert len(ports) == llm.dp, (len(ports), llm.dp)
    engine = ServingEngine(llm)
    return [serve(llm, host, p, served_model, tool_parser,
                  pin_dp=d, engine=engine)
            for d, p in enumerate(ports)]


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    args = make_parser().parse_args(argv)
    multihost = False
    if args.num_hosts > 1 or args.coordinator_address:
        from gllm_tpu.parallel.multihost import init_multihost
        init_multihost(args.coordinator_address, args.num_hosts,
                       args.host_id)
        import jax
        multihost = jax.process_count() > 1
    t_start = time.monotonic()
    llm = LLM(config=build_engine_config(args))
    if not args.skip_warmup:
        llm.runner.warmup()
        if not multihost:
            # Serving-readiness yardstick (reference: CUDA-graph capture
            # logs): one real token through the full engine path.
            from gllm_tpu.sampling_params import SamplingParams
            t0 = time.monotonic()
            llm.generate(prompt_token_ids=[[1, 2, 3]],
                         sampling_params=SamplingParams(
                             temperature=0.0, max_tokens=1,
                             ignore_eos=True))
            logger.info("[startup] phase=first_token seconds=%.2f "
                        "total_startup_seconds=%.2f",
                        time.monotonic() - t0,
                        time.monotonic() - t_start)
    if multihost:
        # Host 0 runs the HTTP frontend + broadcasts every tick's intake;
        # followers mirror the deterministic engine loop so all processes
        # issue identical jit programs (the role of the reference's zmq
        # master/slave plane, comm.py:191-319).
        import jax

        from gllm_tpu.parallel.multihost_engine import (
            MultihostEngine, MultihostServingEngine)
        if jax.process_index() != 0:
            logger.info("follower %d joined; mirroring engine loop",
                        jax.process_index())
            MultihostEngine(llm).run_follower()
            return
        state = ServerState(llm, args.served_model_name or args.model,
                            tool_parser=args.tool_call_parser,
                            engine=MultihostServingEngine(
                                llm,
                                advertise_host=args.blob_advertise_host))
        handler = type("BoundHandler", (Handler,), {"state": state})
        httpd = ThreadingHTTPServer((args.host, args.port), handler)
        httpd.state = state
    elif args.endpoint_per_dp and args.dp > 1:
        if args.endpoint_per_dp_ports:
            ports = [int(p) for p in
                     args.endpoint_per_dp_ports.split(",") if p]
            if len(ports) != args.dp:
                raise SystemExit(
                    f"--endpoint-per-dp-ports has {len(ports)} ports "
                    f"but dp={args.dp}")
        else:
            ports = [args.port + d for d in range(args.dp)]
        servers = serve_per_dp(llm, args.host, ports,
                               args.served_model_name or args.model,
                               tool_parser=args.tool_call_parser)
        logger.info("DP per-replica endpoints: %s",
                    ", ".join(f"dp{d}->:{p}"
                              for d, p in enumerate(ports)))
        import threading
        threads = [threading.Thread(target=s.serve_forever, daemon=True)
                   for s in servers[1:]]
        for t in threads:
            t.start()
        try:
            servers[0].serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            for s in servers[1:]:
                s.shutdown()
            servers[0].state.engine.shutdown(drain=True)
        return
    else:
        httpd = serve(llm, args.host, args.port,
                      args.served_model_name or args.model,
                      tool_parser=args.tool_call_parser,
                      replica_id=args.replica_id)
    logger.info("serving %s on %s:%d", args.model, args.host, args.port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # graceful drain: stop admitting, let in-flight requests finish
        # (bounded), close every open stream with a terminal chunk, join
        eng = httpd.state.engine
        try:
            eng.shutdown(drain=True)
        except TypeError:   # MultihostServingEngine: no drain support
            eng.shutdown()


if __name__ == "__main__":
    main()
