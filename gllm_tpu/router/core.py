"""FrontRouter: the SSE proxy loop + cross-replica failover machine.

One :class:`FrontRouter` fronts N api_server replicas. Streaming
requests are proxied with the ``gllm_router`` body extension: the
replica's preamble event hands back the tokenized prompt + the PR 14
replay-safety verdict, and every token chunk carries its token id for
the journal. When the upstream dies mid-stream — connection drop, idle
timeout (wedged replica), a replica-side terminal ``error``/``abort``
chunk, or a detected silent restart — the router resubmits the request
to a surviving replica with ``gllm_router.continuation`` (prompt +
committed token ids), and the replica's
``ServingEngine.submit_continuation`` resumes generation from exactly
the committed prefix: the client observes ONE uninterrupted,
byte-identical stream. Streams the safety predicate vetoes (unseeded
sampling, mm, stop strings, multi-choice, tool deltas …) never fail
over once content was delivered; they end with a terminal error chunk
carrying ``retry_after``.

Failure-detection / decision table (docs/robustness.md#fleet-topology--
failover):

====================================  =================================
upstream symptom                      router action
====================================  =================================
connect refused / submit error        try next replica (nothing lost)
HTTP 429/503 on submit                try next replica (capacity race)
socket error / EOF mid-stream         failover if safe, else error chunk
read idle > stream_idle_timeout_s     same (the wedged-replica shape)
chunk finish_reason error/abort       same (engine failed server-side)
upstream terminal ``error`` event     same, honoring its retry_after
silent restart (identity changed)     poller closes the upstream socket
                                      → surfaces as a socket error
finish_reason stop/length/deadline…   terminal: forward, never failover
====================================  =================================
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, Optional

import http.client

from gllm_tpu.entrypoints import protocol as proto
from gllm_tpu.faults import FAULTS
from gllm_tpu.obs import metrics as obs
from gllm_tpu.pools import PoolAutoscaler, replica_role
from gllm_tpu.router.journal import (StreamEntry, StreamJournal,
                                     router_unsafe_reason)
from gllm_tpu.router.placement import Placement, PrefixAffinity
from gllm_tpu.router.replica import ReplicaSet

logger = logging.getLogger(__name__)

_M_REQS = obs.counter(
    "gllm_router_requests_total",
    "requests through the router by kind and outcome (ok; error = "
    "terminal error delivered; rejected = no replica could take it; "
    "client_gone = the client disconnected first)",
    ("kind", "outcome"))
_M_STREAMS = obs.gauge(
    "gllm_router_streams_active",
    "streams currently proxied (journaled) by the router")
_M_FAILOVERS = obs.counter(
    "gllm_router_failovers_total",
    "mid-stream failover attempts by outcome (ok = stream resumed on a "
    "surviving replica; unsafe = vetoed by the replay-safety predicate; "
    "exhausted = no surviving replica / attempt budget spent)",
    ("outcome",))
_M_FAILOVER_S = obs.histogram(
    "gllm_router_failover_seconds",
    "failure detection to first continuation chunk forwarded")
_M_POOL_HANDOFFS = obs.counter(
    "gllm_router_pool_handoffs_total",
    "prefill->decode pool stream migrations by outcome (ok = stream "
    "resumed on the decode pool; fallback = handoff vetoed/failed, the "
    "stream continued through normal placement — zero lost tokens "
    "either way; docs/pd_pools.md)", ("outcome",))
_M_POOL_HANDOFF_S = obs.histogram(
    "gllm_router_pool_handoff_seconds",
    "pd handoff raised (first prefill token forwarded) to first decode-"
    "pool chunk forwarded")


class UpstreamFailed(Exception):
    """One upstream attempt died; carries the replica's retry_after
    hint when its terminal error event supplied one.
    ``replica_suspect=False`` marks a CAPACITY answer (429/503
    admission rejection) — the replica is healthy, just busy: try
    elsewhere without prodding its health state. Suspect failures
    trigger an immediate poller re-probe instead of tripping the
    breaker from the handler thread: the POLLER is the breaker's
    single prober (gllm_tpu.utils.CircuitBreaker contract), and a
    transient per-stream fault (replica_kill) must not eject a healthy
    replica from rotation for a whole backoff window."""

    def __init__(self, why: str, retry_after: Optional[float] = None,
                 replica_suspect: bool = True):
        super().__init__(why)
        self.retry_after = retry_after
        self.replica_suspect = replica_suspect


class ClientGone(Exception):
    """The downstream client disconnected; abort the upstream and stop."""


class PoolHandoff(Exception):
    """Internal control flow (docs/pd_pools.md): the first sampled
    token was forwarded from a prefill-pool replica and a decode target
    is picked — leave this upstream and resume the stream on the decode
    pool via the normal continuation path. Deliberately NOT an
    UpstreamFailed: the prefill replica did nothing wrong and the
    failover budget/metrics must not move."""


class FrontRouter:
    """Health-aware placement + journal-backed stream failover over a
    fleet of api_server replicas. Thread-safe: one handler thread per
    client stream, one poller thread, shared journal/placement."""

    def __init__(self, replica_addrs, *,
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 2.0,
                 stream_idle_timeout_s: float = 60.0,
                 request_timeout_s: float = 600.0,
                 max_failovers: int = 2,
                 session_affinity: bool = True,
                 prefix_affinity: bool = False,
                 prefix_probe_timeout_s: float = 0.25,
                 breaker_base_s: float = 1.0,
                 breaker_max_s: float = 30.0,
                 breaker_fails: int = 1,
                 breaker_jitter: float = 0.1,
                 slo_ttft_s: float = 2.0,
                 slo_tpot_s: float = 0.5,
                 autoscale_interval_s: float = 5.0,
                 start_poller: bool = True,
                 initial_probe: bool = True):
        self.journal = StreamJournal()
        # per-pool scale verdicts (docs/pd_pools.md#autoscaling): fed by
        # the poller via info_hook, read by /router_info
        self.autoscaler = PoolAutoscaler(
            slo_ttft_s=slo_ttft_s, slo_tpot_s=slo_tpot_s,
            interval_s=autoscale_interval_s,
            scrape_timeout_s=probe_timeout_s)
        self.replicas = ReplicaSet(
            list(replica_addrs),
            probe_interval_s=probe_interval_s,
            probe_timeout_s=probe_timeout_s,
            breaker_base_s=breaker_base_s,
            breaker_max_s=breaker_max_s,
            breaker_fails=breaker_fails,
            breaker_jitter=breaker_jitter,
            on_restart=self._on_restart,
            info_hook=self.autoscaler.observe,
            start_poller=start_poller,
            initial_probe=initial_probe)
        self.placement = Placement(
            self.replicas, session_affinity=session_affinity,
            prefix_affinity=(PrefixAffinity(prefix_probe_timeout_s)
                             if prefix_affinity else None))
        self.stream_idle_timeout_s = float(stream_idle_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        self.max_failovers = max(0, int(max_failovers))
        self._lock = threading.Lock()
        self._conns: Dict[str, http.client.HTTPConnection] = {}

    # ---- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self.replicas.close()
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _on_restart(self, rep) -> None:
        """A silent process restart forgot every stream it held: close
        those upstream sockets so their reader threads fail over NOW
        instead of waiting out the idle timeout."""
        for entry in self.journal.by_replica(rep.addr):
            with self._lock:
                conn = self._conns.get(entry.rid)
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    # ---- router health (for the router's own /readyz) ----------------------

    def health(self) -> dict:
        rotation = self.replicas.in_rotation()
        return {
            "ready": bool(rotation),
            "replicas_in_rotation": len(rotation),
            "replicas": self.replicas.health(),
            "active_streams": len(self.journal),
            "retry_after_s": (None if rotation
                              else round(self.replicas.min_retry_after(),
                                         2)),
            # per-pool autoscaling signals + scale verdicts
            # (docs/pd_pools.md#autoscaling)
            "pools": self.autoscaler.verdicts(
                list(self.replicas.replicas.values())),
        }

    # ---- pd pools (docs/pd_pools.md) ---------------------------------------

    def _pd_active(self) -> bool:
        """Handoffs happen only when BOTH strict pools are present in
        rotation — a mixed/legacy fleet keeps the single-replica stream
        shape, byte-identical to PR 15."""
        roles = {replica_role(r) for r in self.replicas.in_rotation()}
        return "prefill" in roles and "decode" in roles

    def _push_addr(self, rep) -> Optional[str]:
        """``host:serve_port`` of a replica's prefix store, or None when
        it doesn't serve one (the handoff still migrates; the decode
        side just re-prefills)."""
        store = (rep.info or {}).get("prefix_store") or {}
        port = store.get("serve_port")
        return f"{rep.host}:{int(port)}" if port else None

    def drain_replica(self, addr: str, migrate: bool = False) -> dict:
        """Admin drain (scale-down, docs/pd_pools.md#autoscaling): take
        ``addr`` out of rotation and — with ``migrate`` — close its
        proxied upstream connections so each replay-safe (or
        not-yet-delivering) stream fails over to a surviving replica
        through the journaled continuation path with zero lost tokens.
        Unsafe mid-stream entries are left to FINISH IN PLACE: the
        replica keeps serving them (drain only blocks new placement),
        which is the whole point of drain vs kill."""
        ok = self.replicas.drain(addr, True)
        moved = 0
        if ok and migrate:
            for entry in self.journal.by_replica(addr):
                if not (entry.replay_safe or entry.can_restart):
                    continue
                with self._lock:
                    conn = self._conns.get(entry.rid)
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    moved += 1
        return {"ok": ok, "migrating_streams": moved}

    # ---- non-streaming proxy -----------------------------------------------

    def proxy(self, method: str, path: str, body: Optional[dict] = None,
              session: Optional[str] = None, kind: str = "proxy"
              ) -> tuple:
        """(status, body_bytes, headers_subset). Nothing streams, so
        nothing was delivered before a failure — ANY request may retry
        on the next replica (a deterministic one re-derives the same
        answer; a sampled one re-samples, which a from-scratch client
        retry would do too)."""
        exclude: set = set()
        last = (503, json.dumps(proto.error_response(
            "no replica in rotation", 503)).encode(), {})
        for _ in range(len(self.replicas.replicas)):
            rep = self.placement.pick(session, exclude=exclude)
            if rep is None:
                break
            exclude.add(rep.addr)
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self.request_timeout_s)
                try:
                    conn.request(
                        method, path,
                        body=(json.dumps(body).encode()
                              if body is not None else None),
                        headers={"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    raw = resp.read()
                    headers = {k: v for k, v in resp.getheaders()
                               if k.lower() in ("content-type",
                                                "retry-after")}
                finally:
                    conn.close()
            except (OSError, http.client.HTTPException) as e:
                self.replicas.request_probe()
                last = (503, json.dumps(proto.error_response(
                    f"replica {rep.addr} unreachable: {e}", 503)
                ).encode(), {})
                continue
            if resp.status in (429, 503):
                # capacity race (the poller will catch up) — try the
                # next replica, remember this answer as the fallback
                last = (resp.status, raw, headers)
                continue
            _M_REQS.inc(kind=kind, outcome="ok" if resp.status < 500
                        else "error")
            return resp.status, raw, headers
        status, raw, headers = last
        headers.setdefault("Retry-After", str(int(
            self.replicas.min_retry_after())))
        _M_REQS.inc(kind=kind, outcome="rejected")
        return status, raw, headers

    # ---- streaming proxy + failover ---------------------------------------

    def stream(self, kind: str, body: dict, sse,
               session: Optional[str] = None) -> None:
        """Proxy one streaming request. ``sse`` is the downstream
        surface: ``.started`` (bool), ``.start()`` (send SSE headers,
        idempotent), ``.send(obj)`` (one event; raises
        :class:`ClientGone`), ``.done()`` ([DONE]), ``.fail_json(status,
        obj, headers)`` (only legal before ``start``)."""
        rid = proto.new_request_id(chat=(kind == "chat"))
        entry = self.journal.open(StreamEntry(
            rid=rid, kind=kind, body=body, session=session,
            unsafe_reason=router_unsafe_reason(body, kind)))
        _M_STREAMS.set(len(self.journal))
        exclude: set = set()
        last_failed: Optional[str] = None
        pinned: Optional[str] = None    # decode target after a pd handoff
        give_up_why, give_up_retry = "no replica in rotation", None
        try:
            while True:
                token_hint = entry.prompt_token_ids
                if token_hint is None and kind == "completion" \
                        and isinstance(body.get("prompt"), list):
                    token_hint = body["prompt"]
                pd = self._pd_active()
                # pool preference (docs/pd_pools.md): fresh streams go
                # to the prefill pool, post-handoff continuations to
                # the decode pool; a fallen-back handoff (pd_migrated
                # with no target) reverts to normal placement. Always a
                # preference, never a constraint — placement degrades
                # to the whole rotation when the pool is empty.
                role = None
                if pd and not entry.pd_migrated:
                    role = "prefill"
                elif pd and entry.pd_target:
                    role = "decode"
                rep = None
                if pinned is not None:
                    cand = self.replicas.get(pinned)
                    if cand is not None and cand.in_rotation \
                            and cand.addr not in exclude:
                        rep = cand
                    else:
                        # the decode target died/drained between the
                        # handoff and the dispatch: the PR 15 failover
                        # path takes over via normal placement
                        pinned = None
                if rep is None:
                    rep = self.placement.pick(session,
                                              token_ids=token_hint,
                                              exclude=exclude, role=role)
                if rep is None and exclude:
                    # every in-rotation replica already failed once for
                    # THIS stream (e.g. a fault that follows the stream
                    # around): transient per-connection failures must
                    # not exhaust an otherwise-healthy fleet — re-admit
                    # everything, preferring not-the-most-recent
                    # failure; a rotation of ONE may retry the same
                    # replica (a continuation there succeeds after a
                    # transient drop). The migration/attempt budgets
                    # still bound the loop, and a really-dead replica
                    # leaves rotation via the nudged re-probe.
                    rep = self.placement.pick(
                        session, token_ids=token_hint,
                        exclude={last_failed} if last_failed else ())
                    if rep is None:
                        rep = self.placement.pick(session,
                                                  token_ids=token_hint)
                if rep is None:
                    give_up_retry = self.replicas.min_retry_after()
                    if entry.fail_detected_at is not None:
                        _M_FAILOVERS.inc(outcome="exhausted")
                    break
                # pd handoff arming: a fresh, replay-safe stream landing
                # on a non-decode replica gets a decode target picked
                # NOW (load-based, strictly decode-pool) so the replica
                # can push the prefix KV at first token and the router
                # can migrate the stream after it (docs/pd_pools.md)
                entry.pd_target = None
                if pd and pinned is None and not entry.pd_migrated \
                        and entry.replay_safe \
                        and entry.delivered_events == 0 \
                        and replica_role(rep) != "decode":
                    decs = [r for r in self.replicas.in_rotation()
                            if replica_role(r) == "decode"
                            and r.addr != rep.addr
                            and r.addr not in exclude]
                    if decs:
                        entry.pd_target = min(
                            decs,
                            key=lambda r: r.active_streams).addr
                entry.replica = rep.addr
                entry.attempts += 1
                with self._lock:
                    # handler threads race on this counter and a lost
                    # update would skew least-loaded placement forever
                    rep.active_streams += 1
                try:
                    outcome = self._stream_from(rep, entry, sse)
                    _M_REQS.inc(kind=kind, outcome=outcome)
                    return
                except PoolHandoff:
                    # the prefill replica delivered the first token (and
                    # pushed the prefix KV): migrate the stream to the
                    # decode pool via the same journaled continuation
                    # path a failover uses — one byte-identical client
                    # stream either way. NOT a failure: no breaker, no
                    # exclude, no failover budget charge.
                    entry.pd_migrated = True
                    if FAULTS.fire("pool_migrate_fail") \
                            or not entry.pd_target:
                        # chaos / lost target: fall back to normal
                        # placement — the continuation still resumes
                        # byte-identically, just not on the decode pool
                        _M_POOL_HANDOFFS.inc(outcome="fallback")
                        entry.pd_handoff_at = None
                        entry.pd_target = None
                        pinned = None
                    else:
                        pinned = entry.pd_target
                    continue
                except UpstreamFailed as e:
                    if e.replica_suspect:
                        # the poller (the breaker's single prober)
                        # decides whether this replica is really down
                        self.replicas.request_probe()
                    exclude.add(rep.addr)
                    last_failed = rep.addr
                    logger.warning("upstream %s failed for %s: %s",
                                   rep.addr, rid, e)
                    if entry.finished:
                        # the upstream died BETWEEN the finish chunk and
                        # [DONE]: the stream is complete — close it out;
                        # a continuation would re-finish and duplicate
                        try:
                            sse.done()
                        except ClientGone:
                            pass
                        _M_REQS.inc(kind=kind, outcome="ok")
                        return
                    give_up_why = str(e)
                    give_up_retry = e.retry_after
                    if entry.delivered_events > 0:
                        # a MID-STREAM migration attempt: charge the
                        # failover budget and check the safety veto
                        if entry.fail_detected_at is None:
                            entry.fail_detected_at = time.monotonic()
                        entry.migration_attempts += 1
                        if not entry.replay_safe:
                            _M_FAILOVERS.inc(outcome="unsafe")
                            give_up_why = (
                                "replica failed mid-stream and this "
                                "request is not replay-safe "
                                f"({entry.unsafe_reason})")
                            break
                        if entry.migration_attempts > self.max_failovers:
                            _M_FAILOVERS.inc(outcome="exhausted")
                            break
                    elif entry.attempts > max(
                            2 * len(self.replicas.replicas),
                            self.max_failovers + 1):
                        # nothing delivered yet: submit-time failures
                        # are free retries across the fleet, bounded
                        # only by this loop-termination backstop
                        break
                    continue
                except ClientGone:
                    _M_REQS.inc(kind=kind, outcome="client_gone")
                    return
                finally:
                    with self._lock:
                        rep.active_streams -= 1
            # give-up: terminal error to the client
            retry = give_up_retry if give_up_retry is not None \
                else self.replicas.min_retry_after()
            self._fail_client(entry, sse, give_up_why, retry)
        finally:
            self.journal.close(rid)
            _M_STREAMS.set(len(self.journal))
            with self._lock:
                self._conns.pop(rid, None)

    def _fail_client(self, entry: StreamEntry, sse, message: str,
                     retry_after: float) -> None:
        retry_after = max(1.0, float(retry_after))
        if not sse.started:
            _M_REQS.inc(kind=entry.kind, outcome="rejected")
            sse.fail_json(503, proto.error_response(message, 503),
                          {"Retry-After": str(int(round(retry_after)))})
            return
        _M_REQS.inc(kind=entry.kind, outcome="error")
        model = entry.body.get("model") or ""
        try:
            if entry.kind == "chat":
                sse.send(proto.chat_completion_chunk(
                    entry.rid, model, None, "error"))
            else:
                sse.send(proto.completion_chunk(
                    entry.rid, model, "", "error"))
            sse.send(proto.stream_error_event(message, "error",
                                              retry_after))
            sse.done()
        except ClientGone:
            pass

    # ---- one upstream attempt ---------------------------------------------

    def _path(self, kind: str) -> str:
        return ("/v1/chat/completions" if kind == "chat"
                else "/v1/completions")

    def _stream_from(self, rep, entry: StreamEntry, sse) -> str:
        """Run the stream against one replica until it FINISHES
        (returns the request outcome label) or fails (raises
        UpstreamFailed / ClientGone)."""
        body_up = dict(entry.body)
        body_up["stream"] = True
        if entry.replay_safe:
            ext: dict = {"request_id": entry.rid}
            cont = entry.continuation_payload()
            if cont is not None:
                ext["continuation"] = cont
            elif entry.pd_target:
                # fresh dispatch with a decode target armed: tell the
                # prefill replica where to push the prefix KV (the
                # target's prefix-store serve addr, not its HTTP addr)
                pa = self._push_addr(self.replicas.get(entry.pd_target))
                if pa:
                    ext["push_to"] = pa
            body_up["gllm_router"] = ext
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=self.stream_idle_timeout_s)
        with self._lock:
            self._conns[entry.rid] = conn
        try:
            try:
                conn.request("POST", self._path(entry.kind),
                             body=json.dumps(body_up).encode(),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
            except (OSError, http.client.HTTPException) as e:
                raise UpstreamFailed(f"submit to {rep.addr} failed: {e}")
            if resp.status != 200:
                raw = resp.read()
                try:
                    retry = float(resp.getheader("Retry-After") or 0)
                except (TypeError, ValueError):
                    retry = 0
                if resp.status in (429, 503):
                    raise UpstreamFailed(
                        f"{rep.addr} rejected admission "
                        f"({resp.status})", retry_after=retry or None,
                        replica_suspect=False)
                if entry.delivered_events:
                    raise UpstreamFailed(
                        f"{rep.addr} refused continuation "
                        f"({resp.status})")
                # a request-shaped error (400 …) is the client's to see
                try:
                    parsed = json.loads(raw)
                except ValueError:
                    parsed = proto.error_response(
                        raw.decode(errors="replace"), resp.status)
                sse.fail_json(resp.status, parsed, {})
                return "error"
            self._relay(rep, entry, resp, sse)
            return "ok"
        finally:
            with self._lock:
                self._conns.pop(entry.rid, None)
            try:
                conn.close()
            except OSError:
                pass

    def _relay(self, rep, entry: StreamEntry, resp, sse) -> None:
        pending_err: Optional[dict] = None
        for ev in self._iter_sse(resp, rep.addr):
            if ev is _DONE:
                if entry.finished:
                    sse.done()
                    return
                if pending_err is not None:
                    raise UpstreamFailed(
                        pending_err.get("message")
                        or "replica-side stream failure",
                        retry_after=pending_err.get("retry_after"))
                raise UpstreamFailed(
                    f"{rep.addr} closed the stream without a finish")
            if "choices" not in ev:
                g = ev.get("gllm")
                if g is not None:
                    # preamble: prompt ids + the replica's replay-safety
                    # verdict (the half only it can compute)
                    if entry.prompt_token_ids is None and \
                            g.get("prompt_token_ids") is not None:
                        entry.prompt_token_ids = [
                            int(t) for t in g["prompt_token_ids"]]
                    if entry.unsafe_reason is None \
                            and g.get("unsafe_reason"):
                        entry.unsafe_reason = g["unsafe_reason"]
                    entry.replica_identity = g.get("replica_id")
                    continue
                if "error" in ev:
                    if entry.finished:
                        # a terminal hint for an ALREADY-finished
                        # stream (deadline finishes carry retry_after):
                        # forward it — backoff-aware clients behind the
                        # router must see what direct clients see
                        sse.send(ev)
                        entry.delivered_events += 1
                        continue
                    # terminal error event (satellite: carries
                    # retry_after) — the [DONE] after it resolves
                    pending_err = ev["error"]
                    continue
                continue              # unknown control event: drop
            g = ev.pop("gllm", None)
            fin = (ev.get("choices") or [{}])[0].get("finish_reason")
            if fin in ("error", "abort"):
                # replica-side failure finish: hold it back — the
                # continuation replaces it; keep reading for the error
                # event so a retry_after hint is honored
                pending_err = {"message": f"upstream finish={fin}"}
                continue
            if entry.fail_detected_at is not None:
                # first chunk of a continuation: the migration worked
                entry.last_failover_s = (time.monotonic()
                                        - entry.fail_detected_at)
                entry.fail_detected_at = None
                entry.failovers += 1
                _M_FAILOVERS.inc(outcome="ok")
                _M_FAILOVER_S.observe(entry.last_failover_s)
                logger.warning(
                    "stream %s resumed on %s after %.3fs (%d tokens "
                    "committed)", entry.rid, rep.addr,
                    entry.last_failover_s, len(entry.committed))
            elif entry.pd_handoff_at is not None and entry.pd_migrated:
                # first chunk after a pd handoff: the stream now runs
                # on the decode pool (deliberately separate from the
                # failover metrics — a handoff is routine, not a fault)
                _M_POOL_HANDOFFS.inc(outcome="ok")
                _M_POOL_HANDOFF_S.observe(time.monotonic()
                                          - entry.pd_handoff_at)
                entry.pd_handoff_at = None
                logger.info(
                    "stream %s handed off to decode replica %s "
                    "(%d pages pushed, %d tokens committed)",
                    entry.rid, rep.addr, entry.pushed_pages,
                    len(entry.committed))
            sse.start()
            sse.send(ev)
            entry.delivered_events += 1
            if g is not None and g.get("token_id") is not None:
                entry.committed.append(int(g["token_id"]))
            delta = (ev.get("choices") or [{}])[0].get("delta")
            if isinstance(delta, dict):
                entry.committed_text_len += len(delta.get("content")
                                                or "")
            elif "text" in (ev.get("choices") or [{}])[0]:
                entry.committed_text_len += len(
                    ev["choices"][0].get("text") or "")
            if fin is not None:
                entry.finished = True
                entry.finish_reason = fin
            if g is not None and g.get("pushed_pages") is not None:
                entry.pushed_pages = int(g["pushed_pages"])
            if entry.pd_target and not entry.pd_migrated \
                    and fin is None and entry.replay_safe \
                    and entry.prompt_token_ids is not None \
                    and g is not None \
                    and g.get("token_id") is not None:
                # the first sampled token (and its piggybacked KV push)
                # has been forwarded: migrate to the decode pool. The
                # chunk is already committed, so the continuation
                # resumes right after it — byte-identical either way.
                entry.pd_handoff_at = time.monotonic()
                raise PoolHandoff()
        raise UpstreamFailed(f"{rep.addr} disconnected mid-stream")

    def _iter_sse(self, resp, addr: str):
        """Yield parsed SSE data events (dicts) and the _DONE sentinel;
        transport trouble (including the idle timeout) surfaces as
        UpstreamFailed. Client-side errors (ClientGone from sse.send)
        pass through untouched — they are raised by the CALLER's send,
        never in here."""
        while True:
            try:
                line = resp.readline()
            except OSError as e:
                raise UpstreamFailed(
                    f"{addr} read failed mid-stream: {e}")
            if not line:
                return                    # EOF
            line = line.strip()
            if not line or not line.startswith(b"data:"):
                continue
            payload = line[5:].strip()
            if payload == b"[DONE]":
                yield _DONE
                return
            try:
                yield json.loads(payload)
            except ValueError:
                raise UpstreamFailed(f"{addr} sent a garbled SSE event")


class _Done:
    pass


_DONE = _Done()
