"""Deterministic chaos SOAK (ISSUE 14 satellite; markers: soak + slow).

A multi-minute sustained run driving the three headline fault classes —
``engine_hard_crash`` (supervised in-process rebuild),
``disk_read_corrupt`` (prefix-tier poison-drop degradation), and
``peer_flap`` (per-peer circuit breaker) — under CONCURRENT traffic,
with acceptance on what production cares about:

- zero leaked KV pages and zero dangling handles/journal entries at
  the end of the run;
- every stream terminates (replayed byte-identical, or a terminal
  chunk — no hang);
- bounded recovery time per supervised rebuild;
- the engine ends /readyz-ready without a process restart.

Excluded from tier-1 (slow); run explicitly:

    pytest tests/test_soak_chaos.py -m soak

A guard asserts the marker discipline (soak ⇒ slow) so the suite can
never leak into tier-1.
"""

import threading
import time

import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.engine.serving_engine import ServingEngine
from gllm_tpu.faults import FAULTS
from gllm_tpu.sampling_params import SamplingParams

TINY = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    max_position_embeddings=512, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0, bos_token_id=1,
)


@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(11)
    model = LlamaForCausalLM(LlamaConfig(**TINY, attention_bias=False))
    d = tmp_path_factory.mktemp("soak_model")
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def test_soak_marker_discipline():
    """Tier-1 runs '-m not slow': a soak test without the slow marker
    would leak a multi-minute run into every CI pass."""
    import ast
    src = open(__file__).read()
    for node in ast.walk(ast.parse(src)):
        if not isinstance(node, ast.FunctionDef):
            continue
        decs = [ast.unparse(d) for d in node.decorator_list]
        if any("soak" in d for d in decs):
            assert any("slow" in d for d in decs), (
                f"{node.name} is soak-marked but not slow-marked")


@pytest.mark.soak
@pytest.mark.slow
def test_soak_sustained_chaos_under_traffic(tiny_ckpt, tmp_path):
    """~2 minutes of deterministic chaos: repeated engine hard crashes
    + disk-tier corruption + a flapping prefix peer, under concurrent
    greedy/seeded traffic."""
    cfg = EngineConfig(
        model=tiny_ckpt, dtype="float32", max_model_len=256,
        scheduler=SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=True,
                          kv_host_pool_pages=32,
                          kv_disk_path=str(tmp_path / "kvdisk"),
                          kv_disk_gb=0.5),
        engine_recovery=True, max_step_failures=2,
        rebuild_backoff_s=0.05, rebuild_backoff_max_s=0.5,
        max_rebuilds=5, rebuild_window_s=20.0)
    cfg.validate()
    llm = LLM(config=cfg)
    baseline_free = llm.memory_manager.allocator.num_free
    eng = ServingEngine(llm)

    # a flapping peer on the side: the breaker must hold its cost to
    # one probe per window while the serving plane churns
    from gllm_tpu.kvstore.peer import PrefixClient
    geometry = llm.prefix_tiers.geometry
    srv = llm.prefix_tiers.server or llm.prefix_tiers.start_server(
        host="127.0.0.1", port=0)
    peer = PrefixClient([f"127.0.0.1:{srv.port}"], geometry,
                        backoff_s=0.5, backoff_max_s=2.0,
                        fail_threshold=1, jitter=0.0)

    deadline = time.monotonic() + 110.0
    results = {"ok": 0, "dropped": 0, "hung": 0}
    res_lock = threading.Lock()
    stop = threading.Event()

    def client(idx):
        import numpy as np
        rng = np.random.default_rng(idx)
        while not stop.is_set() and time.monotonic() < deadline:
            prompt = rng.integers(1, 120, size=int(
                rng.integers(4, 24))).tolist()
            seeded = idx % 2 == 0
            sp = SamplingParams(
                temperature=0.8 if seeded else 0.0,
                seed=int(rng.integers(0, 1 << 30)) if seeded else None,
                max_tokens=int(rng.integers(8, 32)), ignore_eos=True)
            try:
                h = eng.submit(prompt, sp)
            except Exception:
                time.sleep(0.05)           # rejected while recovering
                continue
            got_terminal = False
            t0 = time.monotonic()
            for c in h:
                if c.finish_reason is not None:
                    got_terminal = True
                    with res_lock:
                        if c.finish_reason == "length":
                            results["ok"] += 1
                        else:
                            results["dropped"] += 1
                    break
                if time.monotonic() - t0 > 120:
                    break
            if not got_terminal:
                with res_lock:
                    results["hung"] += 1
                return

    workers = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(4)]
    for w in workers:
        w.start()

    crashes = 0
    digest = b"\x07" * 32
    while time.monotonic() < deadline:
        time.sleep(6.0)
        # one hard crash per window, plus tier corruption + peer flap
        FAULTS.arm("engine_hard_crash:0:1")
        FAULTS.arm("disk_read_corrupt:0:1")
        FAULTS.arm("peer_flap:0:1")
        peer.fetch(digest, list(range(8)))       # drives the breaker
        crashes += 1
        # wait for the recovery to complete before the next injection
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30.0:
            if eng.readiness() == (True, "ok") and \
                    FAULTS.hits.get("engine_hard_crash", 0) >= crashes:
                break
            time.sleep(0.1)
    stop.set()
    for w in workers:
        w.join(timeout=150)
        assert not w.is_alive(), "client thread hung"

    # drain: the engine must return to ready and idle
    limit = time.monotonic() + 60
    while time.monotonic() < limit and (
            eng.llm.has_unfinished or not eng.readiness()[0]):
        time.sleep(0.1)
    assert eng.readiness() == (True, "ok"), eng.health()
    assert results["hung"] == 0, results
    assert results["ok"] > 0, results
    # bounded recovery: every supervised rebuild completed promptly
    assert eng.supervisor.recoveries >= 1
    assert eng.supervisor.last_recovery_s is not None
    assert eng.supervisor.last_recovery_s < 30.0
    # zero leaks: pages all free on the CURRENT llm, no dangling
    # handles/journal entries/pending replays
    llm_now = eng.llm
    t0 = time.monotonic()
    while time.monotonic() - t0 < 30 and \
            llm_now.memory_manager.allocator.num_free != baseline_free:
        time.sleep(0.1)
    assert llm_now.memory_manager.allocator.num_free == baseline_free
    assert not eng._handles and not eng._pending_replay
    assert len(eng._journal) == 0
    # the flapped peer is breaker-accounted, never a stall
    health = peer.peer_health()[f"127.0.0.1:{srv.port}"]
    assert health["opens"] >= 1
    peer.close()
    eng.shutdown()
