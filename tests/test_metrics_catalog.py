"""Metrics-catalog guard: the code and docs/observability.md cannot
drift.

Every ``gllm_*`` metric registered anywhere under ``gllm_tpu/`` (via the
``obs.counter/gauge/histogram`` helpers) must have a row in
docs/observability.md, and every ``gllm_*`` name the doc mentions must
be a registered metric (or a histogram's derived ``_bucket``/``_sum``/
``_count`` sample, or a documented-retired alias) — so a new subsystem
can't ship undocumented metrics and the doc can't advertise ghosts.

Registration sites are found by source scan rather than imports: it
covers modules that only load under flags/topologies CI never runs
(pp_runner, disagg, the kvstore tiers), and it needs no jax.
"""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "gllm_tpu")
DOC = os.path.join(REPO, "docs", "observability.md")

# obs.counter( / metrics.gauge( / histogram( ... "gllm_..." — the name
# is always the first (string-literal) argument.
_REG_RE = re.compile(
    r"\b(?:counter|gauge|histogram)\(\s*\n?\s*['\"](gllm_[a-z0-9_]+)['\"]",
    re.MULTILINE)
_DOC_RE = re.compile(r"\bgllm_[a-z0-9_]+")

# Histogram sample suffixes the doc legitimately shows as full series
# names in PromQL recipes / examples.
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _registered_names():
    names = {}
    for root, _, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            src = open(path).read()
            for m in _REG_RE.finditer(src):
                names.setdefault(m.group(1), path)
    return names


def test_every_registered_metric_is_documented():
    registered = _registered_names()
    assert registered, "source scan found no metric registrations"
    doc = open(DOC).read()
    missing = sorted(n for n in registered if n not in doc)
    assert not missing, (
        "metrics registered in gllm_tpu/ but absent from "
        "docs/observability.md (add a catalog row): "
        + ", ".join(f"{n} ({os.path.relpath(registered[n], REPO)})"
                    for n in missing))


def test_every_documented_metric_is_registered():
    registered = set(_registered_names())
    doc = open(DOC).read()
    ghosts = []
    for name in sorted(set(_DOC_RE.findall(doc))):
        if name == "gllm_tpu":           # the package name, not a metric
            continue
        if name in registered:
            continue
        if any(name.endswith(s) and name[:-len(s)] in registered
               for s in _HIST_SUFFIXES):
            continue
        if any(r.startswith(name) for r in registered):
            continue                     # grep-prefix in a shell recipe
        ghosts.append(name)
    assert not ghosts, (
        "docs/observability.md mentions gllm_* names no code registers "
        "(typo or removed metric — fix the doc): " + ", ".join(ghosts))
