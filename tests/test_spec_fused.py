"""Fused on-device speculation (--spec-fused, ISSUE 13).

Contract (docs/speculative_decoding.md#fused): draft+verify run INSIDE
the chained multi-step dispatch — the runner drafts from a device-
resident recent-token ring, verifies q_len=k+1 rows in-loop, and one
dispatch emits up to K·(spec_k+1) tokens. Greedy token streams are
byte-identical to host-driven spec decode AND to plain decode (both by
the argmax-verification argument); sampled rows keep the rejection-
sampling distribution guarantee. schedule_chain accepts spec rows, so
the chain_breaks reason="spec" class is retired (asserted zero), and
dispatches-per-token lands strictly below BOTH host-driven spec and
non-spec chained decode on a draft-friendly workload.
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.obs.steptrace import TRACE, summarize
from gllm_tpu.sampling_params import SamplingParams

# Greedy models on random weights loop quickly → the draft-friendly
# regime; one structureless prompt exercises cold proposals too.
PROMPTS = [
    [5, 9, 23, 5, 9, 23, 5, 9],
    [7, 7, 7, 7],
    list(range(1, 30)),
    [101, 3, 101, 3, 101],
]

TINY = ModelConfig(architecture="LlamaForCausalLM", vocab_size=128,
                   hidden_size=64, num_layers=2, num_heads=4,
                   num_kv_heads=2, head_dim=16, intermediate_size=96,
                   max_position=512, eos_token_id=0)


def mk(ckpt=None, *, num_pages=128, kv_dtype="auto", **kw):
    cfg = EngineConfig(
        model=ckpt or "", load_format="auto" if ckpt else "dummy",
        dtype="float32", max_model_len=256,
        cache=CacheConfig(page_size=4, num_pages=num_pages,
                          kv_cache_dtype=kv_dtype), **kw)
    if ckpt:
        return LLM(config=cfg)
    return LLM(config=cfg, model_cfg=TINY)


FUSED = dict(spec_decode="ngram", spec_k=4, spec_ngram=2, spec_fused=True,
             multi_step_decode=4)


def run(llm, n=24, prompts=PROMPTS, **spkw):
    spkw.setdefault("ignore_eos", True)
    spkw.setdefault("temperature", 0.0)
    outs = llm.generate(prompt_token_ids=[list(p) for p in prompts],
                        sampling_params=SamplingParams(max_tokens=n,
                                                       **spkw))
    return [(o.output_token_ids, o.finish_reason) for o in outs]


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(7)
    d = str(tmp_path_factory.mktemp("tiny_spec_fused"))
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=512, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    return d


# ---- device proposer / ring units ------------------------------------------

def test_ngram_propose_matches_host_proposer():
    """The on-device sliding-window proposer is EXACT against the host
    proposer over the same window, for every (n, k) and ring fill."""
    import jax.numpy as jnp
    from gllm_tpu.ops.sampling import ngram_propose
    from gllm_tpu.scheduler import propose_ngram_drafts
    R = 32
    rng = np.random.default_rng(0)
    cases = [[5, 6, 7, 8, 5, 6], [1, 2, 3, 4], [5, 6, 9, 5, 6, 1, 5, 6],
             [7] * 5, list(rng.integers(0, 9, size=40)), [5, 9] * 20, [3]]
    for toks in cases:
        toks = [int(t) for t in toks]
        tail = toks[-R:]
        ring = np.full((1, R), -1, np.int32)
        ring[0, R - len(tail):] = tail
        rlen = np.asarray([len(tail)], np.int32)
        for n in (1, 2, 3):
            for k in (1, 3, 4):
                dev = ngram_propose(jnp.asarray(ring), jnp.asarray(rlen),
                                    n=n, k=k)
                dev = tuple(int(t) for t in np.asarray(dev)[0] if t >= 0)
                assert dev == propose_ngram_drafts(tail, n, k), \
                    (toks, n, k)


def test_ring_shift_in_variable_counts():
    import jax.numpy as jnp
    from gllm_tpu.ops.sampling import ring_shift_in
    ring = jnp.asarray(np.full((2, 8), -1, np.int32))
    rlen = jnp.zeros(2, jnp.int32)
    ring, rlen = ring_shift_in(ring, rlen,
                               jnp.asarray([[1, 2, 3], [4, 5, 6]]),
                               jnp.asarray([2, 0]))
    ring = np.asarray(ring)
    assert list(ring[0][-2:]) == [1, 2] and int(np.asarray(rlen)[0]) == 2
    # count 0 is the identity (the chain-splice trick)
    assert int(np.asarray(rlen)[1]) == 0 and ring[1][-1] == -1
    # rollover: a full ring keeps only the newest R tokens
    r2 = jnp.asarray(np.arange(8, dtype=np.int32)[None, :])
    l2 = jnp.asarray([8], jnp.int32)
    r2, l2 = ring_shift_in(r2, l2, jnp.asarray([[9, 10]]),
                           jnp.asarray([2]))
    assert list(np.asarray(r2)[0]) == [2, 3, 4, 5, 6, 7, 9, 10]
    assert int(np.asarray(l2)[0]) == 8


# ---- e2e: identity + the dispatch headline ---------------------------------

def test_fused_byte_identity_and_dispatch_drop(ckpt):
    """The acceptance headline: greedy streams byte-identical to plain
    decode, to host-driven spec, and to non-spec chained decode — while
    dispatches-per-token lands STRICTLY below both host-driven spec and
    the non-spec chain on a draft-friendly workload, with zero
    chain_breaks{reason='spec'} (the retired class)."""
    base = mk(ckpt)
    want = [t for t, _ in run(base, n=32)]
    tokens = sum(len(t) for t in want)
    del base

    host = mk(ckpt, spec_decode="ngram", spec_k=4, spec_ngram=2,
              overlap_scheduling=True, multi_step_decode=4)
    assert [t for t, _ in run(host, n=32)] == want
    host_dpt = host.runner.num_dispatches / tokens
    del host

    chain = mk(ckpt, overlap_scheduling=True, multi_step_decode=4,
               decode_slot_batching=True, ondevice_finish=True)
    assert [t for t, _ in run(chain, n=32)] == want
    chain_dpt = chain.runner.num_dispatches / tokens
    del chain

    mark = TRACE.mark()
    fused = mk(ckpt, **{**FUSED, "decode_chain_len": 4},
               decode_slot_batching=True, ondevice_finish=True)
    assert [t for t, _ in run(fused, n=32)] == want
    fused_dpt = fused.runner.num_dispatches / tokens
    summ = summarize(TRACE.events(since=mark))
    assert (summ.get("chain_breaks_by_reason") or {}).get("spec", 0) == 0, \
        "retired reason='spec' break fired under --spec-fused"
    st = fused.scheduler.spec_stats
    assert st["proposed"] > 0 and st["accepted"] > 0
    assert fused_dpt < host_dpt, (fused_dpt, host_dpt)
    assert fused_dpt < chain_dpt, (fused_dpt, chain_dpt)
    # window observability: acceptance + amortization land in summarize
    assert summ.get("spec_accept_rate") is not None
    assert summ.get("tokens_per_dispatch") > 1.0


def test_fused_eos_and_length_identity(ckpt):
    """EOS inside an accepted run and max-token caps truncate exactly
    like the plain engine (finish reasons included)."""
    base = mk(ckpt)
    want = run(base, n=19, ignore_eos=False)
    del base
    fused = mk(ckpt, **FUSED, ondevice_finish=True,
               decode_slot_batching=True)
    assert run(fused, n=19, ignore_eos=False) == want


# ---- composition matrix ----------------------------------------------------

@pytest.mark.parametrize("flags", [
    dict(),
    dict(ondevice_finish=True),
    dict(decode_slot_batching=True),
    dict(ondevice_finish=True, decode_slot_batching=True),
    dict(pipelined_loop=True, decode_slot_batching=True,
         ondevice_finish=True),
    dict(unified_step=True, decode_slot_batching=True,
         ondevice_finish=True),
], ids=["plain", "odf", "slots", "odf_slots", "pipelined", "unified"])
def test_fused_composition_matrix(flags):
    """spec_fused × {ondevice_finish, decode_slot_batching,
    pipelined_loop, unified_step}: greedy byte-identity to the plain
    engine, including EOS, stop-token + min_tokens arming, and the
    max_model_len boundary."""
    base = mk()
    want = run(base)
    want_eos = run(base, n=19, ignore_eos=False)
    want_stop = run(base, stop_token_ids=[44, 17], min_tokens=6,
                    ignore_eos=False)
    longp = ([11, 13] * 120)[:238]
    want_len = run(base, n=64, prompts=[longp])
    del base
    llm = mk(**FUSED, **flags)
    assert run(llm) == want
    assert run(llm, n=19, ignore_eos=False) == want_eos
    assert run(llm, stop_token_ids=[44, 17], min_tokens=6,
               ignore_eos=False) == want_stop
    assert run(llm, n=64, prompts=[longp]) == want_len


def test_fused_int8_kv_composes():
    """spec_fused × int8 KV cache: the quantizing write path serves the
    in-loop verify rows; the run completes with full emission (int8
    numerics are agreement-bounded, not byte-identical — the
    kv_quantization contract)."""
    llm = mk(kv_dtype="int8", **FUSED, ondevice_finish=True)
    got = run(llm)
    assert sum(len(t) for t, _ in got) == len(PROMPTS) * 24
    assert all(r == "length" for _, r in got)
    assert llm.scheduler.spec_stats["proposed"] > 0


def test_fused_preemption_churn_identity():
    """A tiny KV pool forces preemption churn mid-chain; re-admitted
    sequences re-seed their ring from committed tokens and stay
    byte-identical."""
    base = mk(num_pages=28)
    want = run(base)
    del base
    llm = mk(num_pages=28, **FUSED, decode_slot_batching=True,
             ondevice_finish=True)
    assert run(llm) == want


def test_fused_arrival_churn_joins_identity():
    """Staggered arrivals under slots + pipelined loop: joins re-seed
    host-known ring rows mid-chain, finishes become holes, and streams
    stay byte-identical — with zero retired-class breaks."""
    def churn(**kw):
        cfg = EngineConfig(
            load_format="dummy", dtype="float32", max_model_len=256,
            scheduler=SchedulerConfig(max_prefill_tokens=64,
                                      max_decode_seqs=8),
            cache=CacheConfig(page_size=4, num_pages=256), **kw)
        llm = LLM(config=cfg, model_cfg=TINY)
        arrivals = {0: 2, 2: 2, 5: 2, 9: 2, 14: 1}
        seqs, nseq, it = [], 0, 0
        while nseq < 9 or llm.has_unfinished:
            for _ in range(arrivals.get(it, 0)):
                ids = [5, 9] * (3 + nseq % 4)
                s = llm._allocate_seq(list(ids), SamplingParams(
                    temperature=0.0, ignore_eos=(nseq % 3 != 0),
                    max_tokens=12 + 4 * (nseq % 5)))
                llm.add_seq(s)
                seqs.append(s)
                nseq += 1
            llm.step()
            it += 1
            assert it < 3000, "churn wedged"
        return [(s.output_token_ids, s.finish_reason) for s in seqs]

    want = churn()
    mark = TRACE.mark()
    got = churn(**FUSED, decode_slot_batching=True, ondevice_finish=True,
                pipelined_loop=True)
    assert got == want
    breaks = summarize(TRACE.events(since=mark)).get(
        "chain_breaks_by_reason") or {}
    assert breaks.get("spec", 0) == 0


# ---- sampled rows ----------------------------------------------------------

def test_fused_seeded_deterministic():
    """Seeded sampled rows draw from fold_in(seed, out_step) — the fused
    run is reproducible run-to-run (realization differs from the
    non-spec engine by contract; the distribution oracle is below)."""
    a = run(mk(**FUSED), temperature=0.9, seed=11)
    b = run(mk(**FUSED), temperature=0.9, seed=11)
    assert a == b


def test_fused_sampled_distribution_preserved(ckpt):
    """The distribution-preservation oracle against the PLAIN engine:
    fused rejection sampling against the on-device one-hot proposal
    keeps the target distribution (tolerance derived from the run count
    — see test_spec_decode._l1_tolerance)."""
    from tests.test_spec_decode import _l1_tolerance, _spec_distribution_l1
    # roomy pool: spec chains allocate worst-case (k+1)-token strides,
    # and a tight pool breaks them with reason='pages' (sync decode
    # doesn't draft under the fused flag — speculation would sit out)
    llm = mk(ckpt, num_pages=512, **FUSED)
    base = mk(ckpt)
    l1, support, total, hists = _spec_distribution_l1(llm, base, 40, 6)
    assert llm.scheduler.spec_stats["proposed"] > 0
    tol = _l1_tolerance(support, total)
    assert l1 < tol, f"L1 {l1:.3f} >= tol {tol:.3f} ({hists})"


# ---- promise bookkeeping ---------------------------------------------------

def test_futuremap_trims_exactly_the_overpromise():
    """A spec block promised worst-case frontiers; at collect the actual
    counts are known — FutureMap.trim_overpromise rebases in-flight
    descendants by EXACTLY the over-promised token count, keeping later
    entries' schedule-relative strides (an upper bound of their own
    parent) instead of collapsing them onto the committed frontier."""
    from gllm_tpu.engine.pipeline import FutureMap, InFlight
    from gllm_tpu.scheduler import ScheduledBatch, ScheduledSeq
    from gllm_tpu.sequence import Sequence

    seq = Sequence(0, [1, 2, 3], SamplingParams(max_tokens=64))
    mult = 5                              # spec_k + 1
    # block A (collected): scheduled off frontier 10 with K=2 links
    # promising up to 2*mult tokens; it actually committed 4.
    seq.num_computed_tokens = 14          # 10 + 4 committed
    # block B in flight: scheduled off A's upper bound 10 + 2*mult = 20
    b_links = [ScheduledBatch([ScheduledSeq(seq, 1, 20 + j * mult)],
                              spec_block=True) for j in range(2)]
    # block C chained off B's upper bound 20 + 2*mult = 30
    c_links = [ScheduledBatch([ScheduledSeq(seq, 1, 30 + j * mult)],
                              spec_block=True) for j in range(2)]
    inflight = [InFlight(b_links, None, 0.0, None, chained=True),
                InFlight(c_links, None, 0.0, None, chained=True)]
    trimmed = FutureMap.trim_overpromise(
        inflight, {0: seq.num_computed_tokens})
    # over-promise accrued ONCE: B's base 20 vs committed 14 → 6 tokens
    assert trimmed == 6
    assert [it.computed_before for b in b_links for it in b.items] \
        == [14, 19]
    # C rebases by the SAME delta (stride relative to B preserved)
    assert [it.computed_before for b in c_links for it in b.items] \
        == [24, 29]
    # idempotent w.r.t. already-valid entries: nothing left to trim
    assert FutureMap.trim_overpromise(inflight, {0: 14}) == 0


# ---- gating / flags --------------------------------------------------------

def test_spec_fused_requires_ngram():
    with pytest.raises(ValueError, match="spec_decode"):
        EngineConfig(load_format="dummy", spec_fused=True).validate()


def test_spec_fused_lifts_overlap_and_chain_len():
    cfg = EngineConfig(load_format="dummy", spec_decode="ngram",
                       spec_fused=True)
    cfg.validate()
    assert cfg.overlap_scheduling and cfg.multi_step_decode > 1


def test_spec_fused_unsupported_topologies_error_loudly():
    """Flags never silently no-op (ISSUE 20): spec_fused × pp>1 and
    × dp>1 are genuinely unsupported (the fused block is ONE device
    program — it can span neither stage programs nor the stacked
    replica carry), so config.validate() refuses with a per-combination
    ValueError instead of the retired warn-and-clear path."""
    from gllm_tpu.config import ParallelConfig
    for par, pat in ((ParallelConfig(pp=2), "pp > 1"),
                     (ParallelConfig(dp=2), "dp > 1")):
        cfg = EngineConfig(load_format="dummy", spec_decode="ngram",
                           spec_fused=True, parallel=par)
        with pytest.raises(ValueError, match=pat):
            cfg.validate()


def test_fast_paths_refuse_pp_times_dp():
    """unified_step / pipelined_loop compose with pp OR dp, not the
    combined grid — per-combination error, not a silent legacy
    fallback."""
    from gllm_tpu.config import ParallelConfig
    for kw in (dict(unified_step=True), dict(pipelined_loop=True)):
        cfg = EngineConfig(load_format="dummy",
                           parallel=ParallelConfig(pp=2, dp=2), **kw)
        with pytest.raises(ValueError, match="pp>1 OR\\s+dp>1"):
            cfg.validate()


def test_spec_fused_hybrid_model_errors_in_engine():
    """spec_fused × hybrid GDN is the model-level genuinely-incompatible
    case: the engine refuses with a ValueError (the SSM state cannot
    replay a discarded block) instead of warning and running host-driven
    speculation under a flag that claims otherwise."""
    hybrid = ModelConfig(
        architecture="Qwen3NextForCausalLM", vocab_size=128,
        hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, intermediate_size=96, max_position=512,
        eos_token_id=0,
        layer_types=("linear_attention", "full_attention"),
        linear_num_value_heads=4, linear_num_key_heads=2,
        linear_key_head_dim=8, linear_value_head_dim=8)
    cfg = EngineConfig(
        model="", load_format="dummy", dtype="float32",
        max_model_len=256, cache=CacheConfig(page_size=4, num_pages=64),
        **FUSED)
    with pytest.raises(ValueError, match="hybrid"):
        LLM(config=cfg, model_cfg=hybrid)


def test_spec_fused_enforce_eager_clears():
    cfg = EngineConfig(load_format="dummy", spec_decode="ngram",
                       spec_fused=True, enforce_eager=True)
    cfg.validate()
    assert not cfg.spec_fused and not cfg.overlap_scheduling


def test_fused_flag_off_is_host_driven_legacy():
    """spec_fused=False with spec on: host drafting still proposes (the
    pre-flag engine, byte for byte — the retired break class fires as
    before under overlap)."""
    llm = mk(spec_decode="ngram", spec_k=4, spec_ngram=2)
    got = run(llm)
    assert llm.scheduler.spec_stats["proposed"] > 0
    base = mk()
    assert got == run(base)


def test_fused_metrics_counter_moves():
    from gllm_tpu.obs import metrics as obs
    m = obs.REGISTRY.get("gllm_spec_fused_tokens_total")
    before = sum(m.get(kind=k) for k in ("accepted", "rejected",
                                         "correction"))
    llm = mk(**FUSED)
    run(llm)
    after = sum(m.get(kind=k) for k in ("accepted", "rejected",
                                        "correction"))
    assert after > before


# ---- quarantine under spec-fused chains (ISSUE 14 satellite) ---------------

@pytest.mark.chaos
def test_chaos_step_failure_inside_spec_fused_chain_unwinds_clean():
    """A step exception while a --spec-fused multi-step block is in
    flight: quarantine must unwind the FutureMap in-flight entries AND
    the per-slot spec ring state (the ring rides the handle aux — a
    cleared entry must never splice into the next chain) without
    leaking a page, and a fresh run on the same engine must be
    byte-identical to a clean engine's."""
    from gllm_tpu import faults
    llm = mk(num_pages=64, **FUSED, decode_slot_batching=True,
             ondevice_finish=True, pipelined_loop=True)
    baseline = llm.memory_manager.allocator.num_free
    want = run(mk(num_pages=64))
    for p in PROMPTS:
        llm.add_seq(llm._allocate_seq(list(p), SamplingParams(
            temperature=0.0, max_tokens=24, ignore_eos=True)))
    # let spec chains form and run ahead, then poison one step
    for _ in range(3):
        llm.step()
    assert llm._in_flight, "no spec chain in flight — test is inert"
    faults.FAULTS.arm("step_exception:0:1")
    try:
        with pytest.raises(faults.InjectedFault):
            for _ in range(80):
                llm.step()
    finally:
        faults.FAULTS.reset()
    dropped = llm.quarantine_step_failure()
    assert dropped
    # FutureMap in-flight entries unwound, chain/spec carry cleared
    assert not llm._in_flight and llm._chain_tip is None
    assert not llm.has_unfinished
    # zero leaked pages (slot holes, verify-row strides, spec
    # over-promise headroom all returned)
    assert llm.memory_manager.allocator.num_free == baseline
    # the SAME engine serves a fresh workload byte-identically — the
    # per-slot recent-token ring re-seeds from committed tokens at the
    # next chain root, never from the quarantined block's carry
    assert run(llm) == want
