"""Scorer/parser units of the offline eval harnesses (reference
benchmarks/evaluate_bfcl.py + evaluate_mmmu.py drivers)."""

import importlib.util
import os

import pytest


def _load(name):
    path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                        name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bfcl = _load("evaluate_bfcl")
mmmu = _load("evaluate_mmmu")


def test_parse_prompt_calls():
    calls = bfcl.parse_prompt_calls(
        "Sure: [get_weather(city='Paris', days=3), noop()]")
    assert calls == [("get_weather", {"city": "Paris", "days": 3}),
                     ("noop", {})]
    assert bfcl.parse_prompt_calls("no calls here") == []
    assert bfcl.parse_prompt_calls("[broken(") == []


def test_parse_native_calls():
    msg = {"tool_calls": [{"function": {
        "name": "f", "arguments": "{\"x\": 1}"}}]}
    assert bfcl.parse_native_calls(msg) == [("f", {"x": 1})]


@pytest.mark.parametrize("calls,expect,irr,want", [
    ([("f", {"a": 1})],
     [{"name": "f", "args": {"a": [1, 2]}, "required": ["a"]}], False, True),
    ([("f", {"a": 3})],
     [{"name": "f", "args": {"a": [1, 2]}, "required": ["a"]}], False, False),
    ([("f", {})],                                   # missing required
     [{"name": "f", "args": {"a": [1]}, "required": ["a"]}], False, False),
    ([("f", {})],                                   # "" ⇒ omittable
     [{"name": "f", "args": {"a": [1, ""]}, "required": ["a"]}], False, True),
    ([("f", {"a": 1, "z": 9})],                     # undeclared arg
     [{"name": "f", "args": {"a": [1]}, "required": ["a"]}], False, False),
    ([], [], True, True),                           # irrelevance detection
    ([("f", {})], [], True, False),
    ([("f", {"a": "PARIS"})],                       # case-folded strings
     [{"name": "f", "args": {"a": ["Paris"]}, "required": ["a"]}],
     False, True),
    ([("g", {"b": 2}), ("f", {"a": 1})],            # order-free parallel
     [{"name": "f", "args": {"a": [1]}, "required": ["a"]},
      {"name": "g", "args": {"b": [2]}, "required": ["b"]}], False, True),
])
def test_bfcl_score(calls, expect, irr, want):
    assert bfcl.score(calls, expect, irr) is want


def test_mmmu_choice_extraction():
    assert mmmu.extract_choice("The answer is B.") == "B"
    assert mmmu.extract_choice(" c") == "C"
    assert mmmu.extract_choice("unclear") is None


def test_parse_prompt_calls_with_leading_prose_brackets():
    calls = bfcl.parse_prompt_calls(
        "[Note] I'll call it now: [get_weather(city='Paris')]")
    assert calls == [("get_weather", {"city": "Paris"})]


def test_extract_choice_ignores_english_words():
    assert mmmu.extract_choice("I think the answer is B") == "B"
    assert mmmu.extract_choice("I cannot see the image") is None
    assert mmmu.extract_choice("A") == "A"
    assert mmmu.extract_choice("(C) because ...") == "C"


def test_extract_choice_a_and_i_phrasings():
    assert mmmu.extract_choice("Option A.") == "A"
    assert mmmu.extract_choice("A is correct") == "A"
    assert mmmu.extract_choice("I would say B") == "B"  # answer-ish verb,
    # but B is the standalone choice mentioned
    assert mmmu.extract_choice("choice (I)") == "I"
