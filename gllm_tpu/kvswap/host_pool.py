"""Host-RAM KV page pool.

A numpy mirror of the device paged-KV layout: device leaf ``[L, P,
page_size, ...]`` maps to a host store ``[H, L, page_size, ...]`` per
leaf, where one host page holds ALL layers of one device page — the
natural transfer unit (a sequence swap moves whole pages; the per-layer
axis rides along in one gather/scatter).

Two tenant classes share the pool:

- **sequence pages** (swap-based preemption): pinned for the life of the
  swapped-out sequence; freed on resume or abort. Never evicted.
- **prefix pages** (HBM prefix-cache spill): keyed by the same chained
  hash digests as ``PrefixMemoryManager`` with the same 8-token canary
  guard, LRU-evictable whenever unpinned. A canary mismatch on probe is
  treated as a miss and the poisoned entry dropped — the host tier can
  serve stale/garbage data to nobody.

With a tier below (gllm_tpu/kvstore), LRU eviction DEMOTES instead of
discarding: ``on_evict`` receives the evicted page's metadata + a copy
of its bytes and writes it to the disk tier. Prefix metadata carries the
chain-parent digest so the lower tiers can read descendants ahead.

Pure host bookkeeping — no jax imports; device transfers live in
``kvswap/engine.py``. ``lock`` serializes the prefix maps and page
bytes against the peer-serving thread (``export_prefix``); the engine
thread is the only mutator, so its own paths never contend.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from gllm_tpu.obs import metrics as obs

# The host tier verifies with the SAME collision guard as the device
# prefix cache — one constant, so the two can never drift apart and
# silently miss (or under-verify) on every probe.
from gllm_tpu.memory_manager import _CANARY_TOKENS as CANARY_TOKENS

_M_EVICT = obs.counter(
    "gllm_kvswap_prefix_evictions_total",
    "host-tier prefix pages evicted by the LRU (demoted to the disk "
    "tier when one is configured, discarded otherwise)")


class HostKVPool:
    def __init__(self, page_shapes: Sequence[Tuple[tuple, object]],
                 num_pages: int):
        """``page_shapes``: one ``(shape, dtype)`` per paged KV leaf,
        where ``shape`` is the per-page slab ``(L, page_size, *tail)``."""
        if num_pages < 1:
            raise ValueError("host pool needs at least one page")
        self.num_pages = num_pages
        self.page_shapes = [(tuple(s), np.dtype(d)) for s, d in page_shapes]
        # Lazily-touched backing store: np.zeros is virtual until written,
        # so an oversized pool costs address space, not resident RAM.
        self.store: List[np.ndarray] = [
            np.zeros((num_pages,) + s, d) for s, d in self.page_shapes]
        self._free: OrderedDict[int, None] = OrderedDict(
            (i, None) for i in range(num_pages))
        self._pins: Dict[int, int] = {}
        # Prefix tier (mirrors PrefixMemoryManager's maps; meta is
        # (digest, canary, chain-parent digest or None)).
        self.hash_to_page: Dict[bytes, int] = {}
        self.page_meta: Dict[int, Tuple[bytes, Tuple[int, ...],
                                        Optional[bytes]]] = {}
        # Unpinned prefix pages in recency order (oldest first) —
        # the eviction frontier.
        self._lru: OrderedDict[int, None] = OrderedDict()
        # Serializes prefix maps + page bytes against the peer-serving
        # thread; reentrant because free/allocate call drop_prefix.
        self.lock = threading.RLock()
        # Demotion hook (gllm_tpu/kvstore.TieredPrefixManager): called
        # with (digest, canary, parent, leaf copies) as an evicted page
        # leaves this tier. None keeps legacy discard-on-evict.
        self.on_evict: Optional[Callable] = None

    # ---- sizing -----------------------------------------------------------

    @property
    def bytes_per_page(self) -> int:
        return sum(int(np.prod(s)) * d.itemsize for s, d in self.page_shapes)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_pages - len(self._free)

    # ---- allocation / eviction -------------------------------------------

    def allocate(self, n: int) -> Optional[List[int]]:
        """``n`` host pages, LRU-evicting unpinned prefix pages to make
        room; None (nothing changed) when even eviction can't cover."""
        if n <= 0:
            return []
        can_evict = sum(1 for p in self._lru if not self._pins.get(p))
        if len(self._free) + can_evict < n:
            return None
        while len(self._free) < n:
            self._evict_one()
        out = []
        for _ in range(n):
            page, _ = self._free.popitem(last=False)
            out.append(page)
        return out

    def _evict_one(self) -> None:
        for page in self._lru:
            if not self._pins.get(page):
                demote = None
                with self.lock:
                    del self._lru[page]
                    meta = self.page_meta.get(page)
                    if (meta is not None and self.on_evict is not None
                            and self.hash_to_page.get(meta[0]) == page):
                        # copy before the slot is re-tenanted; the hook
                        # itself (serialization + file I/O scheduling)
                        # runs AFTER the lock drops so the peer-serving
                        # thread is never blocked on a demotion. An
                        # evictable page is never in-flight (spills pin
                        # until the gather lands), so the bytes are
                        # real.
                        demote = meta + ([s[page].copy()
                                          for s in self.store],)
                    self.drop_prefix(page)
                    self._free[page] = None
                if demote is not None:
                    digest, canary, parent, leaves = demote
                    self.on_evict(digest, canary, parent, leaves)
                _M_EVICT.inc()
                return
        raise RuntimeError("no evictable host page")  # guarded by caller

    def free(self, pages) -> None:
        with self.lock:
            for page in pages:
                if page in self._free:
                    raise RuntimeError(
                        f"double free of host page {page}")
                self._pins.pop(page, None)
                self._lru.pop(page, None)
                self.drop_prefix(page)
                self._free[page] = None

    def pin(self, pages) -> None:
        """In-flight / ownership guard: pinned pages are never evicted
        (and the manager defers their free until the transfer lands)."""
        for page in pages:
            self._pins[page] = self._pins.get(page, 0) + 1

    def unpin(self, pages) -> None:
        for page in pages:
            left = self._pins.get(page, 0) - 1
            if left > 0:
                self._pins[page] = left
            else:
                self._pins.pop(page, None)

    def is_pinned(self, page: int) -> bool:
        return bool(self._pins.get(page))

    # ---- page data --------------------------------------------------------

    def write_page(self, page: int, gathered: Sequence[np.ndarray],
                   col: int) -> None:
        """Store column ``col`` of a gathered batch (leaves
        ``[L, n, page_size, ...]``) as host page ``page``."""
        with self.lock:
            for store, src in zip(self.store, gathered):
                store[page] = src[:, col]

    def read_pages(self, pages: Sequence[int],
                   pad_to: Optional[int] = None) -> List[np.ndarray]:
        """Stack host pages into scatter-shaped leaves
        ``[L, n(_pad), page_size, ...]``; padding columns are zeros (they
        scatter into the dummy page)."""
        n = len(pages)
        idx = list(pages) + [0] * (max(pad_to or n, n) - n)
        out = []
        for store in self.store:
            stacked = np.moveaxis(store[np.asarray(idx, np.int64)], 0, 1)
            if len(idx) > n:
                stacked = stacked.copy()
                stacked[:, n:] = 0
            out.append(stacked)
        return out

    # ---- prefix tier ------------------------------------------------------

    def put_prefix(self, page: int, digest: bytes,
                   canary: Tuple[int, ...],
                   parent: Optional[bytes] = None) -> None:
        from gllm_tpu.faults import FAULTS
        if FAULTS.fire("host_canary_corrupt"):
            # chaos point (docs/robustness.md): store a poisoned canary —
            # the next match_prefix probe must detect it and miss rather
            # than serve this page
            canary = tuple(int(c) + 1 for c in canary)
        with self.lock:
            old = self.hash_to_page.get(digest)
            if old is not None and old != page:
                # newer copy wins; the old page keeps its data but loses
                # the key (it will age out of the LRU)
                self.page_meta.pop(old, None)
            self.hash_to_page[digest] = page
            self.page_meta[page] = (digest, tuple(canary), parent)
            self._lru[page] = None
            self._lru.move_to_end(page)

    def match_prefix(self, digest: bytes, tokens) -> Optional[int]:
        """Host page for this chained digest, canary-verified; a mismatch
        (hash collision / corruption) drops the entry and misses."""
        with self.lock:
            page = self.hash_to_page.get(digest)
            if page is None:
                return None
            _, canary, _ = self.page_meta[page]
            if tuple(tokens[:CANARY_TOKENS]) != canary:
                # collision / corruption: poison the entry, never serve
                # it. The page stays in the LRU (metaless) and ages out
                # normally.
                self.drop_prefix(page)
                return None
            self._lru[page] = None
            self._lru.move_to_end(page)
            return page

    def drop_prefix(self, page: int) -> None:
        with self.lock:
            meta = self.page_meta.pop(page, None)
            if meta is not None and self.hash_to_page.get(meta[0]) == page:
                del self.hash_to_page[meta[0]]

    def export_prefix(self, digest: bytes) -> Optional[
            Tuple[Tuple[int, ...], Optional[bytes], List[np.ndarray]]]:
        """Peer-serving read (handler thread): ``(canary, parent, leaf
        copies)`` for a resident digest, or None. Copies under the lock
        so a concurrent eviction/rewrite can never tear the bytes; does
        not touch the LRU (a remote reader is not a local reuse
        signal). PINNED pages are never exported: a freshly spilled
        page stays pinned until its device→host gather lands, and its
        canary would validate bytes that were never written — the peer
        retries later or misses."""
        with self.lock:
            page = self.hash_to_page.get(digest)
            if page is None or self._pins.get(page):
                return None
            _, canary, parent = self.page_meta[page]
            return canary, parent, [s[page].copy() for s in self.store]
