"""Encoder-node entrypoint: vision tower only, serving encode jobs.

Reference: /root/reference/gllm/entrypoints/encoder_server.py (157 LoC).
Loads ONLY the visual half of the checkpoint (skip_language), publishes on
the discovery registry, and encodes jobs dispatched by LM nodes.
"""

from __future__ import annotations

import argparse
import logging

logger = logging.getLogger(__name__)


def make_parser():
    p = argparse.ArgumentParser("gllm-tpu encoder server")
    p.add_argument("--model", required=True)
    p.add_argument("--discovery-endpoint", required=True)
    p.add_argument("--encoder-id", default="enc0")
    p.add_argument("--advertise-host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="job-server port (0 = ephemeral)")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--mm-processor-min-pixels", type=int, default=None)
    p.add_argument("--mm-processor-max-pixels", type=int, default=None,
                   help="pixel bounds for the image/video processor "
                        "(reference api_server.py:488-494)")
    return p


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    args = make_parser().parse_args(argv)
    from gllm_tpu.disagg.encoder_runtime import EncoderEngine, EncoderRuntime
    from gllm_tpu.engine.mm_processing import processor_config_hash
    engine = EncoderEngine(args.model, dtype=args.dtype,
                           min_pixels=args.mm_processor_min_pixels,
                           max_pixels=args.mm_processor_max_pixels)
    runtime = EncoderRuntime(
        engine, args.discovery_endpoint, encoder_id=args.encoder_id,
        advertise_host=args.advertise_host,
        processor_config_hash=processor_config_hash(
            args.model, min_pixels=args.mm_processor_min_pixels,
            max_pixels=args.mm_processor_max_pixels),
        port=args.port)
    logger.info("encoder %s serving %s (port %d)", args.encoder_id,
                args.model, runtime.port)
    runtime.serve_forever()


if __name__ == "__main__":
    main()
