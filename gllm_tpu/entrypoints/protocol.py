"""OpenAI-compatible request/response schema.

Covers the surface of the reference's pydantic protocol
(/root/reference/gllm/entrypoints/protocol.py, 812 LoC): chat/completions
requests with sampling knobs, stream & aggregate responses, logprob shapes,
usage. Re-designed as stdlib dataclasses with explicit validation because
this image ships no pydantic — the serving stack is dependency-free.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any, Dict, List, Optional, Union

from gllm_tpu.sampling_params import SamplingParams


class ProtocolError(ValueError):
    """Maps to HTTP 400 with an OpenAI-style error body."""


def _get(d: dict, key: str, typ, default=None, required=False):
    if key not in d or d[key] is None:
        if required:
            raise ProtocolError(f"missing required field {key!r}")
        return default
    v = d[key]
    if typ is float and isinstance(v, int):
        v = float(v)
    if not isinstance(v, typ):
        raise ProtocolError(
            f"field {key!r} must be {getattr(typ, '__name__', typ)}")
    return v


def sampling_from_request(d: dict, default_max_tokens: int) -> SamplingParams:
    stop = d.get("stop")
    if isinstance(stop, str):
        stop = [stop]
    elif stop is None:
        stop = []
    elif not (isinstance(stop, list)
              and all(isinstance(s, str) for s in stop)):
        raise ProtocolError("stop must be a string or list of strings")
    logit_bias = d.get("logit_bias")
    if logit_bias is not None:
        if not isinstance(logit_bias, dict):
            raise ProtocolError("logit_bias must be an object")
        try:
            # OpenAI sends token ids as JSON-object string keys
            logit_bias = {int(k): float(v) for k, v in logit_bias.items()}
        except (TypeError, ValueError) as e:
            raise ProtocolError(
                "logit_bias keys must be token ids and values "
                "numbers") from e
    sp = SamplingParams(
        temperature=_get(d, "temperature", float, 1.0),
        top_p=_get(d, "top_p", float, 1.0),
        top_k=_get(d, "top_k", int, -1),
        min_p=_get(d, "min_p", float, 0.0),
        logit_bias=logit_bias,
        repetition_penalty=_get(d, "repetition_penalty", float, 1.0),
        presence_penalty=_get(d, "presence_penalty", float, 0.0),
        frequency_penalty=_get(d, "frequency_penalty", float, 0.0),
        max_tokens=_get(d, "max_tokens", int,
                        _get(d, "max_completion_tokens", int,
                             default_max_tokens)),
        ignore_eos=_get(d, "ignore_eos", bool, False),
        stop_token_ids=_get(d, "stop_token_ids", list, []),
        stop=stop,
        prompt_logprobs=_get(d, "prompt_logprobs", int, None),
        seed=_get(d, "seed", int, None),
    )
    logprobs = d.get("logprobs")
    if isinstance(logprobs, bool):
        sp.logprobs = _get(d, "top_logprobs", int, 0) if logprobs else None
    elif isinstance(logprobs, int):
        sp.logprobs = logprobs
    try:
        sp.validate()
    except ValueError as e:
        raise ProtocolError(str(e)) from e
    return sp


def n_best_of(d: dict):
    n = _get(d, "n", int, 1)
    best_of = _get(d, "best_of", int, n)
    if n < 1 or best_of < n:
        raise ProtocolError("need n >= 1 and best_of >= n")
    return n, best_of


@dataclasses.dataclass
class ChatCompletionRequest:
    messages: List[Dict[str, Any]]
    model: str
    sampling: SamplingParams
    stream: bool
    chat_template_kwargs: Dict[str, Any]
    tools: List[Dict[str, Any]]
    tool_choice: Any
    n: int = 1
    best_of: int = 1

    @classmethod
    def from_dict(cls, d: dict, default_max_tokens: int):
        messages = _get(d, "messages", list, required=True)
        if not messages:
            raise ProtocolError("messages must be non-empty")
        for m in messages:
            if not isinstance(m, dict) or "role" not in m:
                raise ProtocolError("each message needs a 'role'")
        n, best_of = n_best_of(d)
        return cls(
            messages=messages,
            model=_get(d, "model", str, ""),
            sampling=sampling_from_request(d, default_max_tokens),
            stream=_get(d, "stream", bool, False),
            chat_template_kwargs=_get(d, "chat_template_kwargs", dict, {}),
            tools=_get(d, "tools", list, []),
            tool_choice=d.get("tool_choice", "auto"),
            n=n, best_of=best_of,
        )


@dataclasses.dataclass
class CompletionRequest:
    prompt: Union[str, List[int]]
    model: str
    sampling: SamplingParams
    stream: bool
    echo: bool
    n: int = 1
    best_of: int = 1

    @classmethod
    def from_dict(cls, d: dict, default_max_tokens: int):
        prompt = d.get("prompt")
        if isinstance(prompt, list):
            if not all(isinstance(t, int) for t in prompt):
                raise ProtocolError("token-array prompt must be ints")
        elif not isinstance(prompt, str):
            raise ProtocolError("prompt must be a string or token array")
        n, best_of = n_best_of(d)
        return cls(
            prompt=prompt,
            model=_get(d, "model", str, ""),
            sampling=sampling_from_request(d, default_max_tokens),
            stream=_get(d, "stream", bool, False),
            echo=_get(d, "echo", bool, False),
            n=n, best_of=best_of,
        )


# ---- response builders ----------------------------------------------------

def _id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def usage_dict(prompt_tokens: int, completion_tokens: int) -> dict:
    return {"prompt_tokens": prompt_tokens,
            "completion_tokens": completion_tokens,
            "total_tokens": prompt_tokens + completion_tokens}


def chat_logprobs_content(lp_entries, decode) -> Optional[dict]:
    """OpenAI chat logprobs shape: {"content": [{token, logprob, bytes,
    top_logprobs: [...]}, ...]} from our (chosen, top_ids, top_lps)
    per-token tuples."""
    if lp_entries is None:
        return None
    content = []
    for tok_id, (chosen, top_ids, top_lps) in lp_entries:
        tok = decode(tok_id)
        content.append({
            "token": tok,
            "logprob": chosen,
            "bytes": list(tok.encode()),
            "top_logprobs": [
                {"token": decode(i), "logprob": lp,
                 "bytes": list(decode(i).encode())}
                for i, lp in zip(top_ids, top_lps)],
        })
    return {"content": content}


def completion_logprobs(lp_entries, decode, text_offset0: int = 0) \
        -> Optional[dict]:
    """OpenAI completions logprobs shape (tokens / token_logprobs /
    top_logprobs / text_offset)."""
    if lp_entries is None:
        return None
    tokens, token_logprobs, top_logprobs, text_offset = [], [], [], []
    off = text_offset0
    for tok_id, entry in lp_entries:
        tok = decode(tok_id)
        tokens.append(tok)
        text_offset.append(off)
        off += len(tok)
        if entry is None:            # first prompt position
            token_logprobs.append(None)
            top_logprobs.append(None)
            continue
        chosen, top_ids, top_lps = entry
        token_logprobs.append(chosen)
        top_logprobs.append({decode(i): lp
                             for i, lp in zip(top_ids, top_lps)})
    return {"tokens": tokens, "token_logprobs": token_logprobs,
            "top_logprobs": top_logprobs, "text_offset": text_offset}


def chat_completion_response(model: str, choices: list,
                             usage: dict) -> dict:
    """choices: [{"text", "finish_reason", "tool_calls"?, "logprobs"?}]"""
    out = []
    for i, c in enumerate(choices):
        message: Dict[str, Any] = {"role": "assistant",
                                   "content": c["text"]}
        finish = c["finish_reason"]
        if c.get("tool_calls"):
            message["tool_calls"] = c["tool_calls"]
            message["content"] = c["text"] or None
            finish = "tool_calls"
        out.append({"index": i, "message": message,
                    "finish_reason": finish,
                    "logprobs": c.get("logprobs")})
    return {
        "id": _id("chatcmpl"),
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": out,
        "usage": usage,
    }


def chat_completion_chunk(rid: str, model: str, delta: Optional[str],
                          finish_reason: Optional[str],
                          role: bool = False, index: int = 0) -> dict:
    d: Dict[str, Any] = {}
    if role:
        d["role"] = "assistant"
    if delta:
        d["content"] = delta
    return {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": index, "delta": d,
                     "finish_reason": finish_reason}],
    }


def completion_response(model: str, choices: list, usage: dict) -> dict:
    """choices: [{"text", "finish_reason", "logprobs"?}]"""
    return {
        "id": _id("cmpl"),
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": i, "text": c["text"],
                     "finish_reason": c["finish_reason"],
                     "logprobs": c.get("logprobs")}
                    for i, c in enumerate(choices)],
        "usage": usage,
    }


def completion_chunk(rid: str, model: str, delta: str,
                     finish_reason: Optional[str],
                     index: int = 0) -> dict:
    return {
        "id": rid,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": index, "text": delta,
                     "finish_reason": finish_reason, "logprobs": None}],
    }


def error_response(message: str, code: int = 400) -> dict:
    return {"error": {"message": message, "type": "invalid_request_error",
                      "code": code}}


def stream_error_event(message, finish_reason: str = "error",
                       retry_after=None) -> dict:
    """Terminal SSE error event: emitted after the finish chunk when a
    stream ends with a server-side failure, carrying the failure detail
    and — for transient failures — the ``retry_after`` backoff hint
    (the StreamChunk.retry_after the plain-chunk rendering used to
    drop). Routers and backoff-aware clients key on it; ordinary
    clients that stop at the finish_reason chunk are unaffected."""
    err = {"message": message or finish_reason, "type": "server_error",
           "code": finish_reason}
    if retry_after is not None:
        err["retry_after"] = round(float(retry_after), 3)
    return {"error": err}


def new_request_id(chat: bool) -> str:
    return _id("chatcmpl" if chat else "cmpl")
