"""2-process multi-host serving smoke test (VERDICT r1 item 6).

Launches two OS processes joined via jax.distributed on the CPU backend
(2 virtual devices each → a 4-device global tp=2 mesh whose collectives
cross the process boundary), serves two requests through the
host-0-frontend + broadcast engine, and checks the outputs match a
single-process run of the same model.
"""

import json
import os
import socket
import subprocess
import sys

import pytest
import torch


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_serving(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(4)
    model_dir = tmp_path / "m"
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(model_dir,
                                               safe_serialization=True)
    result = tmp_path / "result.json"
    port = free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)    # worker sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), "2", str(i), str(model_dir),
         str(result)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out.decode(errors="replace"))
            assert p.returncode == 0, outs[-1][-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    d = json.loads(result.read_text())
    assert d["procs"] == 2 and d["devices"] == 4, d
    assert all(o and len(o) == 4 for o in d["outputs"]), (d, outs)

    # oracle: single-process (tp=1) greedy on the same checkpoint
    import jax
    jax.config.update("jax_platforms", "cpu")
    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams
    llm = LLM(config=EngineConfig(
        model=str(model_dir), dtype="float32", max_model_len=64,
        cache=CacheConfig(page_size=4, num_pages=64)))
    want = [o.output_token_ids for o in llm.generate(
        prompt_token_ids=[[5, 9, 23], [7, 7]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=4,
                                       ignore_eos=True))]
    assert d["outputs"] == want, (d["outputs"], want)


def test_two_process_http_serving(tmp_path):
    """One OpenAI completion over HTTP against a 2-process engine."""
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(4)
    model_dir = tmp_path / "m"
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(model_dir,
                                               safe_serialization=True)
    result = tmp_path / "result.json"
    port = free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), "2", str(i), str(model_dir),
         str(result), "http"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            assert p.returncode == 0, out.decode(errors="replace")[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    d = json.loads(result.read_text())
    assert d["status"] == 200, d
    assert d["body"]["choices"][0]["finish_reason"] == "length"
    assert d["body"]["usage"]["completion_tokens"] == 4


@pytest.mark.parametrize("blob_min", [None, "1"],
                         ids=["broadcast-pixels", "blob-channel"])
def test_two_process_mm_serving(tmp_path, blob_min):
    """Image request over multi-host: small pixels ride the intake
    broadcast; with GLLM_TPU_BLOB_MIN_BYTES=1 they are lifted onto the
    host-0 blob server and the follower fetches them out-of-band (the
    reference's pixels-off-the-schedule-plane property, comm.py:436-524).
    Output matches a single-process run either way."""
    import numpy as np
    from transformers import (Qwen2_5_VLConfig,
                              Qwen2_5_VLForConditionalGeneration)
    torch.manual_seed(11)
    text = dict(vocab_size=160, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2,
                intermediate_size=96, max_position_embeddings=512,
                rms_norm_eps=1e-6, rope_theta=10000.0,
                tie_word_embeddings=False,
                rope_scaling={"type": "mrope", "mrope_section": [2, 2, 4]})
    vision = dict(depth=2, hidden_size=32, intermediate_size=48,
                  num_heads=4, patch_size=2, temporal_patch_size=2,
                  in_channels=3, spatial_merge_size=2, out_hidden_size=64,
                  window_size=8, fullatt_block_indexes=[1],
                  hidden_act="silu")
    model_dir = tmp_path / "vl"
    Qwen2_5_VLForConditionalGeneration(Qwen2_5_VLConfig(
        text_config=text, vision_config=vision, image_token_id=150,
        video_token_id=151, vision_start_token_id=152,
        vision_end_token_id=153, eos_token_id=0,
        bos_token_id=1)).save_pretrained(model_dir,
                                         safe_serialization=True)

    result = tmp_path / "result.json"
    port = free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if blob_min is not None:
        env["GLLM_TPU_BLOB_MIN_BYTES"] = blob_min
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), "2", str(i), str(model_dir),
         str(result), "mm"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out.decode(errors="replace"))
            assert p.returncode == 0, outs[-1][-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    d = json.loads(result.read_text())
    assert d["procs"] == 2 and d["output"], (d, [o[-800:] for o in outs])

    # oracle: single-process run of the same request
    import jax
    jax.config.update("jax_platforms", "cpu")
    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams
    rng = np.random.default_rng(0)
    pix = rng.standard_normal((16, 24)).astype(np.float32)
    grid = np.asarray([[1, 4, 4]])
    ids = [5, 9, 23, 152] + [150] * 4 + [153, 7, 30]
    llm = LLM(config=EngineConfig(
        model=str(model_dir), dtype="float32", max_model_len=64,
        cache=CacheConfig(page_size=4, num_pages=64)))
    want = llm.generate(
        prompt_token_ids=[ids],
        mm_inputs=[{"pixel_values": pix, "image_grid_thw": grid}],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=4,
                                       ignore_eos=True))[0]
    assert d["output"] == want.output_token_ids, (d, want.output_token_ids)


def test_blob_lift_resolve_roundtrip():
    """Unit: _lift_blobs / BlobStore / BlobClient / _resolve_blobs."""
    import numpy as np
    from gllm_tpu.parallel import multihost_engine as me

    rng = np.random.default_rng(1)
    big = rng.standard_normal((me.BLOB_MIN_BYTES // 4 + 16,)) \
        .astype(np.float32)                      # > threshold
    small = np.arange(4, dtype=np.int64)
    mm = {"pixel_values": big, "image_grid_thw": small, "none": None}
    wire, blobs = me._lift_blobs(mm)
    assert isinstance(wire["pixel_values"], me.BlobRef)
    assert isinstance(wire["image_grid_thw"], np.ndarray)
    assert len(blobs) == 1

    store = me.BlobStore(host="127.0.0.1")
    try:
        store.put(blobs)
        cli = me.BlobClient(f"127.0.0.1:{store.port}")
        out = me._resolve_blobs(wire, cli.fetch)
        np.testing.assert_array_equal(out["pixel_values"], big)
        np.testing.assert_array_equal(out["image_grid_thw"], small)
        # cache hit path (after retire the bytes only live in the cache)
        store.retire(blobs.keys())
        out2 = me._resolve_blobs(wire, cli.fetch)
        np.testing.assert_array_equal(out2["pixel_values"], big)
        # a truly unknown key is fatal
        import pytest as _pytest
        with _pytest.raises(RuntimeError):
            cli.fetch("deadbeef")
    finally:
        store.close()


def test_two_process_disagg_serving(tmp_path):
    """Encoder disaggregation over multi-host: the coordinator runs on
    host 0 only; admits and gate-B embedding rows replicate to the
    follower as tick events (blob channel for bulk rows). Output must be
    byte-identical to a single-host disagg run of the same request."""
    import numpy as np
    from transformers import (Qwen2_5_VLConfig,
                              Qwen2_5_VLForConditionalGeneration)
    torch.manual_seed(11)
    text = dict(vocab_size=160, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2,
                intermediate_size=96, max_position_embeddings=512,
                rms_norm_eps=1e-6, rope_theta=10000.0,
                tie_word_embeddings=False,
                rope_scaling={"type": "mrope", "mrope_section": [2, 2, 4]})
    vision = dict(depth=2, hidden_size=32, intermediate_size=48,
                  num_heads=4, patch_size=2, temporal_patch_size=2,
                  in_channels=3, spatial_merge_size=2, out_hidden_size=64,
                  window_size=8, fullatt_block_indexes=[1],
                  hidden_act="silu")
    model_dir = tmp_path / "vl"
    Qwen2_5_VLForConditionalGeneration(Qwen2_5_VLConfig(
        text_config=text, vision_config=vision, image_token_id=150,
        video_token_id=151, vision_start_token_id=152,
        vision_end_token_id=153, eos_token_id=0,
        bos_token_id=1)).save_pretrained(model_dir,
                                         safe_serialization=True)
    # the encoder loads the checkpoint's image processor; without the
    # pixel bounds the default upscales the tiny test image past the
    # slot capacity
    from transformers.models.qwen2_vl.image_processing_qwen2_vl import (
        Qwen2VLImageProcessor)
    Qwen2VLImageProcessor(patch_size=2, temporal_patch_size=2,
                          merge_size=2, min_pixels=16,
                          max_pixels=4096).save_pretrained(model_dir)

    result = tmp_path / "result.json"
    port = free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["GLLM_TPU_BLOB_MIN_BYTES"] = "1"      # force rows over the blob channel
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), "2", str(i), str(model_dir),
         str(result), "disagg"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out.decode(errors="replace"))
            assert p.returncode == 0, outs[-1][-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    d = json.loads(result.read_text())
    assert d["procs"] == 2 and d["output"], (d, [o[-800:] for o in outs])
    assert d["output"][0] != "ERROR", d
    # the mid-flight abort propagated (DisaggAbort event) and both
    # processes exited cleanly (rc checks above)
    assert d["abort_finish"] == "abort", d

    # oracle: SINGLE-host disagg run of the same request (single-host
    # disagg == monolith is covered by test_disagg)
    import time as _time

    import jax
    jax.config.update("jax_platforms", "cpu")
    from multihost_worker import DISAGG_IDS, disagg_image
    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.disagg.config import DisaggConfig
    from gllm_tpu.disagg.discovery import DiscoveryServer
    from gllm_tpu.disagg.encoder_runtime import (EncoderEngine,
                                                 EncoderRuntime)
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams
    srv = DiscoveryServer("127.0.0.1", 0).start()
    endpoint = f"127.0.0.1:{srv.port}"
    enc = EncoderRuntime(EncoderEngine(str(model_dir), dtype="float32"),
                         endpoint, encoder_id="enc0").start()
    llm = LLM(config=EngineConfig(
        model=str(model_dir), dtype="float32", max_model_len=64,
        cache=CacheConfig(page_size=4, num_pages=64)))
    llm.init_disagg(DisaggConfig(
        is_lm=True, discovery_endpoint=endpoint, num_slots=4,
        max_vis_tokens=64, overlap=True))
    try:
        seq = llm._allocate_seq(list(DISAGG_IDS), SamplingParams(
            temperature=0.0, max_tokens=4, ignore_eos=True))
        llm.submit_disagg(seq, [("image", disagg_image())])
        deadline = _time.monotonic() + 90
        while not seq.is_finished:
            assert _time.monotonic() < deadline
            llm.step()
        want = seq.output_token_ids
    finally:
        llm.disagg_coordinator.close()
        enc.stop()
        srv.stop()
    assert d["output"] == want, (d["output"], want)


def test_three_process_blob_peer_chain(tmp_path):
    """Blob-channel fan-out (VERDICT r03 weak #5): with 3 processes the
    chain topology points follower 2 at follower 1's peer server — its
    blob fetches must come from the PEER (or its own LRU), not host 0,
    bounding host-0 egress to one stream per blob regardless of pod
    size."""
    import numpy as np
    from transformers import (Qwen2_5_VLConfig,
                              Qwen2_5_VLForConditionalGeneration)
    torch.manual_seed(11)
    text = dict(vocab_size=160, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2,
                intermediate_size=96, max_position_embeddings=512,
                rms_norm_eps=1e-6, rope_theta=10000.0,
                tie_word_embeddings=False,
                rope_scaling={"type": "mrope", "mrope_section": [2, 2, 4]})
    vision = dict(depth=2, hidden_size=32, intermediate_size=48,
                  num_heads=4, patch_size=2, temporal_patch_size=2,
                  in_channels=3, spatial_merge_size=2, out_hidden_size=64,
                  window_size=8, fullatt_block_indexes=[1],
                  hidden_act="silu")
    model_dir = tmp_path / "vl3"
    Qwen2_5_VLForConditionalGeneration(Qwen2_5_VLConfig(
        text_config=text, vision_config=vision, image_token_id=150,
        video_token_id=151, vision_start_token_id=152,
        vision_end_token_id=153, eos_token_id=0,
        bos_token_id=1)).save_pretrained(model_dir,
                                         safe_serialization=True)

    result = tmp_path / "result3.json"
    port = free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["GLLM_TPU_BLOB_MIN_BYTES"] = "1"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), "3", str(i), str(model_dir),
         str(result), "mm"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(3)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out.decode(errors="replace"))
            assert p.returncode == 0, outs[-1][-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    d = json.loads(result.read_text())
    assert d["procs"] == 3 and d["output"], (d, [o[-800:] for o in outs])

    s1 = json.loads((tmp_path / "result3.json.blobstats1").read_text())
    s2 = json.loads((tmp_path / "result3.json.blobstats2").read_text())
    # follower 1 heads the chain: it fetched from host 0
    assert s1["host0"] >= 1, s1
    # follower 2 fetched everything from its peer / LRU — host 0 skipped
    assert s2["peer"] >= 1, s2
    assert s2["host0"] == 0, s2


def test_two_process_spec_serving(tmp_path):
    """Speculative decoding under multi-host: drafts are proposed from
    identical token state on every host (the mirror loops issue identical
    jit programs), and outputs stay byte-identical to a single-process
    plain engine."""
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(4)
    model_dir = tmp_path / "m"
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(model_dir,
                                               safe_serialization=True)
    result = tmp_path / "result.json"
    port = free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), "2", str(i), str(model_dir),
         str(result), "spec"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out.decode(errors="replace"))
            assert p.returncode == 0, outs[-1][-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    d = json.loads(result.read_text())
    assert d["procs"] == 2
    assert d["spec_stats"]["proposed"] > 0, d
    assert d["spec_stats"]["accepted"] > 0, d

    import jax
    jax.config.update("jax_platforms", "cpu")
    from gllm_tpu.config import CacheConfig, EngineConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams
    llm = LLM(config=EngineConfig(
        model=str(model_dir), dtype="float32", max_model_len=64,
        cache=CacheConfig(page_size=4, num_pages=64)))
    want = [o.output_token_ids for o in llm.generate(
        prompt_token_ids=[[5, 9, 23, 5, 9, 23, 5, 9], [7, 7, 7, 7]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))]
    assert d["outputs"] == want, (d["outputs"], want)
