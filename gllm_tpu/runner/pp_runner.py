"""Pipeline-parallel model runner.

TPU-native re-design of the reference's PP machinery (per-GPU worker
processes, NCCL isend/recv of hidden states, zmq delta-schedule broadcast to
follower ranks — /root/reference/gllm/worker.py:504-544,
dist_utils.py:8-22,494-528, dist_schedule.py). On TPU one controller process
owns every stage:

- layers split into ``pp`` contiguous stages (even split, or
  ``--assigned-layers``; reference get_pp_layers dist_utils.py:494-528);
  each stage's params + its layers' KV cache live on a disjoint device
  group (optionally TP-sharded within the stage).
- one jit program per stage; hidden/residual move between stages with
  ``jax.device_put`` (ICI transfer on real hardware).
- **pipelining comes from async dispatch**: the engine keeps up to
  ``pp_size`` scheduled microbatches in flight (scheduler in-flight
  marking), and because consecutive microbatches' stage programs run on
  different device groups, XLA's per-device queues overlap them — no
  explicit microbatch scheduler needed. Token throttling balances the
  token count across those in-flight microbatches (scheduler policy).
- the follower-mirror/delta-payload machinery disappears: there is one
  scheduler and one page table, shared by construction.

The sampled-token array returned by ``step_async`` is an uncommitted device
future; ``collect`` blocks on it one pipeline depth later.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gllm_tpu.config import EngineConfig
from gllm_tpu.models import ModelConfig, get_model_def
from gllm_tpu.ops.sampling import sample
from gllm_tpu.runner.runner import (ModelRunner, _DTYPES,
                                    pick_kv_pack)
from gllm_tpu.utils import cdiv, tpu_compiler_options

logger = logging.getLogger(__name__)


def split_layers(num_layers: int, pp: int,
                 assigned: Optional[List[int]] = None):
    """[(first, last)] per stage: even split with remainder spread from the
    front, or an explicit per-stage layer-count list."""
    if assigned is not None:
        if sum(assigned) != num_layers or len(assigned) != pp:
            raise ValueError(
                f"assigned_layers {assigned} must sum to {num_layers} "
                f"over {pp} stages")
        counts = assigned
    else:
        base, rem = divmod(num_layers, pp)
        counts = [base + (1 if i < rem else 0) for i in range(pp)]
    bounds, first = [], 0
    for c in counts:
        bounds.append((first, first + c))
        first += c
    return bounds


@dataclasses.dataclass
class _Stage:
    cfg: ModelConfig
    params: dict
    kv: object
    device: object          # placement target (Device or NamedSharding mesh)
    mesh: object
    fn: object              # jit'd stage program


class PPModelRunner(ModelRunner):
    """Same interface as ModelRunner; executes a multi-stage pipeline."""

    def __init__(self, config: EngineConfig, model_cfg: ModelConfig,
                 params=None, mesh=None):
        # Deliberately NOT calling super().__init__: the single-program
        # setup doesn't apply. Shared helpers are used piecemeal.
        if params is not None or mesh is not None:
            raise NotImplementedError(
                "PPModelRunner builds its own per-stage params/meshes")
        self.config = config
        self.model_cfg = model_cfg
        self.mesh = None
        self.dtype = _DTYPES[config.dtype]
        self.model_def = get_model_def(model_cfg)
        pp, tp = config.parallel.pp, config.parallel.tp
        if config.parallel.dp > 1:
            raise NotImplementedError("dp with pp pending multi-replica "
                                      "engine")
        if model_cfg.use_hybrid:
            raise NotImplementedError(
                "hybrid (GDN) models with pp > 1 are not wired up yet")
        devices = jax.devices()
        if len(devices) < pp * tp:
            raise ValueError(f"pp={pp} tp={tp} needs {pp * tp} devices, "
                             f"have {len(devices)}")
        # PP builds per-stage meshes, which don't fit the single TP shard
        # context — clear any stale one a prior runner left behind.
        from gllm_tpu.ops.attention import set_shard_context
        set_shard_context(None)
        impl = config.attention_impl
        pack = pick_kv_pack(model_cfg, tp_sharded=tp > 1)
        if impl == "auto":
            impl = ("pallas" if tp == 1 and pack
                    and jax.default_backend() in ("tpu", "axon") else "xla")
        elif impl == "pallas":
            if tp > 1:
                raise NotImplementedError(
                    "attention_impl='pallas' with pp×tp is not wired up "
                    "yet; use attention_impl='xla'")
            if not pack:
                raise NotImplementedError(
                    "attention_impl='pallas' needs a 128-lane-aligned KV "
                    "layout (head_dim ×pack % 128 == 0)")
        self.kv_pack = pack if impl == "pallas" else 1
        self.attn_impl = impl
        from gllm_tpu.runner.prepare import BatchBuilder
        self.builder = BatchBuilder(config, config.cache.page_size,
                                    vocab_size=model_cfg.vocab_size,
                                    hidden_size=model_cfg.hidden_size,
                                    use_mm=model_cfg.use_mm,
                                    mm_embed_dim=model_cfg.mm_embed_dim)
        if model_cfg.use_mm:
            from gllm_tpu.utils import LRUBytesCache
            self._mm_cache = LRUBytesCache()
        self.rng_key = jax.random.key(config.seed)
        self._step_count = 0

        bounds = split_layers(model_cfg.num_layers, pp,
                              config.parallel.assigned_layers)

        # Phase 1: load (and optionally quantize) every stage's weights so
        # page sizing sees the real post-load memory on each stage device.
        staged = []
        for i, (first, last) in enumerate(bounds):
            scfg = dataclasses.replace(model_cfg, first_layer=first,
                                       last_layer=last)
            stage_devs = devices[i * tp:(i + 1) * tp]
            if tp > 1:
                from jax.sharding import Mesh
                smesh = Mesh(np.asarray(stage_devs).reshape(1, tp),
                             ("dp", "tp"))
            else:
                smesh = None
            if config.load_format == "dummy" or not config.model:
                sparams = self.model_def.init_params(scfg,
                                                     seed=config.seed,
                                                     dtype=self.dtype)
                if model_cfg.use_mm and first > 0:
                    sparams.pop("visual", None)
            elif model_cfg.use_mm and first > 0:
                # only stage 0 embeds visual rows — later stages never
                # read the tower (disagg-LM skip_visual rule filtering)
                sparams = self.model_def.load_params(
                    config.model, scfg, dtype=self.dtype, skip_visual=True)
            else:
                sparams = self.model_def.load_params(config.model, scfg,
                                                     dtype=self.dtype)
            if config.quantization:
                from gllm_tpu.ops.quant import (param_bytes,
                                                quantize_params)
                before = param_bytes(sparams)
                sparams = quantize_params(sparams,
                                          mode=config.quantization)
                logger.info(
                    "stage %d quantized (%s): %.2f GB -> %.2f GB", i,
                    config.quantization, before / 1e9,
                    param_bytes(sparams) / 1e9)
            staged.append((scfg, stage_devs, smesh, sparams))

        # Phase 2: one shared page count from the TIGHTEST stage device
        # (page tables are global; honors cache.memory_util).
        self.num_pages = (config.cache.num_pages
                          or self._determine_num_pages(bounds, staged))

        self.stages: List[_Stage] = []
        for i, (scfg, stage_devs, smesh, sparams) in enumerate(staged):
            skv = self.model_def.init_kv_cache(
                scfg, self.num_pages, config.cache.page_size,
                self.dtype if config.cache.kv_cache_dtype == "auto"
                else _DTYPES[config.cache.kv_cache_dtype],
                **({"kv_pack": self.kv_pack} if self.kv_pack > 1 else {}))
            if smesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                from gllm_tpu.parallel.shardings import shard_params
                sparams = shard_params(
                    sparams, self.model_def.param_specs(scfg, tp), smesh)
                kspecs = self.model_def.kv_specs(scfg, tp)
                skv = jax.tree.map(
                    lambda x, s: jax.device_put(x, NamedSharding(smesh, s)),
                    skv, kspecs)
                # Activations/batch enter the stage replicated over its mesh.
                place = NamedSharding(smesh, PartitionSpec())
            else:
                place = stage_devs[0]
                sparams = jax.device_put(sparams, place)
                skv = jax.device_put(skv, place)
            fn = self._make_stage_fn(scfg)
            self.stages.append(_Stage(scfg, sparams, skv, place, smesh, fn))
        self.cos_sin = self.model_def.make_rope_table(model_cfg)
        if model_cfg.use_mm:
            # the inherited _prepare_mm embeds on stage 0 (visual tower)
            self.params = self.stages[0].params
        logger.info("pipeline: %d stages %s × tp=%d, %d KV pages/stage",
                    pp, bounds, tp, self.num_pages)

    def _determine_num_pages(self, bounds, staged) -> int:
        """Size the shared KV page count from the TIGHTEST stage: every
        stage's weights are already resident (phase 1), so each stage
        device's free memory divided by that stage's per-page KV bytes
        (via the shared _kv_bytes_per_page, with the stage's layer count)
        bounds its page budget; take the minimum (reference
        profile-then-size discipline, memory_manager.py:476-526)."""
        best = None
        for (scfg, stage_devs, _, _), (first, last) in zip(staged, bounds):
            try:
                stats = stage_devs[0].memory_stats()
                limit = stats["bytes_limit"]
                in_use = stats["bytes_in_use"]
            except Exception:
                return 2048        # CPU / no memory_stats
            free = limit * self.config.cache.memory_util - in_use
            free -= 512 * 1024 * 1024      # activation headroom
            per_page = self._kv_bytes_per_page(n_layers=last - first)
            num = int(free // per_page)
            best = num if best is None else min(best, num)
        min_pages = cdiv(self.config.max_model_len,
                         self.config.cache.page_size) + 2
        if best < min_pages:
            raise RuntimeError(
                f"not enough device memory for PP KV cache: {best} pages "
                f"(need >= {min_pages})")
        return best

    # ---- stage programs ---------------------------------------------------

    def _make_stage_fn(self, scfg: ModelConfig):
        fwd = self.model_def.forward
        logits_fn = self.model_def.compute_logits
        attn_impl = self.attn_impl

        @functools.partial(jax.jit, static_argnames=("max_q_len",),
                           compiler_options=tpu_compiler_options(),
                           donate_argnums=(1,))
        def stage(params, kv, batch, cos_sin, hidden, residual,
                  token_counts, *, max_q_len: int):
            hidden, residual, kv = fwd(params, kv, batch, scfg,
                                       cos_sin=cos_sin,
                                       attn_impl=attn_impl,
                                       max_q_len=max_q_len,
                                       hidden_in=hidden,
                                       residual_in=residual)
            if scfg.is_last_stage:
                logits = logits_fn(params, hidden, residual, batch, scfg)
                tokens = sample(logits, batch.sampling, token_counts)
                return tokens, kv
            return (hidden, residual), kv

        return stage

    # ---- execution --------------------------------------------------------

    def step_async(self, sched_batch):
        from gllm_tpu.parallel.mesh import mesh_context
        self._step_count += 1
        if self.model_cfg.use_mm:
            # ViT embedding on stage 0's params (visual tower lives there)
            self._prepare_mm(sched_batch)
        step_key = jax.random.fold_in(self.rng_key, self._step_count)
        batch, max_q, presence = self.builder.build(sched_batch, step_key)
        hidden = residual = None
        out = None
        for stage in self.stages:
            sb = jax.device_put(batch, stage.device)
            if hidden is not None:
                hidden = jax.device_put(hidden, stage.device)
                residual = jax.device_put(residual, stage.device)
            pm = presence if stage.cfg.is_last_stage else None
            if pm is not None:
                pm = jax.device_put(pm, stage.device)
            with mesh_context(stage.mesh):
                out, stage.kv = stage.fn(stage.params, stage.kv, sb,
                                         self.cos_sin, hidden, residual,
                                         pm, max_q_len=max_q)
            if not stage.cfg.is_last_stage:
                hidden, residual = out
        # aux slot kept empty: per-token logprobs are a single-runner
        # feature for now (last PP stage could compute them the same way).
        return out, {}, sched_batch.num_seqs

    def collect(self, handle):
        tokens, aux, n = handle
        return np.asarray(tokens)[:n], aux

    def step(self, sched_batch) -> np.ndarray:
        return self.collect(self.step_async(sched_batch))[0]
