"""Engine configuration.

One dataclass is the single config schema for the whole engine — the TPU-native
equivalent of the reference's constructor-kwarg threading
(/root/reference/gllm/llm_engine.py:34-75) and CLI flag surface
(/root/reference/gllm/entrypoints/api_server.py:267-508).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from gllm_tpu.utils import cdiv


@dataclasses.dataclass
class SchedulerConfig:
    """Scheduling policy knobs (reference: scheduler.py:16-163, api_server flags
    --schedule-method/--maxd/--maxp/--minp/--iterp)."""

    schedule_method: str = "chunked_prefill"  # chunked_prefill | token_throttling | split_pd
    max_decode_seqs: int = 256            # --maxd: decode seqs per batch
    max_prefill_tokens: int = 2048        # --maxp: prefill token budget per batch
    min_prefill_tokens: int = 128         # --minp: throttling lower clamp
    iter_smooth: int = 16                 # --iterp: waiting-token smoothing divisor
    init_new_token_ratio: float = 0.7     # adaptive KV admission ramp start
    min_new_token_ratio: float = 0.1      # ramp floor
    new_token_ratio_decay_steps: int = 600
    # KV free-ratio reserve used by token throttling's prefill budget ramp
    # (reference scheduler.py:613-696).
    throttle_reserve: float = 0.2
    # pd-pool topology role this replica advertises on /server_info
    # (--pool-role, docs/pd_pools.md): the front router places new
    # prompts on the prefill pool and migrates streams to the decode
    # pool at first token. "mixed" (default) keeps the replica eligible
    # for both phases — the single-replica and legacy-fleet behavior.
    pool_role: str = "mixed"              # prefill | decode | mixed


@dataclasses.dataclass
class CacheConfig:
    """Paged KV cache geometry (reference: memory_manager.py, --page-size,
    --gpu-memory-util)."""

    page_size: int = 16
    memory_util: float = 0.9              # fraction of free HBM given to KV
    num_pages: Optional[int] = None       # explicit override (tests/benchmarks)
    # Paged-KV storage dtype (--kv-cache-dtype). "auto" stores the model
    # dtype (byte-identical legacy). "int8" stores quantized K/V with
    # running per-page per-head f32 scales, dequantized inside the
    # attention kernels — halves KV read bandwidth and roughly doubles
    # page capacity from the same HBM budget at a bounded numerics cost
    # (docs/kv_quantization.md; unsupported for MLA/hybrid models).
    kv_cache_dtype: str = "auto"   # auto | bfloat16 | float16 | float32
                                   # | fp8 | int8
    enable_prefix_caching: bool = False
    # Hybrid (GDN) models: cached-prefix SSM state slots (reference
    # --max-snapshot-ssm-slots; 0 disables the SSM half of prefix caching)
    ssm_snapshot_slots: int = 64
    # Host-RAM KV tier size in GiB (gllm_tpu/kvswap, --kv-host-pool-gb):
    # pinned host pages mirroring the device paged layout. Preemption
    # victims swap out instead of recomputing, and evicted prefix-cache
    # pages spill here so match_prefix can restore them. 0 = tier
    # disabled (the pre-offload recompute behavior, byte for byte).
    kv_host_pool_gb: float = 0.0
    # Explicit host page count override (tests / benchmarks); wins over
    # the GB sizing when set.
    kv_host_pool_pages: Optional[int] = None
    # --swap-policy: "auto" enables the tier iff a host pool is
    # configured; "swap" requires one (config error otherwise);
    # "recompute" forces the legacy free-and-recompute preemption even
    # with a pool configured.
    swap_policy: str = "auto"
    # ---- tiered prefix store (gllm_tpu/kvstore, docs/kv_offload.md) ----
    # Disk tier behind the host pool (--kv-disk-path): content-addressed
    # prefix-page files written on host-tier eviction, probed on host
    # miss, byte-budgeted LRU (--kv-disk-gb). Requires the host pool and
    # prefix caching; None disables the tier (byte-identical legacy).
    kv_disk_path: Optional[str] = None
    kv_disk_gb: float = 4.0
    # Cluster tier (--prefix-peers): comma-separated host:port of peer
    # replicas' prefix servers — match_prefix can restore a prefix
    # another replica computed. --prefix-serve-port starts this
    # replica's serving endpoint (0 = ephemeral; None = don't serve).
    prefix_peers: Optional[str] = None
    prefix_serve_port: Optional[int] = None

    @property
    def host_pool_configured(self) -> bool:
        return (self.swap_policy != "recompute"
                and (self.kv_host_pool_gb > 0
                     or bool(self.kv_host_pool_pages)))

    @property
    def kvstore_configured(self) -> bool:
        return bool(self.kv_disk_path or self.prefix_peers
                    or self.prefix_serve_port is not None)


@dataclasses.dataclass
class ParallelConfig:
    """Mesh geometry. The reference exposes --pp/--tp/--dp/--enable-ep
    (dist_utils.py:149-263); on TPU these become named mesh axes over which
    jit/GSPMD lays out shardings and inserts ICI collectives."""

    pp: int = 1
    tp: int = 1
    dp: int = 1
    # Sequence/context parallelism (beyond the reference, which has none —
    # SURVEY.md §2.2): long single-seq prefill chunks run causal ring
    # attention over the ``sp`` mesh axis (parallel/ring_attention.py);
    # decode and mixed batches use the paged path with activations
    # sharded over sp. Composes with tp; requires pp == dp == 1.
    sp: int = 1
    enable_ep: bool = False
    # Explicit per-stage layer counts (reference --assigned-layers,
    # dist_utils.py:494-528); None → even split.
    assigned_layers: Optional[list] = None

    @property
    def world_size(self) -> int:
        return self.pp * self.tp * self.dp * self.sp


@dataclasses.dataclass
class EngineConfig:
    model: str = ""
    tokenizer: Optional[str] = None
    dtype: str = "bfloat16"
    seed: int = 0
    max_model_len: int = 4096
    max_num_seqs: int = 256
    load_format: str = "auto"             # auto | dummy (weight-less bring-up,
                                          # reference api_server.py:293-299)
    # Overlap scheduling (reference --overlap-scheduling + OverlapWorker):
    # chain decode steps on-device so the host round trip between decode
    # iterations disappears.
    overlap_scheduling: bool = False
    # In-flight chained decode steps when overlap_scheduling is on. Depth
    # 2 hides host batch-building; deeper pipelines also hide the
    # dispatch round trip of remote-attached TPUs (axon tunnel).
    overlap_depth: int = 2
    # Fuse up to K chained decode steps into ONE device program
    # (lax.scan over the step axis): one dispatch + one token fetch per K
    # tokens/seq. The decisive lever when dispatch latency is high
    # (remote-attached TPUs); trades up to K-1 wasted steps per EOS
    # unless ondevice_finish is on. Legacy name — decode_chain_len is the
    # canonical knob and wins when both are set.
    multi_step_decode: int = 1
    # Canonical fused-chain length (--decode-chain-len): K decode steps
    # per device dispatch. None defers to multi_step_decode, except that
    # ondevice_finish (which removes the post-EOS waste that made long
    # chains risky) raises an unset chain length to 16 — the scheduler's
    # page-feasibility check still shortens any individual block that
    # would not fit its page bucket.
    decode_chain_len: Optional[int] = None
    # On-device finish detection (--ondevice-finish, fused multi-step
    # blocks only): the fused scan compares each sampled token against
    # the row's EOS/stop-token set and folds the result into a carried
    # alive mask (position frozen, KV writes to the dummy page — the
    # same freeze machinery length deaths use), and the block driver
    # early-exits once every row is dead instead of burning the
    # remaining sub-steps. The precomputed active_until becomes a
    # conservative upper bound instead of the only death mechanism; the
    # per-row finish step returns with the token block. Token streams
    # are byte-identical either way (the host discards post-death
    # tokens in both modes); off = byte-identical legacy device
    # programs. docs/overlap_scheduling.md#on-device-finish.
    ondevice_finish: bool = False
    # Bubble-zero pipelined engine loop (--pipelined-loop,
    # docs/overlap_scheduling.md#pipelined-loop): when a decode chain
    # cannot extend (finish, compaction, membership growth), the engine
    # speculatively RE-FORMS the next pure-decode batch off *promised*
    # token counts — the sampled ids stay on device and are spliced in
    # as the new batch's inputs — instead of draining the pipeline and
    # rebuilding only after the collect lands. Divergence between
    # promised and actual state (host-side EOS/stop, stop strings) is
    # reconciled at collect time by invalidating and rebuilding exactly
    # the speculated entries (the reference's OverlapWorker/FutureMap
    # design, PAPER.md §4-5). Greedy and seeded token streams are
    # byte-identical to the sync loop; implies overlap_scheduling.
    # False = today's loop, byte for byte.
    pipelined_loop: bool = False
    # Unified mixed-batch step (--unified-step,
    # docs/overlap_scheduling.md#unified-step): one ragged kernel and
    # one jitted program serve EVERY paged step — decode rows are
    # q_len=1 rows of the same ragged batch (per-row-class block
    # geometry + AMLA mul-by-add rescaling inside the one Pallas
    # kernel), the shape-signature space collapses to (pow2 row bucket
    # × pow2 token bucket) with the max_q_len axis gone, and under
    # overlap scheduling a decode chain ABSORBS prefill chunks through
    # mixed re-formed batches (scheduler.schedule_reform across phase
    # boundaries) instead of yielding — the chain_breaks
    # reason="waiting" class and the chain_under_prefill ramp knob are
    # retired (deprecated no-ops). Greedy + seeded token streams are
    # byte-identical to the flag-off engine under churn; off =
    # byte-identical legacy dispatch, kernels included.
    unified_step: bool = False
    # Persistent-slot decode batching (--decode-slot-batching, overlap
    # scheduling only): chain membership becomes slot-based, so fused
    # decode chains survive sequence finishes — a finished row is masked
    # dead (a HOLE: position frozen, KV writes to the dummy page, sampled
    # tokens discarded) instead of forcing a sync re-form, newly
    # decode-ready sequences JOIN vacant slots at chain boundaries
    # without a shape-signature change, and the batch compacts only when
    # live occupancy drops below its pow2 seq bucket. False = legacy
    # all-or-nothing chain membership, byte-identical token streams.
    decode_slot_batching: bool = False
    # Ramp policy (--chain-under-prefill): with prefill work waiting,
    # chain up to this many decode steps before yielding ONE sync pass to
    # prefill (the chain then resumes off its on-device tokens). 0 =
    # legacy: any waiting arrival forces every subsequent step through
    # the unfused sync path until the queue empties. Only meaningful with
    # overlap_scheduling; the token-throttling decode budget bounds how
    # much decode each yielded pass carries.
    chain_under_prefill: int = 0
    # In-flight microbatches for pp>1 (None → pp, the reference's depth:
    # pp_size batches running, scheduler.py:358-364). 1 forces serialized
    # launch-collect — the control arm for measuring pipeline overlap.
    pp_pipeline_depth: Optional[int] = None
    # Prompt-lookup (n-gram) speculative decoding — beyond the reference:
    # propose up to spec_k draft tokens from the most recent spec_ngram
    # match in the sequence's own history and verify them in ONE forward
    # pass (k+1 rows through the chunked-prefill machinery). Greedy
    # verification makes outputs byte-identical to plain greedy decoding
    # by construction; per-seq eligibility (temperature 0, no penalties,
    # no logprobs) gates drafts, everything else runs normally in the
    # same batch. On TPU this multiplies tokens-per-dispatch and turns
    # decode GEMVs into small GEMMs for the MXU.
    spec_decode: Optional[str] = None        # None | "ngram"
    spec_k: int = 4
    spec_ngram: int = 2
    # On-device speculation (--spec-fused, requires spec_decode="ngram";
    # docs/speculative_decoding.md#fused): draft → verify →
    # accept/reject → correction-token emission run INSIDE the jitted
    # multi-step program, so a decode chain of K sub-steps emits up to
    # K·(spec_k+1) tokens in one dispatch. The runner keeps a bounded
    # per-slot recent-token ring on device (seeded from committed tokens
    # at chain splice time, then advanced by the loop carry), a
    # vectorized n-gram match proposes drafts without host readback, and
    # verify rows ride the ragged kernel as q_len=k+1 rows with
    # on-device acceptance. Speculation and chained dispatch stop being
    # mutually exclusive: schedule_chain accepts spec rows (the
    # chain_breaks reason="spec" class is retired) and the FutureMap's
    # scheduled frontiers become token-count UPPER bounds trimmed to the
    # actual accepted counts at collect. Greedy token streams stay
    # byte-identical to host-driven spec decode AND to plain decode;
    # sampled rows keep the rejection-sampling distribution guarantee
    # (draws keyed by fold_in(seed, out_step)). Inert (warned) for
    # hybrid GDN, multimodal, pp>1 and dp>1 — those keep the host-driven
    # snapshot path. Implies overlap_scheduling; off = byte-identical
    # host-driven speculation.
    spec_fused: bool = False
    # Quantization: None | "int8" | "fp8" | "int4" (weight-only,
    # per-output-channel, XLA-fused dequant) | "w8a8" (int8 weights +
    # per-token int8 activations on the MXU) — reference quantization
    # stack SURVEY §2.6
    quantization: Optional[str] = None
    enforce_eager: bool = False           # disable donation/async tricks (debug)
    # Minimum single-seq prefill chunk (tokens) that routes through ring
    # attention when parallel.sp > 1; shorter chunks / mixed batches /
    # decode use the paged path with activations sharded over sp.
    sp_ring_threshold: int = 1024
    # Bounds on the pixel count the multimodal processor resizes images /
    # video frames into (reference --mm-processor-min/max-pixels,
    # api_server.py:488-494 → encoder_engine.py:67-74). max_pixels is the
    # operator lever that keeps large-image ViT inputs inside HBM.
    mm_processor_min_pixels: Optional[int] = None
    mm_processor_max_pixels: Optional[int] = None
    # Resolve a non-local model id via HF-hub snapshot download (file-lock
    # serialized, reference model_loader.py hub path). Off by default:
    # loads are local-path-only unless explicitly opted in.
    allow_hub_download: bool = False
    attention_impl: str = "auto"          # auto | pallas | xla
    # Performance-attribution tracing (docs/observability.md#tracing):
    # request-scoped span trees (gllm_tpu/obs/spans.py) + the per-step
    # phase/device/MFU fields on steptrace events, exported via
    # GET /trace and ``obs.dump --format chrome``. Default ON — pure
    # host dict work off the device path (the bench --tiny gate holds
    # the overhead under 2%); ``--no-tracing`` disables the span layer
    # for this engine (token streams are byte-identical either way).
    tracing: bool = True
    # ---- request-lifecycle robustness (docs/robustness.md) ----
    # Admission control: cap the serving engine's intake queue and the
    # number of resident (handle-open) requests; over-limit submits are
    # rejected (HTTP 429 with Retry-After) instead of queueing without
    # bound. 0 = unbounded (legacy).
    max_queued_requests: int = 0
    max_resident_requests: int = 0
    # Default per-request wall-clock TTL in seconds: a request still
    # waiting or still generating this long after submit is aborted with
    # finish reason "deadline". Per-request SamplingParams.deadline_s /
    # submit(deadline_s=...) override. 0 = no TTL (legacy).
    request_deadline_s: float = 0.0
    # Consecutive failed engine steps before the serving engine latches
    # "unhealthy" (readiness 503, admission closed; liveness stays up).
    # Individual failures only quarantine their own batch.
    max_step_failures: int = 3
    # Watchdog: flip readiness while the engine-thread heartbeat is
    # older than this many seconds (a hung device dispatch blocks the
    # loop inside collect). Must exceed the longest legitimate blocking
    # operation (first-dispatch XLA compiles!). 0 = watchdog off.
    watchdog_stall_s: float = 0.0
    # shutdown(drain=True): how long to wait for in-flight requests
    # before aborting them with terminal chunks.
    drain_timeout_s: float = 5.0
    # ---- self-healing recovery (docs/robustness.md#recovery-lifecycle) ----
    # Supervised in-process rebuild (--engine-recovery): when the
    # unhealthy latch fires (max_step_failures consecutive failures, an
    # engine-loop death, or a watchdog HARD stall), an EngineSupervisor
    # tears the engine down and rebuilds it in-process — /readyz reports
    # "recovering" with Retry-After, journaled retry-safe requests
    # (seeded or greedy) replay onto the rebuilt engine and continue
    # from their committed prefix, and the rebuilt engine warms from the
    # disk prefix tier + the persistent compile cache. False = today's
    # one-way latch (permanent unhealthy until process restart).
    engine_recovery: bool = False
    # Crash-loop latch: this many FAILED rebuild attempts within
    # rebuild_window_s seconds latch the permanent unhealthy state (the
    # pre-recovery behavior is the bounded fallback — never an infinite
    # rebuild loop).
    max_rebuilds: int = 3
    rebuild_window_s: float = 300.0
    # Exponential backoff between rebuild attempts: first retry waits
    # rebuild_backoff_s, doubling per failure, capped at
    # rebuild_backoff_max_s. (The first attempt runs immediately.)
    rebuild_backoff_s: float = 0.25
    rebuild_backoff_max_s: float = 30.0
    # Watchdog HARD stall: a heartbeat older than this abandons the
    # wedged engine thread and triggers the supervised rebuild (the soft
    # watchdog_stall_s threshold only flips readiness). Requires
    # engine_recovery and a running watchdog; 0 = soft flips only.
    watchdog_hard_stall_s: float = 0.0
    # Deterministic fault injection spec (gllm_tpu/faults.py grammar:
    # "point[:after_n[:count]][,...]"), armed when the serving engine
    # starts; also armable via GLLM_FAULT_INJECT. Empty = disarmed.
    fault_inject: str = ""
    # Disagg LM nodes: drop the vision tower from params after load —
    # visual embeddings arrive from the encoder fleet (reference
    # DisaggConfig.skip_visual). The engine can then only serve disagg
    # (or text-only) requests.
    skip_visual_load: bool = False
    scheduler: SchedulerConfig = dataclasses.field(default_factory=SchedulerConfig)
    cache: CacheConfig = dataclasses.field(default_factory=CacheConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)

    @property
    def max_pages_per_seq(self) -> int:
        return cdiv(self.max_model_len, self.cache.page_size)

    def validate(self) -> None:
        if self.enforce_eager:
            # The reference's enforce_eager drops CUDA-graph capture; the
            # analogues here are the async-execution tricks — chained
            # overlap decode and the fused multi-step loop. Plain
            # one-dispatch-per-step execution remains.
            if self.overlap_scheduling or self.multi_step_decode > 1:
                import logging
                logging.getLogger(__name__).warning(
                    "enforce_eager overrides overlap_scheduling/"
                    "multi_step_decode (were %s/%d) — plain per-step "
                    "execution", self.overlap_scheduling,
                    self.multi_step_decode)
            self.overlap_scheduling = False
            self.multi_step_decode = 1
            self.decode_chain_len = None
            self.ondevice_finish = False
            self.decode_slot_batching = False
            self.chain_under_prefill = 0
            self.pipelined_loop = False
            self.spec_fused = False
        if self.pipelined_loop and not self.overlap_scheduling:
            # the pipelined loop is the overlap machinery run one step
            # further ahead — chains are its primary edge; lifting the
            # flag keeps "--pipelined-loop" a one-flag opt-in
            self.overlap_scheduling = True
        if self.unified_step:
            if self.overlap_scheduling and not self.pipelined_loop:
                # absorbing a prefill chunk into a running chain IS a
                # speculative mixed re-form — the unified overlap loop
                # runs on the pipelined FutureMap machinery
                self.pipelined_loop = True
            if self.chain_under_prefill:
                # the ramp-yield policy is obsolete: chains never yield
                # to prefill under the unified step — they absorb it
                import logging
                logging.getLogger(__name__).warning(
                    "chain_under_prefill is deprecated and ignored "
                    "under --unified-step: mixed re-formed batches "
                    "absorb prefill chunks, chains never yield")
                self.chain_under_prefill = 0
        if self.chain_under_prefill < 0:
            raise ValueError("chain_under_prefill must be >= 0")
        if self.overlap_depth < 1:
            raise ValueError("overlap_depth (--inflight-depth) must be "
                             ">= 1")
        if (self.pipelined_loop or self.unified_step) \
                and self.parallel.pp > 1 and self.parallel.dp > 1:
            # Each fast path composes with pp OR dp, but the combined
            # grid would need per-replica stage pipelines driven by the
            # run-ahead loop — refuse loudly rather than silently fall
            # back to the legacy sync dispatch
            # (docs/overlap_scheduling.md#topology-matrix).
            raise ValueError(
                "--pipelined-loop/--unified-step compose with pp>1 OR "
                "dp>1, not both at once: run pp with dp=1 or dp with "
                "pp=1, or drop the flags for the legacy sync pipeline")
        if self.spec_fused:
            if self.spec_decode != "ngram":
                raise ValueError(
                    "spec_fused (--spec-fused) requires "
                    "spec_decode='ngram'")
            if self.parallel.pp > 1:
                # The fused draft+verify block is ONE device program (a
                # while_loop over sub-steps spanning the whole layer
                # stack); pipeline stages are separate per-device
                # programs, so the block cannot span them. A loud error
                # replaces the old warn-and-clear (flags must never
                # silently no-op); host-driven speculation
                # (--spec-decode ngram without --spec-fused) works
                # under pp.
                raise ValueError(
                    "--spec-fused is not supported with pp > 1: the "
                    "fused block cannot span pipeline stages — drop "
                    "--spec-fused to keep host-driven speculation")
            if self.parallel.dp > 1:
                # The dp fast path runs lockstep super-steps over ONE
                # stacked program; fused spec blocks would need stacked
                # per-replica carry state (not yet built). Loud error,
                # same rationale as the pp case above.
                raise ValueError(
                    "--spec-fused is not supported with dp > 1: fused "
                    "blocks are single-replica — drop --spec-fused to "
                    "keep host-driven speculation")
            if not self.overlap_scheduling:
                # fused draft+verify lives in the chained dispatch body —
                # lifting the flag keeps "--spec-fused" a one-flag opt-in
                # (same discipline as pipelined_loop)
                self.overlap_scheduling = True
        if self.decode_chain_len is not None:
            if self.decode_chain_len < 1:
                raise ValueError("decode_chain_len must be >= 1")
            self.multi_step_decode = self.decode_chain_len
        elif (self.spec_fused and self.multi_step_decode == 1):
            # one fused block should amortize several verify rounds per
            # dispatch; page feasibility still shortens individual blocks
            self.multi_step_decode = 8
        elif (self.ondevice_finish and self.overlap_scheduling
                and self.multi_step_decode == 1):
            # with post-EOS waste gone, the conservative single-step
            # default stops paying for itself — chain 16 steps per
            # dispatch (page feasibility still bounds each block)
            self.multi_step_decode = 16
        if not self.overlap_scheduling and not self.enforce_eager and (
                self.ondevice_finish or self.decode_chain_len is not None):
            # same silent-drop class the assigned_layers check guards:
            # the engine only forms fused chains under overlap scheduling
            import logging
            logging.getLogger(__name__).warning(
                "ondevice_finish/decode_chain_len have no effect without "
                "overlap_scheduling — fused decode chains never form")
        if self.parallel.assigned_layers is not None \
                and len(self.parallel.assigned_layers) != self.parallel.pp:
            # catch --assigned-layers with a forgotten/mismatched --pp at
            # config time (pp_runner re-checks per-stage sums later, but
            # only engages for pp > 1 — pp=1 would silently drop the flag)
            raise ValueError(
                f"assigned_layers has {len(self.parallel.assigned_layers)}"
                f" entries but pp={self.parallel.pp}")
        if self.cache.page_size <= 0:
            raise ValueError("page_size must be positive")
        if self.cache.kv_cache_dtype not in (
            "auto", "bfloat16", "float16", "float32", "fp8", "int8",
        ):
            raise ValueError(
                f"unknown kv_cache_dtype {self.cache.kv_cache_dtype!r} "
                "(choices: auto, bfloat16, float16, float32, fp8, int8)")
        if self.scheduler.max_prefill_tokens < self.cache.page_size:
            raise ValueError("max_prefill_tokens must cover at least one page")
        if self.scheduler.schedule_method not in (
            "chunked_prefill", "token_throttling", "split_pd",
        ):
            raise ValueError(
                f"unknown schedule_method {self.scheduler.schedule_method!r}")
        if self.scheduler.pool_role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"unknown pool_role {self.scheduler.pool_role!r} "
                "(choices: prefill, decode, mixed)")
        if self.quantization not in (None, "int8", "fp8", "int4",
                                     "w8a8", "fp8_block"):
            raise ValueError(
                f"unknown quantization {self.quantization!r} "
                "(choices: int8, fp8, int4, w8a8, fp8_block)")
        if self.spec_decode not in (None, "ngram"):
            raise ValueError(
                f"unknown spec_decode {self.spec_decode!r} "
                "(choices: ngram)")
        if self.spec_decode is not None:
            # May be combined with overlap_scheduling/multi_step_decode:
            # speculation then OWNS decode dispatch (schedule_chain
            # defers — drafting needs committed token values a chained
            # step leaves on device), each accepted draft replacing the
            # dispatch round trip a chain would have hidden; prefill
            # batches still pipeline through the in-flight depth.
            if self.spec_k < 1 or self.spec_ngram < 1:
                raise ValueError("spec_k and spec_ngram must be >= 1")
        if self.parallel.sp > 1 and (self.parallel.pp > 1
                                     or self.parallel.dp > 1):
            raise ValueError(
                "sp (sequence parallelism) composes with tp only; "
                "set pp = dp = 1")
        if self.max_queued_requests < 0 or self.max_resident_requests < 0:
            raise ValueError("admission limits must be >= 0 (0 = off)")
        if self.request_deadline_s < 0 or self.watchdog_stall_s < 0 \
                or self.drain_timeout_s < 0:
            raise ValueError("robustness timeouts must be >= 0")
        if self.max_step_failures < 1:
            raise ValueError("max_step_failures must be >= 1")
        if self.max_rebuilds < 1:
            raise ValueError("max_rebuilds must be >= 1")
        if self.rebuild_window_s <= 0 or self.rebuild_backoff_s < 0 \
                or self.rebuild_backoff_max_s < self.rebuild_backoff_s:
            raise ValueError(
                "rebuild_window_s must be > 0 and 0 <= rebuild_backoff_s "
                "<= rebuild_backoff_max_s")
        if self.watchdog_hard_stall_s < 0:
            raise ValueError("watchdog_hard_stall_s must be >= 0")
        if self.watchdog_hard_stall_s > 0:
            if not self.engine_recovery:
                raise ValueError(
                    "watchdog_hard_stall_s needs --engine-recovery (the "
                    "hard-stall escalation IS a supervised rebuild)")
            if self.watchdog_stall_s <= 0:
                raise ValueError(
                    "watchdog_hard_stall_s needs --watchdog-stall-s > 0 "
                    "(the watchdog thread detects the stall)")
            if self.watchdog_hard_stall_s < self.watchdog_stall_s:
                raise ValueError(
                    "watchdog_hard_stall_s must be >= watchdog_stall_s "
                    "(soft flip first, then the hard escalation)")
        if self.fault_inject:
            # fail fast on a bad spec instead of at first fire
            from gllm_tpu.faults import FaultInjector
            FaultInjector().arm(self.fault_inject)
        if self.cache.swap_policy not in ("auto", "swap", "recompute"):
            raise ValueError(
                f"unknown swap_policy {self.cache.swap_policy!r} "
                "(choices: auto, swap, recompute)")
        if self.cache.swap_policy == "swap" \
                and self.cache.kv_host_pool_gb <= 0 \
                and not self.cache.kv_host_pool_pages:
            raise ValueError(
                "swap_policy='swap' needs a host pool: set "
                "kv_host_pool_gb (--kv-host-pool-gb) > 0")
        if self.cache.kvstore_configured:
            # the lower tiers stage every restore through the host pool
            # and only cache digest-keyed prefix pages — both upper
            # layers must exist or the flags silently do nothing
            if not self.cache.enable_prefix_caching:
                raise ValueError(
                    "--kv-disk-path/--prefix-peers/--prefix-serve-port "
                    "extend the prefix cache: add "
                    "--enable-prefix-caching")
            if not self.cache.host_pool_configured:
                raise ValueError(
                    "the disk/peer prefix tiers stage restores through "
                    "the host pool: set --kv-host-pool-gb > 0")
            if self.cache.kv_disk_path and self.cache.kv_disk_gb <= 0:
                raise ValueError("kv_disk_gb (--kv-disk-gb) must be > 0 "
                                 "when --kv-disk-path is set")
            if self.cache.prefix_peers:
                # a typo'd peer must fail startup, not the first
                # scheduling probe
                from gllm_tpu.kvstore.peer import parse_peer_addr
                for a in self.cache.prefix_peers.split(","):
                    if a.strip():
                        parse_peer_addr(a)
            if self.cache.prefix_serve_port is not None \
                    and self.cache.prefix_serve_port < 0:
                raise ValueError("prefix_serve_port must be >= 0 "
                                 "(0 = ephemeral)")
