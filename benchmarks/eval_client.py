"""Shared concurrent HTTP client for the eval harnesses.

VERDICT r03 weak #6: the evals were one-connection-per-question serial
loops — correctness-adequate, useless as load generators. This helper
gives every harness the reference's eval ergonomics
(reference benchmarks/evaluate_mmlu_pro.py drives a thread pool against
the server): a thread pool with per-thread persistent connections,
bounded retries with backoff, and order-preserving results.

``serve_bench.py`` remains the source of TTFT/TPOT latency claims; this
is about saturating the server during accuracy runs so a 1k-question
eval doesn't serialize on round-trips.
"""

from __future__ import annotations

import concurrent.futures as cf
import http.client
import json
import sys
import threading
import time

_tls = threading.local()


def _conn(host: str, port: int, timeout: float):
    c = getattr(_tls, "conn", None)
    if c is None or _tls.addr != (host, port):
        if c is not None:
            try:
                c.close()
            except OSError:
                pass
        c = http.client.HTTPConnection(host, port, timeout=timeout)
        _tls.conn = c
        _tls.addr = (host, port)
    return c


def post_json(host: str, port: int, path: str, body: dict, *,
              timeout: float = 600.0, retries: int = 3) -> dict:
    """POST ``body`` as JSON; returns the parsed response. Retries
    connection errors and 5xx with exponential backoff; 4xx raise
    immediately (a malformed request never becomes valid by retrying)."""
    delay = 1.0
    for attempt in range(retries + 1):
        conn = _conn(host, port, timeout)
        try:
            conn.request("POST", path, body=json.dumps(body),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status < 400:
                return json.loads(data)
            if resp.status < 500:
                raise RuntimeError(
                    f"HTTP {resp.status} from {path}: {data[:300]!r}")
            err = f"HTTP {resp.status}"
        except (OSError, http.client.HTTPException,
                json.JSONDecodeError) as e:
            err = repr(e)
            _tls.conn = None          # drop the broken connection
        if attempt == retries:
            raise RuntimeError(f"{path} failed after {retries + 1} "
                               f"attempts: {err}")
        time.sleep(delay)
        delay = min(delay * 2, 15.0)


def map_concurrent(fn, items, *, concurrency: int = 8, label: str = "",
                   progress_every: int = 50):
    """Run ``fn(item)`` over ``items`` with a thread pool; returns results
    in input order. Progress goes to stderr every ``progress_every``
    completions."""
    results = [None] * len(items)
    done = 0
    with cf.ThreadPoolExecutor(max_workers=max(1, concurrency)) as ex:
        futs = {ex.submit(fn, it): i for i, it in enumerate(items)}
        for fut in cf.as_completed(futs):
            results[futs[fut]] = fut.result()
            done += 1
            if progress_every and done % progress_every == 0:
                print(f"[{label or 'eval'}] {done}/{len(items)}",
                      file=sys.stderr, flush=True)
    return results
