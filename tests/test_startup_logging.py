"""Startup-latency instrumentation (VERDICT r03 next #9).

The engine logs one structured ``[startup] phase=... seconds=...`` line per
startup phase (weight load, each warmup bucket compile, warmup total) — the
serving-readiness breakdown the reference gets from its CUDA-graph capture
logs (model_runner.py:1525-1615). These tests pin the lines' presence so
the instrumentation can't silently rot.
"""

import logging

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models.config import ModelConfig


def _tiny_llm():
    mcfg = ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=256, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=96, max_position=256)
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=64,
        max_num_seqs=8,
        scheduler=SchedulerConfig(max_prefill_tokens=32, max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=64))
    return LLM(config=cfg, model_cfg=mcfg)


def test_startup_phase_lines(caplog):
    with caplog.at_level(logging.INFO):
        llm = _tiny_llm()
        llm.runner.warmup()
    msgs = [r.getMessage() for r in caplog.records]
    assert any("[startup] phase=weight_load seconds=" in m for m in msgs)
    # per-bucket compile lines (decode and mixed prefill+decode variants)
    assert any("[startup] phase=warmup_bucket seqs=" in m
               and "pages=" in m for m in msgs)
    assert any("[startup] phase=warmup_bucket seqs=" in m
               and "prefill_chunk=" in m for m in msgs)
    # warmup total with bucket count
    assert any("[startup] phase=warmup seconds=" in m and "buckets=" in m
               for m in msgs)
