"""Placement: rotation filter, session affinity, prefix-digest probes.

The ROADMAP item-4 deployment shape wants the front router to place
requests where their KV prefix already lives. This module is that
placement skeleton, in three layers the router composes per request:

1. **rotation filter** — only replicas whose last ``/readyz`` probe said
   ready, that are not admin-drained, and whose breaker is closed are
   candidates (ReplicaSet.in_rotation);
2. **session affinity** — a request carrying a session key (the
   ``X-Session-Id`` header, or the OpenAI ``user`` field) sticks to the
   replica that served the session before, while that replica stays in
   rotation — a conversation's prefix cache (and KV) stays resident on
   one replica (the reference --endpoint-per-dp motivation, one level
   up);
3. **prefix affinity** — for requests whose prompt token ids are known
   up front (token-array completions), chained page digests
   (``memory_manager.prefix_digests`` — replica-independent by design)
   are probed against each candidate's prefix-store serve port with the
   peer protocol's ``has`` op; the deepest hit wins. Bounded: at most
   ``max_probes`` digests per replica, one short deadline each, failures
   score 0 and never stall placement.

Ties (and the no-affinity case) break least-loaded by active router
streams.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from collections import OrderedDict
from typing import List, Optional

from gllm_tpu.kvstore.peer import _recv_frame, _send_frame
from gllm_tpu.memory_manager import prefix_digests
from gllm_tpu.obs import metrics as obs
from gllm_tpu.router.replica import Replica, ReplicaSet

logger = logging.getLogger(__name__)

_M_AFFINITY = obs.counter(
    "gllm_router_placements_total",
    "placement decisions by rule (session = sticky session hit; "
    "prefix = digest-probe winner; load = least-loaded fallback)",
    ("rule",))

_SESSION_CAP = 4096


class PrefixAffinity:
    """Digest probes against each replica's prefix store (the item-4
    placement skeleton). Stateless per call; sockets are per-probe
    (placement is rare relative to token traffic, and a cached socket
    to a dying replica is exactly the stall this module must never
    take)."""

    def __init__(self, timeout_s: float = 0.25, max_probes: int = 4):
        self.timeout_s = float(timeout_s)
        self.max_probes = max(1, int(max_probes))

    def score(self, rep: Replica, token_ids: List[int]) -> int:
        """Number of whole prefix pages ``rep`` holds for this prompt
        (deepest chained digest it answers ``has`` for); 0 on any
        failure or when the replica advertises no prefix serve port."""
        store = (rep.info or {}).get("prefix_store") or {}
        port = store.get("serve_port")
        page_size = (rep.info or {}).get("page_size")
        if not port or not page_size:
            return 0
        try:
            # inside the try: a malformed prompt (str entries, ints
            # past 4 bytes) raises from the digest hash — any scoring
            # failure is a 0, never a router 500 (the replica will
            # reject a bad prompt with its own clean 400)
            digests = prefix_digests(list(token_ids), len(token_ids),
                                     int(page_size))
            if not digests:
                return 0
            # deepest-first: the first hit bounds every shallower
            # digest (chained digests are prefix-closed), so one hit
            # answers all
            probe = digests[-self.max_probes:]
            with socket.create_connection((rep.host, int(port)),
                                          timeout=self.timeout_s) as sock:
                sock.settimeout(self.timeout_s)
                for depth in range(len(digests), len(digests) -
                                   len(probe), -1):
                    digest = digests[depth - 1][0]
                    _send_frame(sock, {"op": "has",
                                       "digest": digest.hex()})
                    reply = _recv_frame(sock)
                    if reply and reply.get("hit"):
                        return depth
        except (OSError, ValueError, TypeError, AttributeError,
                OverflowError):
            return 0
        return 0


class Placement:
    """Per-request replica choice. Thread-safe: handler threads call
    pick() concurrently; the session map is the only shared state."""

    def __init__(self, replica_set: ReplicaSet, *,
                 session_affinity: bool = True,
                 prefix_affinity: Optional[PrefixAffinity] = None):
        self.replicas = replica_set
        self.session_affinity = session_affinity
        self.prefix_affinity = prefix_affinity
        self._lock = threading.Lock()
        self._sessions: "OrderedDict[str, str]" = OrderedDict()

    def _remember(self, session: Optional[str], addr: str) -> None:
        if not session:
            return
        with self._lock:
            self._sessions[session] = addr
            self._sessions.move_to_end(session)
            while len(self._sessions) > _SESSION_CAP:
                self._sessions.popitem(last=False)

    def session_replica(self, session: Optional[str]) -> Optional[str]:
        if not session:
            return None
        with self._lock:
            return self._sessions.get(session)

    def pick(self, session: Optional[str] = None,
             token_ids: Optional[List[int]] = None,
             exclude=(), role: Optional[str] = None) -> Optional[Replica]:
        """The replica for one placement (None = nothing in rotation).
        ``exclude`` removes replicas this stream already failed on (the
        failover path must not bounce straight back). ``role`` prefers
        the pd pool of that name (docs/pd_pools.md) — replicas
        advertising ``role`` or ``mixed`` — but degrades to the whole
        rotation when the pool is empty: a pool outage must cost
        latency, never availability."""
        candidates = [r for r in self.replicas.in_rotation()
                      if r.addr not in exclude]
        if role is not None:
            from gllm_tpu.pools import replica_role
            pooled = [r for r in candidates
                      if replica_role(r) in (role, "mixed")]
            if pooled:
                candidates = pooled
        if not candidates:
            return None
        if self.session_affinity and session:
            sticky = self.session_replica(session)
            for r in candidates:
                if r.addr == sticky:
                    # refresh the LRU slot: an ACTIVE session must not
                    # age out just because it placed long ago
                    self._remember(session, r.addr)
                    _M_AFFINITY.inc(rule="session")
                    return r
        if self.prefix_affinity is not None and token_ids:
            t0 = time.monotonic()
            scored = [(self.prefix_affinity.score(r, token_ids), r)
                      for r in candidates]
            best = max(s for s, _ in scored)
            if best > 0:
                rep = min((r for s, r in scored if s == best),
                          key=lambda r: r.active_streams)
                logger.debug("prefix placement: %s holds %d pages "
                             "(probe %.1fms)", rep.addr, best,
                             1e3 * (time.monotonic() - t0))
                self._remember(session, rep.addr)
                _M_AFFINITY.inc(rule="prefix")
                return rep
        rep = min(candidates, key=lambda r: r.active_streams)
        self._remember(session, rep.addr)
        _M_AFFINITY.inc(rule="load")
        return rep
