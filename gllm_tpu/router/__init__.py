"""Health-aware front router with journal-backed cross-replica stream
failover (docs/robustness.md#fleet-topology--failover).

The reference frames gLLM as a frontend driving a fleet of replicas
(PAPER.md §1); this package is that frontend's reliability spine. It
places OpenAI-compatible requests over N api_server replicas using each
replica's /readyz state behind per-replica circuit breakers
(gllm_tpu.utils.CircuitBreaker — the same ladder kvstore/peer.py runs
per prefix peer), keeps a stream journal (immutable submission +
delivered token ids, mirroring engine/recovery.RequestJournal), and on
replica death / crash-loop / mid-stream disconnect resumes retry-safe
streams on a surviving replica byte-identically via the api_server
continuation path — the client observes one uninterrupted stream.

Pieces:

- :mod:`gllm_tpu.router.journal` — per-stream journal + the router-side
  half of the PR 14 replay-safety predicate
- :mod:`gllm_tpu.router.replica` — replica registry: /readyz +
  /server_info health poller, breaker ladder, restart detection
- :mod:`gllm_tpu.router.placement` — rotation filter, session affinity,
  prefix-affinity digest probes (the item-4 placement skeleton)
- :mod:`gllm_tpu.router.core` — :class:`FrontRouter`: SSE proxy loop +
  failover state machine
- ``gllm_tpu/entrypoints/router_server.py`` — the HTTP entrypoint

No jax imports anywhere in the package: the router is a pure host
process and deploys on frontend nodes with no accelerator.
"""

from gllm_tpu.router.core import FrontRouter                 # noqa: F401
from gllm_tpu.router.journal import (StreamEntry,            # noqa: F401
                                     StreamJournal,
                                     router_unsafe_reason)
from gllm_tpu.router.placement import (Placement,            # noqa: F401
                                       PrefixAffinity)
from gllm_tpu.router.replica import Replica, ReplicaSet      # noqa: F401
