"""Encoder-disaggregation configuration, threaded from the entrypoints.

Mirrors the reference's explicit config object
(/root/reference/gllm/disagg/config.py): role flags consumed by the model
loader (skip_visual / skip_language) plus the LM-side coordinator knobs.
Runtime failure-injection / watchdog tuning stays in env vars like the
reference (GLLM_TPU_ENC_FAIL_FIRST_N, GLLM_TPU_DISAGG_REDISPATCH_TIMEOUT_S,
GLLM_TPU_DISAGG_MAX_REDISPATCH).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class DisaggConfig:
    # Model-loader role flags.
    skip_visual: bool = False     # LM node: no vision tower
    skip_language: bool = False   # encoder node: vision tower only

    # LM-side coordinator (None fields use defaults / derived values).
    is_lm: bool = False
    discovery_endpoint: str = ""          # "host:port"
    lm_id: Optional[str] = None
    processor_config_hash: str = ""
    advertise_host: str = "127.0.0.1"
    num_slots: int = 8
    max_vis_tokens: int = 4096            # per-slot row capacity
    # Gate B overlap: admit at meta-complete and prefill up to the first
    # unready span (reference GLLM_DISAGG_OVERLAP). Off → admit only when
    # every embedding landed.
    overlap: bool = True
