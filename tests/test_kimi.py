"""Kimi K2.5: DeepSeek-V3 backbone + MoonViT tower.

No HF class ships for Kimi (the real checkpoint uses remote code), so the
oracle splits (SURVEY.md §4 discipline):
- LM path: a hand-built kimi checkpoint whose ``language_model.*`` weights
  ARE a transformers DeepseekV3 model — text-only prompts through the
  kimi engine must be HF-greedy-identical (loader prefix handling + the
  backbone itself).
- Tower math: independent numpy oracles for the x/y-interleaved 2-D rope
  and the spatial-merge + temporal-mean pooling (the two pieces with real
  room for silent error); plus determinism / prefix-cache behavior of the
  full MM path end to end.
"""

import json
import os

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams

MEDIA = 163605   # outside the 128-token LM vocab, like the real model

TEXT = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=3,
    num_attention_heads=4, num_key_value_heads=4, intermediate_size=96,
    max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0,
    kv_lora_rank=32, qk_nope_head_dim=16, qk_rope_head_dim=8,
    v_head_dim=16, q_lora_rank=48,
    n_routed_experts=8, num_experts_per_tok=2, moe_intermediate_size=32,
    first_k_dense_replace=1, n_shared_experts=1, moe_layer_freq=1,
    routed_scaling_factor=1.5, n_group=4, topk_group=2,
    topk_method="noaux_tc", scoring_func="sigmoid", norm_topk_prob=True,
)
VISION = dict(
    vt_hidden_size=32, vt_num_hidden_layers=2, vt_num_attention_heads=4,
    vt_intermediate_size=48, patch_size=2, merge_kernel_size=[2, 2],
    init_pos_emb_height=4, init_pos_emb_width=4, init_pos_emb_time=4,
    mm_hidden_size=32, text_hidden_size=64, projector_ln_eps=1e-5,
)


@pytest.fixture(scope="module")
def kimi_ckpt(tmp_path_factory):
    from safetensors.torch import save_file
    from transformers import DeepseekV3Config, DeepseekV3ForCausalLM
    torch.manual_seed(41)
    lm = DeepseekV3ForCausalLM(DeepseekV3Config(**TEXT))
    lm.eval()
    d = str(tmp_path_factory.mktemp("tiny_kimi"))

    tensors = {f"language_model.{k}": v.contiguous()
               for k, v in lm.state_dict().items()}
    C, I = VISION["vt_hidden_size"], VISION["vt_intermediate_size"]
    ps = VISION["patch_size"]
    g = torch.Generator().manual_seed(7)

    def r(*shape, scale=0.1):
        return torch.randn(*shape, generator=g) * scale

    tensors["vision_tower.patch_embed.proj.weight"] = r(C, 3, ps, ps)
    tensors["vision_tower.patch_embed.proj.bias"] = r(C)
    tensors["vision_tower.patch_embed.pos_emb.weight"] = r(4, 4, C)
    for i in range(VISION["vt_num_hidden_layers"]):
        p = f"vision_tower.encoder.blocks.{i}."
        tensors[p + "norm0.weight"] = torch.ones(C)
        tensors[p + "norm0.bias"] = torch.zeros(C)
        tensors[p + "norm1.weight"] = torch.ones(C)
        tensors[p + "norm1.bias"] = torch.zeros(C)
        tensors[p + "wqkv.weight"] = r(3 * C, C)
        tensors[p + "wqkv.bias"] = r(3 * C)
        tensors[p + "wo.weight"] = r(C, C)
        tensors[p + "wo.bias"] = r(C)
        tensors[p + "mlp.fc0.weight"] = r(I, C)
        tensors[p + "mlp.fc0.bias"] = r(I)
        tensors[p + "mlp.fc1.weight"] = r(C, I)
        tensors[p + "mlp.fc1.bias"] = r(C)
    tensors["vision_tower.encoder.final_layernorm.weight"] = torch.ones(C)
    tensors["vision_tower.encoder.final_layernorm.bias"] = torch.zeros(C)
    k4 = 4 * C
    tensors["mm_projector.pre_norm.weight"] = torch.ones(C)
    tensors["mm_projector.pre_norm.bias"] = torch.zeros(C)
    tensors["mm_projector.proj.0.weight"] = r(k4, k4)
    tensors["mm_projector.proj.0.bias"] = r(k4)
    tensors["mm_projector.proj.2.weight"] = r(64, k4)
    tensors["mm_projector.proj.2.bias"] = r(64)
    save_file(tensors, os.path.join(d, "model.safetensors"))

    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({
            "architectures": ["KimiK25ForConditionalGeneration"],
            "text_config": TEXT,
            "vision_config": VISION,
            "media_placeholder_token_id": MEDIA,
            "eos_token_id": 0,
        }, f)
    return d, lm


def make_llm(model_dir, prefix=False):
    cfg = EngineConfig(model=model_dir, tokenizer="", dtype="float32",
                       max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=128,
                                         enable_prefix_caching=prefix))
    return LLM(config=cfg)


def hf_greedy(model, prompt_ids, n):
    ids = list(prompt_ids)
    with torch.no_grad():
        for _ in range(n):
            logits = model(torch.tensor([ids])).logits[0, -1]
            ids.append(int(logits.argmax()))
    return ids[len(prompt_ids):]


def test_kimi_text_matches_deepseek_backbone(kimi_ckpt):
    """Text-only through the kimi engine == HF DeepseekV3 greedy (loader
    language_model.* prefix + backbone parity)."""
    d, lm = kimi_ckpt
    llm = make_llm(d)
    prompts = [[7, 3, 56, 21], [99, 14, 2]]
    got = [o.output_token_ids for o in llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))]
    for p, g in zip(prompts, got):
        assert g == hf_greedy(lm, p, 8), (p, g)


def kimi_image(rng, grid=(1, 4, 4)):
    t, h, w = grid
    pix = rng.standard_normal((t * h * w, 3 * 2 * 2)).astype(np.float32)
    n_tok = (h // 2) * (w // 2)        # frame-independent (temporal pool)
    return pix, [list(grid)], n_tok


def test_kimi_mm_deterministic_and_prefix_cache(kimi_ckpt):
    d, _ = kimi_ckpt
    rng = np.random.default_rng(3)
    pix, grid, n_tok = kimi_image(rng)
    ids = [5, 9] + [MEDIA] * n_tok + [7, 30]
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    llm = make_llm(d, prefix=True)

    def run(p, g):
        return llm.generate(
            prompt_token_ids=[ids],
            mm_inputs=[{"pixel_values": p, "grid_thws": g}],
            sampling_params=sp)[0].output_token_ids

    cold = run(pix, grid)
    hits0 = llm.memory_manager.hit_tokens
    warm = run(pix, grid)
    assert warm == cold
    assert llm.memory_manager.hit_tokens > hits0
    # a DIFFERENT image with the same placeholder layout must not share
    pix_b, _, _ = kimi_image(np.random.default_rng(8))
    out_b = run(pix_b, grid)
    fresh = make_llm(d).generate(
        prompt_token_ids=[ids],
        mm_inputs=[{"pixel_values": pix_b, "grid_thws": grid}],
        sampling_params=sp)[0].output_token_ids
    assert out_b == fresh
    # visual rows actually matter: different image → different output
    # (random weights make the visual rows dominate)
    assert out_b != cold


def test_kimi_video_chunk_tpool(kimi_ckpt):
    """A t=2 chunk produces (h/2)·(w/2) tokens (temporal mean pooling) and
    runs through the engine."""
    d, _ = kimi_ckpt
    rng = np.random.default_rng(5)
    pix, grid, n_tok = kimi_image(rng, (2, 4, 4))
    assert n_tok == 4
    ids = [5] + [MEDIA] * n_tok + [9]
    llm = make_llm(d)
    out = llm.generate(
        prompt_token_ids=[ids],
        mm_inputs=[{"pixel_values": pix, "grid_thws": grid}],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=4,
                                       ignore_eos=True))[0]
    assert len(out.output_token_ids) == 4


# ---------------------------------------------------------------------------
# Tower math oracles (independent numpy derivations)
# ---------------------------------------------------------------------------

def test_kimi_rope2d_matches_complex_oracle():
    """Our cos/sin pair rotation == the reference's complex formulation
    (x/y-interleaved frequency slots), derived independently here with
    numpy complex arithmetic."""
    from gllm_tpu.models.kimi_vision import _rope2d, _rope2d_cos_sin
    import jax.numpy as jnp
    h, w, t, hd, nh = 3, 4, 2, 16, 2
    rng = np.random.default_rng(0)
    q = rng.standard_normal((t * h * w, nh, hd)).astype(np.float32)

    cos, sin = _rope2d_cos_sin(h, w, t, hd)
    got = np.asarray(_rope2d(jnp.asarray(q), jnp.asarray(cos),
                             jnp.asarray(sin)))

    # independent complex oracle
    flat = np.arange(h * w)
    x_pos, y_pos = flat % w, flat // w
    freqs = 1.0 / 10000.0 ** (np.arange(0, hd, 4)[: hd // 4] / hd)
    x_cis = np.exp(1j * np.outer(x_pos, freqs))
    y_cis = np.exp(1j * np.outer(y_pos, freqs))
    cis = np.stack([x_cis, y_cis], axis=-1).reshape(h * w, hd // 2)
    cis = np.tile(cis, (t, 1))
    qc = q.reshape(t * h * w, nh, hd // 2, 2)
    qc = qc[..., 0] + 1j * qc[..., 1]
    out = qc * cis[:, None, :]
    want = np.stack([out.real, out.imag], axis=-1).reshape(t * h * w, nh,
                                                           hd)
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-5,
                               atol=1e-5)


def test_kimi_tpool_merge_oracle():
    """Spatial 2×2 merge + temporal mean == a direct per-output-token numpy
    average over the (kh, kw) patch block across frames."""
    from gllm_tpu.models import kimi_vision
    t, h, w, C = 2, 4, 6, 8
    kh = kw = 2
    rng = np.random.default_rng(1)
    x = rng.standard_normal((t * h * w, C)).astype(np.float32)

    merged = x.reshape(t, h // kh, kh, w // kw, kw, C) \
              .transpose(0, 1, 3, 2, 4, 5).mean(axis=0) \
              .reshape((h // kh) * (w // kw), kh * kw, C)

    want = np.zeros_like(merged)
    grid = x.reshape(t, h, w, C)
    for oi in range(h // kh):
        for oj in range(w // kw):
            block = grid[:, oi * kh:(oi + 1) * kh, oj * kw:(oj + 1) * kw]
            want[oi * (w // kw) + oj] = block.mean(axis=0).reshape(
                kh * kw, C)
    np.testing.assert_allclose(merged, want, rtol=1e-6, atol=1e-6)


def test_kimi_tool_parser():
    from gllm_tpu.entrypoints.tool_parsers import KimiToolParser
    text = ("sure<|tool_calls_section_begin|>"
            "<|tool_call_begin|>functions.get_weather:0"
            "<|tool_call_argument_begin|>{\"city\": \"SF\"}"
            "<|tool_call_end|><|tool_calls_section_end|>")
    content, calls = KimiToolParser().parse(text)
    assert content == "sure"
    assert len(calls) == 1
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "SF"}
