"""Shared multiple-choice answer extraction for the MMLU-Pro / MMMU
harnesses (one implementation so the scorers cannot drift)."""

import re


def extract_choice(text):
    """Priority ladder:
    1. explicit "answer is X" / "Answer: X"
    2. "option X" / "choice X"
    3. reply leading with the letter then punctuation/EOL ("B.", "(C)")
    4. leading letter + copula ("A is correct") — accepts A/I here
       because the verb disambiguates from English prose
    5. leading letter + space for the unambiguous letters B-H, J
    6. first standalone B-H/J anywhere (A/I excluded: they are common
       English words and would be scored as choices)
    """
    t = (text or "").strip()
    m = re.search(r"answer\s*(?:is|:)?\s*\*{0,2}\(?([A-Ja-j])\b", t,
                  re.IGNORECASE)
    if m:
        return m.group(1).upper()
    m = re.search(r"(?:option|choice)\s*\(?([A-Ja-j])\b", t, re.IGNORECASE)
    if m:
        return m.group(1).upper()
    m = re.match(r"\(?([A-Ja-j])\)?(?:[.,:)]|$)", t)
    if m:
        return m.group(1).upper()
    # "would/should/could" belong to first-person prose ("I would say B"),
    # so only the copulas disambiguate a leading A/I as an answer
    m = re.match(r"([A-Ja-j])\s+(?:is|was|seems)\b", t)
    if m:
        return m.group(1).upper()
    m = re.match(r"([B-HJb-hj])\s", t)
    if m:
        return m.group(1).upper()
    m = re.search(r"\b([B-HJ])\b", t)
    return m.group(1) if m else None
