"""MoE model definition for the registry."""

from __future__ import annotations

from gllm_tpu.models.registry import ModelDef


def moe_def() -> ModelDef:
    from gllm_tpu.models import loader, moe
    from gllm_tpu.parallel.shardings import moe_param_specs
    return ModelDef(
        family="moe",
        init_params=moe.init_params,
        forward=moe.forward,
        compute_logits=moe.compute_logits,
        make_rope_table=moe.make_rope_table,
        load_params=loader.load_moe_params,
        init_kv_cache=moe.init_kv_cache,
        param_specs=moe_param_specs,
    )
