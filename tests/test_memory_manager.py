"""Unit tests: IDAllocator, paged MemoryManager, prefix cache."""

import pytest

from gllm_tpu.id_allocator import IDAllocator
from gllm_tpu.memory_manager import MemoryManager, PrefixMemoryManager
from gllm_tpu.sampling_params import SamplingParams
from gllm_tpu.sequence import Sequence


def make_seq(seq_id, n_tokens, start=0):
    return Sequence(seq_id, list(range(start, start + n_tokens)),
                    SamplingParams(max_tokens=8))


class TestIDAllocator:
    def test_fifo(self):
        a = IDAllocator(4)
        assert [a.allocate() for _ in range(4)] == [0, 1, 2, 3]
        with pytest.raises(RuntimeError):
            a.allocate()
        a.free(2)
        a.free(0)
        assert a.allocate() == 2  # FIFO: freed first, reused first
        assert a.allocate() == 0

    def test_targeted(self):
        a = IDAllocator(4, start=10)
        a.allocate_id(12)
        assert not a.is_free(12)
        assert a.num_free == 3
        a.free(12)
        with pytest.raises(RuntimeError):
            a.free(12)


class TestMemoryManager:
    def test_alloc_free(self):
        mm = MemoryManager(num_pages=9, page_size=4)  # 8 usable
        seq = make_seq(0, 10)
        assert mm.pages_needed(seq, 10) == 3
        mm.allocate_seq_pages(seq, 10)
        assert len(seq.page_table) == 3
        assert mm.num_free_pages == 5
        assert mm.dummy_page not in seq.page_table
        # decode growth: token 11,12 fit page 3; token 13 needs a new page
        seq.num_computed_tokens = 10
        assert mm.pages_needed(seq, 2) == 0
        assert mm.pages_needed(seq, 3) == 1
        mm.free_seq(seq)
        assert mm.num_free_pages == 8

    def test_exhaustion(self):
        mm = MemoryManager(num_pages=3, page_size=4)
        seq = make_seq(0, 8)
        assert not mm.can_allocate(mm.pages_needed(seq, 9))
        assert mm.can_allocate(mm.pages_needed(seq, 8))


class TestPrefixCache:
    def test_hit_after_registration(self):
        mm = PrefixMemoryManager(num_pages=32, page_size=4)
        a = make_seq(0, 14)
        assert mm.match_prefix(a) == 0
        mm.allocate_seq_pages(a, 14)
        a.num_computed_tokens = 14
        mm.register_computed_pages(a)  # pages 0..2 full (12 tokens)

        b = make_seq(1, 14)  # identical prompt
        hit = mm.match_prefix(b)
        assert hit == 12  # 3 full pages; page 4 partial not cacheable
        assert b.page_table == a.page_table[:3]
        assert b.num_computed_tokens == 12
        # shared pages ref-counted
        assert mm.ref_count[a.page_table[0]] == 2

    def test_whole_prompt_cached_leaves_one_token(self):
        mm = PrefixMemoryManager(num_pages=32, page_size=4)
        a = make_seq(0, 8)
        mm.allocate_seq_pages(a, 8)
        a.num_computed_tokens = 8
        mm.register_computed_pages(a)
        b = make_seq(1, 8)
        # prompt is exactly 2 pages but only page 0 may be reused: at least
        # one token must be computed to produce logits.
        assert mm.match_prefix(b) == 4

    def test_cache_survives_refcount_zero_until_remint(self):
        mm = PrefixMemoryManager(num_pages=8, page_size=4)  # 7 usable
        a = make_seq(0, 9)
        mm.allocate_seq_pages(a, 9)
        a.num_computed_tokens = 9
        mm.register_computed_pages(a)
        pages_a = list(a.page_table)
        mm.free_seq(a)
        assert mm.num_free_pages == 7
        # Still hits: freed pages keep their cache identity.
        b = make_seq(1, 9)
        assert mm.match_prefix(b) == 8
        assert b.page_table == pages_a[:2]
        mm.free_seq(b)

        # Exhaust the allocator with unrelated content → pages re-minted,
        # stale keys dropped.
        c = make_seq(2, 28, start=1000)
        mm.allocate_seq_pages(c, 28)
        d = make_seq(3, 9)
        assert mm.match_prefix(d) == 0

    def test_divergent_prompt_partial_hit(self):
        mm = PrefixMemoryManager(num_pages=32, page_size=4)
        a = make_seq(0, 12)
        mm.allocate_seq_pages(a, 12)
        a.num_computed_tokens = 12
        mm.register_computed_pages(a)
        b = Sequence(1, list(range(8)) + [99, 98, 97, 96, 95],
                     SamplingParams())
        assert mm.match_prefix(b) == 8  # first two pages match, third diverges

    def test_decode_pages_registered_incrementally(self):
        mm = PrefixMemoryManager(num_pages=32, page_size=4)
        a = make_seq(0, 6)
        mm.allocate_seq_pages(a, 6)
        a.num_computed_tokens = 6
        mm.register_computed_pages(a)
        # decode 3 tokens → 9 total, page 1 (tokens 4..7) becomes full
        for t in (100, 101, 102):
            a.append_token(t)
        mm.allocate_seq_pages(a, 3)
        a.num_computed_tokens = 9
        mm.register_computed_pages(a)
        b = Sequence(1, list(range(6)) + [100, 101, 102], SamplingParams())
        assert mm.match_prefix(b) == 8


def test_pt_cache_invalidated_on_preempt_and_rollback():
    """The builder's cached np page-table row must never survive a shrink:
    a same-length regrow with different page ids (preempt → re-admit)
    would otherwise write KV into pages owned by other sequences."""
    import jax
    import numpy as np

    from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
    from gllm_tpu.runner.prepare import BatchBuilder
    from gllm_tpu.sampling_params import SamplingParams
    from gllm_tpu.scheduler import ScheduledBatch, ScheduledSeq
    from gllm_tpu.sequence import Sequence

    cfg = EngineConfig(max_model_len=64, max_num_seqs=8,
                       scheduler=SchedulerConfig(max_prefill_tokens=32,
                                                 max_decode_seqs=8),
                       cache=CacheConfig(page_size=4, num_pages=32))
    b = BatchBuilder(cfg, 4, vocab_size=128)
    seq = Sequence(0, [1, 2, 3, 4, 5, 6, 7], SamplingParams(max_tokens=4))
    seq.page_table = [3, 4]
    seq.num_computed_tokens = 0
    key = jax.random.key(0)
    sb = ScheduledBatch([ScheduledSeq(seq, 7, 0)])
    batch, _, _ = b.build(sb, key)
    assert list(np.asarray(batch.attn.page_table)[0][:2]) == [3, 4]

    seq.preempt()
    seq.page_table = [9, 10]          # same length, different pages
    seq.num_computed_tokens = 0
    batch, _, _ = b.build(ScheduledBatch([ScheduledSeq(seq, 7, 0)]), key)
    assert list(np.asarray(batch.attn.page_table)[0][:2]) == [9, 10]
