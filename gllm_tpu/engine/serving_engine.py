"""Threaded serving core: continuous-batching loop + per-request streams.

The reference splits this across PipeAsyncLLM (asyncio streams,
/root/reference/gllm/async_llm_engine.py:11-139) and the worker processes it
talks to over zmq. Our single-controller design needs neither asyncio nor
IPC: one engine thread owns the scheduler + runner and runs the continuous
batching loop; HTTP handler threads submit requests through a thread-safe
queue and block on per-sequence output queues (SSE streams one queue item
per token). Client disconnects abort the sequence mid-flight, matching the
reference's disconnect→abort propagation.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import List, Optional

from gllm_tpu.engine.llm import LLM
from gllm_tpu.obs import metrics as obs
from gllm_tpu.sampling_params import SamplingParams

logger = logging.getLogger(__name__)

_M_SUBMITTED = obs.counter("gllm_requests_submitted_total",
                           "requests submitted to the serving engine")
_M_ACTIVE = obs.gauge("gllm_requests_active",
                      "requests with an open output stream")
_M_ABORTED = obs.counter("gllm_requests_aborted_total",
                         "requests aborted (client disconnect or error)")


@dataclasses.dataclass
class StreamChunk:
    token_id: Optional[int]
    text: str
    finish_reason: Optional[str]
    # cumulative counts for usage reporting
    num_prompt_tokens: int = 0
    num_output_tokens: int = 0
    # (chosen_logprob, top_ids, top_logprobs) for this token, when the
    # request asked for logprobs
    logprob: Optional[tuple] = None
    # full per-position prompt logprobs, attached on the finishing chunk
    prompt_logprobs: Optional[list] = None
    # authoritative full output text on the finishing chunk (stop-string
    # truncation may shorten it relative to the streamed deltas)
    final_text: Optional[str] = None


class RequestHandle:
    def __init__(self, seq_id: int, prompt_len: int):
        self.seq_id = seq_id
        self.prompt_len = prompt_len
        self.chunks: "queue.Queue[StreamChunk]" = queue.Queue()

    def __iter__(self):
        while True:
            chunk = self.chunks.get()
            yield chunk
            if chunk.finish_reason is not None:
                return


def deliver_output(llm: LLM, out, handle: RequestHandle,
                   emitted: dict) -> None:
    """Turn one SeqOutput into a StreamChunk on the request's queue
    (shared by the single-host and multi-host serving engines)."""
    text = ""
    final_text = None
    if llm.tokenizer is not None:
        # the engine step may already have detokenized (stop strings) —
        # emit the delta of seq.output_text beyond what this handle
        # already streamed
        if out.new_token_id is not None:
            llm._stream_detokenize(out.seq)
        if out.finish_reason is not None:
            final_text = llm._finalize(out.seq).text
        full = out.seq.output_text
        text = full[emitted.get(out.seq.seq_id, 0):]
        emitted[out.seq.seq_id] = len(full)
    if out.new_token_id is not None or out.finish_reason:
        lp = None
        if out.new_token_id is not None and out.seq.output_logprobs:
            lp = out.seq.output_logprobs[-1]
        handle.chunks.put(StreamChunk(
            token_id=out.new_token_id,
            text=text,
            finish_reason=out.finish_reason,
            num_prompt_tokens=out.seq.prompt_len,
            num_output_tokens=out.seq.num_output_tokens,
            logprob=lp,
            prompt_logprobs=(out.seq.prompt_logprobs
                             if out.finish_reason else None),
            final_text=final_text))
    if out.finish_reason is not None:
        emitted.pop(out.seq.seq_id, None)


class ServingEngine:
    """Owns the LLM on a dedicated thread; thread-safe submit/abort."""

    def __init__(self, llm: LLM):
        self.llm = llm
        self._intake: "queue.Queue" = queue.Queue()
        self._handles: dict[int, RequestHandle] = {}
        self._seqs: dict[int, object] = {}
        self._emitted: dict[int, int] = {}   # seq_id → chars streamed
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gllm-engine")
        self._thread.start()

    # ---- client-facing (any thread) ---------------------------------------

    def submit(self, token_ids: List[int],
               sampling_params: SamplingParams,
               mm_input: Optional[dict] = None,
               disagg_items: Optional[list] = None,
               target_dp: Optional[int] = None) -> RequestHandle:
        sampling_params.validate()
        mm_state = None
        if mm_input:
            # Hashing + position building over full pixel arrays is
            # hundreds of ms for big images — do it before taking the
            # engine-wide lock.
            from gllm_tpu.engine.mm import build_mm_state
            mm_state = build_mm_state(token_ids, self.llm.model_cfg,
                                      **mm_input)
        with self._lock:
            seq = self.llm._allocate_seq(token_ids, sampling_params)
            seq.mm = mm_state
            if target_dp is not None:
                # per-DP-endpoint pinning (reference --endpoint-per-dp,
                # llm_engine.py:121-133 + sequence.py:79-83): the endpoint
                # that received the request pins its KV/prefix-cache to
                # that replica
                seq.target_dp = target_dp
            if disagg_items is not None:
                # skeleton request → coordinator (gate A admits it later)
                seq._disagg_items = disagg_items
            handle = RequestHandle(seq.seq_id, len(token_ids))
            self._handles[seq.seq_id] = handle
            self._seqs[seq.seq_id] = seq
            _M_SUBMITTED.inc()
            _M_ACTIVE.set(len(self._handles))
        self._intake.put(seq)
        self._wake.set()
        return handle

    def abort(self, seq_id: int) -> None:
        self.llm.abort(seq_id)
        self._wake.set()

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5)

    # ---- engine thread ----------------------------------------------------

    def _run(self) -> None:
        llm = self.llm
        while not self._stop:
            drained = False
            while True:
                try:
                    seq = self._intake.get_nowait()
                except queue.Empty:
                    break
                try:
                    items = getattr(seq, "_disagg_items", None)
                    if items is not None:
                        llm.submit_disagg(seq, items)
                    else:
                        llm.add_seq(seq)
                except ValueError as e:
                    self._deliver_error(seq.seq_id, str(e))
                drained = True
            if not llm.has_unfinished:
                if not drained:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                continue
            try:
                outputs = llm.step()
            except Exception:
                logger.exception("engine step failed")
                self._fail_all()
                continue
            for out in outputs:
                handle = self._handles.get(out.seq.seq_id)
                if handle is None:
                    continue
                deliver_output(llm, out, handle, self._emitted)
                if out.finish_reason is not None:
                    with self._lock:
                        self._handles.pop(out.seq.seq_id, None)
                        self._seqs.pop(out.seq.seq_id, None)
                        _M_ACTIVE.set(len(self._handles))
                    self._emitted.pop(out.seq.seq_id, None)
            # aborted sequences never produce a SeqOutput → close their
            # streams here
            self._reap_aborted()

    def _reap_aborted(self):
        with self._lock:
            dead = [sid for sid, seq in self._seqs.items()
                    if seq.is_finished and sid in self._handles]
            for sid in dead:
                self._seqs.pop(sid, None)
        for sid in dead:
            self._deliver_error(sid, "abort")

    def _deliver_error(self, seq_id: int, reason: str) -> None:
        with self._lock:
            handle = self._handles.pop(seq_id, None)
            _M_ACTIVE.set(len(self._handles))
        if handle is not None:
            _M_ABORTED.inc()
            handle.chunks.put(StreamChunk(None, "", reason or "error"))

    def _fail_all(self) -> None:
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
            _M_ACTIVE.set(0)
        if handles:
            _M_ABORTED.inc(len(handles))
        for h in handles:
            h.chunks.put(StreamChunk(None, "", "error"))
