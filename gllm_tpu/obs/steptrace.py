"""Ring-buffer step-trace event log (stdlib only).

Every engine iteration appends one small dict (kind, batch size, token
counts, wall ms, ...) to a fixed-capacity ring; compile events, chain
breaks, and pp stage dispatches ride the same ring. The api_server dumps
it as JSON (``GET /steptrace``), bench.py summarizes the measured-pass
window into its metrics snapshot, and ``python -m gllm_tpu.obs.dump``
pretty-prints a saved JSONL for post-mortems.

The round-5 "18/59 decode steps running unfused at 90.8 ms vs 11.2 ms"
finding took an afternoon of grepping ``docs/onchip_r05/*.out``; with
this ring it is ``summarize(TRACE.events())`` — one call.

Overhead: one dict + one list slot assignment per ENGINE iteration (not
per token, not per layer), behind a lock only the host ever takes. No jax
import anywhere in this module.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional

__all__ = ["StepTrace", "TRACE", "summarize"]

# Step-event kinds recorded by the engine/runner instrumentation:
#   prefill      - step whose batch carries at least one prefill chunk
#                  (retired under --unified-step: see unified_step)
#   decode       - single-step pure-decode dispatch (the UNfused path;
#                  retired under --unified-step: see unified_step)
#   unified_step - one unified mixed-batch dispatch (--unified-step,
#                  docs/overlap_scheduling.md#unified-step): the single
#                  step kind replacing prefill/decode when the flag is
#                  on — the ``mix`` field ("decode" | "mixed") keeps the
#                  composition readable (summarize() folds mix=decode
#                  into the unfused-decode accounting and reports
#                  mixed_step_frac over the window)
#   fused_block  - multi-step decode block (one dispatch, K sub-steps);
#                  under fused on-device speculation
#                  (config.spec_fused) the event also carries
#                  ``k_drafted`` / ``k_accepted`` (draft rows proposed /
#                  accepted on device) and ``tokens`` counts the
#                  actually-committed emission (up to K·(spec_k+1))
#   pp_stage     - one pipeline-stage dispatch of a microbatch
#   compile      - first dispatch of a new (shape-bucket, static-flag)
#                  signature (an XLA compile unless the persistent cache
#                  already held it)
#   chain_break  - overlap scheduling failed to extend a decode chain;
#                  carries a ``reason`` field (docs/overlap_scheduling.md
#                  taxonomy): waiting (prefill pressure / unseated ready
#                  seqs), pages (KV pool), shape (compaction, non-decode
#                  batch, host-work features), spec (host-driven
#                  speculation owns dispatch — retired, zero, under
#                  --spec-fused), finish (legacy membership loss — zero under
#                  --decode-slot-batching), reform (unified step: the
#                  chain re-formed through a mixed/grown batch instead
#                  of waiting — 'waiting' is retired, zero with
#                  --unified-step on)
#   fault        - a robustness event (docs/robustness.md): an injected
#                  fault point fired (``point`` field names it), the
#                  watchdog detected a stale heartbeat
#                  (point=dispatch_stall_detected), or the engine latched
#                  unhealthy (point=engine_unhealthy)
#   quarantine   - a step exception was isolated: the failed dispatch's
#                  sequences were aborted (``num_seqs``), everything else
#                  rescheduled
#   prefix       - one prefix-cache admission probe
#                  (PrefixMemoryManager.match_prefix): ``query_tokens``,
#                  ``hit_tokens``, and ``pages`` — claimed page counts
#                  keyed by the serving tier (hbm/host/disk/peer,
#                  docs/kv_offload.md)
#   loop_stall   - the pipelined engine loop failed to run further ahead
#                  (config.pipelined_loop); ``reason``: readback (the
#                  next step needs host-committed state), rebuild
#                  (promised-vs-actual divergence invalidated speculated
#                  entries — ``invalidated`` counts them), pages (no KV
#                  room to speculate), depth (the overlap_depth cap was
#                  binding); ``depth`` = in-flight entries at the stall
#
# Step events (prefill/decode/fused_block) additionally carry the
# performance-attribution fields (docs/observability.md#tracing):
# ``ph`` = host wall by engine phase {schedule, build, dispatch,
# collect} in ms, ``step_wall_ms`` = schedule-start → collect-end,
# ``dev_ms`` = device wall attributed back to the launching step
# (block-until-ready delta at collect), and optional ``mfu`` /
# ``hbm_gbps`` estimates from the step FLOPs model (obs/spans.py).
STEP_KINDS = ("prefill", "decode", "unified_step", "fused_block",
              "pp_stage", "compile", "chain_break", "fault",
              "quarantine", "prefix", "loop_stall", "recovery")
# recovery (config.engine_recovery, docs/robustness.md#recovery-
# lifecycle) event phases: begin (latch handed to the supervisor),
# partition (streams split into replayable vs dropped), rebuild_fail
# (one factory attempt raised; backoff doubles), ready (rebuilt engine
# adopted — carries recovery_s/replayed/dropped), crash_loop (K failed
# rebuilds within the window → permanent unhealthy).
RECOVERY_PHASES = ("begin", "partition", "rebuild_fail", "ready",
                   "crash_loop")
CHAIN_BREAK_REASONS = ("waiting", "pages", "shape", "spec", "finish",
                       "reform")
LOOP_STALL_REASONS = ("readback", "rebuild", "pages", "depth")


class StepTrace:
    """Fixed-capacity ring of event dicts with monotonically increasing
    sequence numbers (``mark()``/``events(since=...)`` bracket a window
    even across rollover)."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(os.environ.get("GLLM_OBS_TRACE_CAP", "8192"))
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._buf: List[Optional[dict]] = [None] * capacity
        self._next_seq = 0               # total events ever recorded
        self._lock = threading.Lock()
        self._t0 = time.monotonic()

    def record(self, kind: str, **fields) -> None:
        ev = {"seq": 0, "t": 0.0, "kind": kind}
        ev.update(fields)
        with self._lock:
            ev["seq"] = self._next_seq
            ev["t"] = round(time.monotonic() - self._t0, 6)
            self._buf[self._next_seq % self.capacity] = ev
            self._next_seq += 1

    def mark(self) -> int:
        """Current sequence number; pass to ``events(since=...)`` to read
        only what was recorded after this point."""
        with self._lock:
            return self._next_seq

    @property
    def t0(self) -> float:
        """The ring's monotonic epoch — event ``t`` fields are relative
        to this; the chrome exporter rebases span timestamps onto it."""
        with self._lock:
            return self._t0

    def __len__(self) -> int:
        with self._lock:
            return min(self._next_seq, self.capacity)

    @property
    def dropped(self) -> int:
        """Events lost to rollover since construction/clear."""
        with self._lock:
            return max(0, self._next_seq - self.capacity)

    def events(self, since: int = 0, kinds: Optional[Iterable[str]] = None
               ) -> List[dict]:
        with self._lock:
            first = max(since, self._next_seq - self.capacity)
            out = [self._buf[s % self.capacity]
                   for s in range(first, self._next_seq)]
        if kinds is not None:
            ks = set(kinds)
            out = [e for e in out if e["kind"] in ks]
        return out

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._next_seq = 0
            self._t0 = time.monotonic()

    def to_jsonl(self, path: str, since: int = 0) -> int:
        evs = self.events(since)
        with open(path, "w") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")
        return len(evs)


TRACE = StepTrace()


def summarize(events: List[dict]) -> dict:
    """Attribute wall time by step kind over a window of events.

    Returns a machine-readable blob answering "where did the measured
    pass go": per-kind {steps, wall_ms, tokens, ms_per_step}, fused
    decode sub-step totals, the unfused share of decode wall time (the
    round-5 18/59 class of finding), and compile/chain-break counts.
    """
    kinds: Dict[str, dict] = {}
    fused_steps = unfused_steps = 0
    fused_ms = unfused_ms = 0.0
    total_ms = 0.0
    # unified-step composition (--unified-step): collected step events
    # vs the share of them that carried at least one prefill row
    step_events = unified_mixed = 0
    compiles = chain_breaks = 0
    break_reasons: Dict[str, int] = {}
    faults_total = quarantines = 0
    fault_points: Dict[str, int] = {}
    # self-healing recovery (config.engine_recovery): completed
    # supervised rebuilds over the window, requests replayed across
    # them, failed rebuild attempts, and total latch-to-ready wall
    recoveries = rebuild_failures = requests_replayed = 0
    recovery_s_total = 0.0
    # pipelined-loop stalls (loop_stall events) + the sustained run-ahead
    # depth (the ``inflight`` field step events carry)
    loop_stalls = 0
    stall_reasons: Dict[str, int] = {}
    inflight_sum = inflight_n = 0
    # on-device finish attribution (fused_block events carry k_exec /
    # dead_substeps when config.ondevice_finish is on): wasted sub-step
    # share of all executed row-sub-steps over the window
    dead_rows = exec_rows = 0
    # fused on-device speculation (config.spec_fused; fused_block
    # events carry k_drafted / k_accepted): window acceptance rate +
    # committed tokens per device dispatch
    spec_drafted = spec_accepted = 0
    total_tokens = dispatches = 0
    # prefix-cache attribution: per-window hit rate + tier split
    pfx_queries = pfx_query_tokens = pfx_hit_tokens = 0
    pfx_pages: Dict[str, int] = {}
    # engine-loop phase breakdown + device-wall attribution (events
    # carrying ``ph``/``dev_ms`` — docs/observability.md#tracing)
    host_phase: Dict[str, float] = {}
    dev_by_kind: Dict[str, float] = {}
    dev_total = hidden_total = 0.0
    mfu_dev = hbm_dev = 0.0          # Σ(estimate · dev_ms) numerators
    mfu_seen = hbm_seen = False
    t_first_start = t_last_end = None
    for e in events:
        k = e["kind"]
        if k == "prefix":
            pfx_queries += 1
            pfx_query_tokens += int(e.get("query_tokens", 0))
            pfx_hit_tokens += int(e.get("hit_tokens", 0))
            for tier, n in (e.get("pages") or {}).items():
                pfx_pages[tier] = pfx_pages.get(tier, 0) + int(n)
            continue
        if k == "compile":
            compiles += 1
            continue
        if k == "chain_break":
            chain_breaks += 1
            r = e.get("reason", "unknown")
            break_reasons[r] = break_reasons.get(r, 0) + 1
            continue
        if k == "fault":
            faults_total += 1
            p = e.get("point", "unknown")
            fault_points[p] = fault_points.get(p, 0) + 1
            continue
        if k == "quarantine":
            quarantines += 1
            continue
        if k == "recovery":
            ph_name = e.get("phase", "")
            if ph_name == "ready":
                recoveries += 1
                requests_replayed += int(e.get("replayed", 0))
                if e.get("recovery_s") is not None:
                    recovery_s_total += float(e["recovery_s"])
            elif ph_name == "rebuild_fail":
                rebuild_failures += 1
            continue
        if k == "loop_stall":
            loop_stalls += 1
            r = e.get("reason", "unknown")
            stall_reasons[r] = stall_reasons.get(r, 0) + 1
            continue
        if k == "pp_stage":
            continue                     # dispatch-side only; no wall
        if e.get("inflight") is not None:
            inflight_sum += int(e["inflight"])
            inflight_n += 1
        row = kinds.setdefault(k, {"steps": 0, "wall_ms": 0.0,
                                   "tokens": 0})
        row["steps"] += 1
        wall = float(e.get("wall_ms", 0.0))
        row["wall_ms"] += wall
        total_ms += wall
        row["tokens"] += int(e.get("tokens", 0))
        total_tokens += int(e.get("tokens", 0))
        dispatches += 1
        if e.get("k_drafted") is not None:
            spec_drafted += int(e["k_drafted"])
            spec_accepted += int(e.get("k_accepted", 0))
        ph = e.get("ph")
        if isinstance(ph, dict):
            for name, ms in ph.items():
                host_phase[name] = host_phase.get(name, 0.0) + float(ms)
            dev = float(e.get("dev_ms", 0.0))
            dev_by_kind[k] = dev_by_kind.get(k, 0.0) + dev
            dev_total += dev
            coll = float(ph.get("collect", wall))
            hidden_total += max(0.0, dev - coll)
            if e.get("mfu") is not None:
                mfu_seen = True
                mfu_dev += float(e["mfu"]) * dev
            if e.get("hbm_gbps") is not None:
                hbm_seen = True
                hbm_dev += float(e["hbm_gbps"]) * dev
            start = float(e["t"]) - float(
                e.get("step_wall_ms", wall)) / 1e3
            if t_first_start is None or start < t_first_start:
                t_first_start = start
            if t_last_end is None or float(e["t"]) > t_last_end:
                t_last_end = float(e["t"])
        step_events += 1
        if k == "decode" or (k == "unified_step"
                             and e.get("mix") == "decode"):
            unfused_steps += 1
            unfused_ms += wall
        elif k == "unified_step":
            unified_mixed += 1
        elif k == "fused_block":
            fused_steps += int(e.get("k", 1))
            fused_ms += wall
            if "dead_substeps" in e:
                dead_rows += int(e["dead_substeps"])
                exec_rows += (int(e.get("k_exec", e.get("k", 1)))
                              * int(e.get("num_seqs", 0)))
    for row in kinds.values():
        row["wall_ms"] = round(row["wall_ms"], 2)
        row["ms_per_step"] = round(row["wall_ms"] / row["steps"], 2)
    decode_ms = fused_ms + unfused_ms
    # window wall: first step's schedule-start → last step's collect-end
    elapsed_ms = ((t_last_end - t_first_start) * 1e3
                  if t_first_start is not None
                  and t_last_end > t_first_start else 0.0)
    return {
        "by_kind": kinds,
        "decode_steps_unfused": unfused_steps,
        "decode_substeps_fused": fused_steps,
        "unfused_decode_wall_frac": (round(unfused_ms / decode_ms, 4)
                                     if decode_ms else None),
        # unfused share of the WHOLE window's wall (prefill included) —
        # the regression class bench.py promotes to its result JSON
        "unfused_frac": (round(unfused_ms / total_ms, 4)
                         if total_ms else None),
        # wasted (dead-row) sub-step share of executed fused-block work;
        # None when no block reported finish steps (ondevice_finish off)
        "dead_substep_frac": (round(dead_rows / exec_rows, 4)
                              if exec_rows else None),
        # fused on-device speculation (config.spec_fused): window draft
        # acceptance rate (None when no block drafted) and committed
        # tokens per collected device dispatch — the dispatch-
        # amortization headline the fused path must raise
        "spec_accept_rate": (round(spec_accepted / spec_drafted, 4)
                             if spec_drafted else None),
        "tokens_per_dispatch": (round(total_tokens / dispatches, 2)
                                if dispatches else None),
        # unified step (--unified-step): share of collected step
        # dispatches that were MIXED unified batches (prefill rows
        # riding the decode stream — chains absorbing arrivals); None
        # when the window saw no unified_step events (flag off)
        "mixed_step_frac": (round(unified_mixed / step_events, 4)
                            if step_events and "unified_step" in kinds
                            else None),
        # per-window prefix-cache hit rate by tier (None when the window
        # saw no admission probes — prefix caching off or pure decode)
        "prefix": ({
            "queries": pfx_queries,
            "query_tokens": pfx_query_tokens,
            "hit_tokens": pfx_hit_tokens,
            "hit_rate": (round(pfx_hit_tokens / pfx_query_tokens, 4)
                         if pfx_query_tokens else 0.0),
            "pages_by_tier": pfx_pages,
        } if pfx_queries else None),
        # ---- performance attribution (docs/observability.md#tracing;
        # None/{} when the window's events predate the tracing layer) --
        # host wall by engine-loop phase over the window
        "host_ms_by_phase": ({k: round(v, 2)
                              for k, v in host_phase.items()}
                             if host_phase else None),
        # device wall (block-until-ready deltas) attributed by step kind
        "device_ms_by_kind": ({k: round(v, 2)
                               for k, v in dev_by_kind.items()}
                              if dev_by_kind else None),
        # share of device wall hidden under host work (1 = the host
        # never blocked on the device; 0 = fully synchronous)
        "overlap_efficiency": (round(hidden_total / dev_total, 4)
                               if dev_total > 0 else None),
        # share of the window's wall clock with the device idle — the
        # gLLM bubble ratio, reproduced from engine-side attribution
        "bubble_frac": (round(max(0.0, 1.0 - dev_total / elapsed_ms), 4)
                        if elapsed_ms > 0 and dev_total > 0 else None),
        # window MFU against the wall clock (Σ step-FLOPs / peak /
        # elapsed) and against device-busy time only; None when the
        # peak is unknown (CPU without GLLM_TPU_PEAK_TFLOPS). 6 digits:
        # a tiny-model window with compile gaps sits at 1e-6 and must
        # not quantize to a fake hard zero
        "mfu": (round(mfu_dev / elapsed_ms, 6)
                if mfu_seen and elapsed_ms > 0 else None),
        "device_mfu": (round(mfu_dev / dev_total, 6)
                       if mfu_seen and dev_total > 0 else None),
        # estimated HBM read bandwidth over device-busy time (weights +
        # KV stream per step; per-device)
        "hbm_gbps": (round(hbm_dev / dev_total, 2)
                     if hbm_seen and dev_total > 0 else None),
        "compiles": compiles,
        "chain_breaks": chain_breaks,
        "chain_breaks_by_reason": break_reasons,
        # pipelined loop (docs/overlap_scheduling.md#pipelined-loop):
        # why the fill pass failed to run further ahead, and the mean
        # run-ahead depth sustained over the window's collected steps
        # (None when the window's events predate the pipelined layer)
        "loop_stalls": loop_stalls,
        "loop_stalls_by_reason": stall_reasons,
        "mean_inflight_depth": (round(inflight_sum / inflight_n, 2)
                                if inflight_n else None),
        "faults": faults_total,
        "faults_by_point": fault_points,
        "quarantines": quarantines,
        # supervised in-process recovery (config.engine_recovery):
        # completed rebuilds over the window, their total latch-to-ready
        # wall, failed rebuild attempts, and requests replayed across
        # the rebuilds (docs/robustness.md#recovery-lifecycle)
        "recoveries": recoveries,
        "recovery_s": (round(recovery_s_total, 3) if recoveries
                       else None),
        "rebuild_failures": rebuild_failures,
        "requests_replayed": requests_replayed,
    }
