"""Encoder node: pixel IO + ViT, serving encode jobs from LM nodes.

Re-design of /root/reference/gllm/disagg/encoder_runtime.py +
encoder_engine.py: the encoder process loads ONLY the vision tower
(skip_language), publishes itself on the discovery registry, accepts
EncoderJob messages, and for each job

  1. runs the image processor on the raw content → pixels + grid,
  2. sends MmItemMeta to the LM's meta endpoint (control plane, BEFORE the
     ViT — this unblocks gate-A admission),
  3. runs the ViT (LRU-cached by content hash),
  4. writes the embedding into the LM's slot pool (the write ack doubles
     as the embedding-ready notification).

Failure injection: GLLM_TPU_ENC_FAIL_FIRST_N=<n> silently drops the first
n jobs (reference GLLM_ENC_FAIL_FIRST_N) so the LM watchdog paths can be
tested.
"""

from __future__ import annotations

import base64
import io
import logging
import os
import queue
import threading
import time
from typing import Dict, Optional

import numpy as np

from gllm_tpu.disagg.discovery import NetworkDiscovery, make_payload
from gllm_tpu.disagg.protocol import EncodeFailed, EncoderJob, MmItemMeta
from gllm_tpu.disagg.transfer import TransferClient
from gllm_tpu.disagg.wire import MsgServer, connect, send_msg
from gllm_tpu.utils import LRUBytesCache

logger = logging.getLogger(__name__)


def load_raw_image(content):
    """Raw job content → PIL image. Accepts PIL images, data URLs, base64
    strings, file paths, and raw bytes."""
    from PIL import Image
    if hasattr(content, "convert"):          # PIL image
        return content
    if isinstance(content, bytes):
        return Image.open(io.BytesIO(content)).convert("RGB")
    if isinstance(content, str):
        if content.startswith("data:"):
            _, _, b64 = content.partition(",")
            return Image.open(io.BytesIO(
                base64.b64decode(b64))).convert("RGB")
        if os.path.exists(content):
            return Image.open(content).convert("RGB")
        # bare base64
        return Image.open(io.BytesIO(
            base64.b64decode(content))).convert("RGB")
    raise ValueError(f"unsupported image content type {type(content)!r}")


class EncoderEngine:
    """Processor + vision tower + per-item embedding cache (reference
    encoder_engine.py:35-178)."""

    def __init__(self, model_dir: str, dtype="float32",
                 min_pixels=None, max_pixels=None):
        import jax.numpy as jnp

        from gllm_tpu.models.config import from_hf_config
        from gllm_tpu.models.loader import load_hf_config
        from gllm_tpu.models.registry import get_model_def

        self.model_cfg = from_hf_config(load_hf_config(model_dir))
        assert self.model_cfg.use_mm, "encoder node needs a VL checkpoint"
        self.model_def = get_model_def(self.model_cfg)
        self.dtype = {"float32": jnp.float32,
                      "bfloat16": jnp.bfloat16}[dtype]
        # vision-only load: the full-template rules, filtered to visual.*
        self.params = self._load_visual(model_dir)
        from gllm_tpu.engine.mm_processing import load_image_processor
        self.processor = load_image_processor(
            model_dir, self.model_cfg.vision_config or {},
            min_pixels=min_pixels, max_pixels=max_pixels)
        self._cache = LRUBytesCache()
        merge = (self.model_cfg.vision_config or {}).get(
            "spatial_merge_size", 2)
        self._merge_unit = merge * merge

    def _load_visual(self, model_dir: str) -> dict:
        """Load only the visual.* half of the checkpoint (reference
        skip_language, model_loader.py use_mm flags)."""
        import jax

        from gllm_tpu.models import loader as loader_mod
        full = jax.eval_shape(
            lambda: self.model_def.init_params(self.model_cfg,
                                               dtype=self.dtype))
        template = {"visual": full["visual"]}
        if self.model_cfg.architecture.startswith("Qwen3VL"):
            from gllm_tpu.models.qwen3_vl import _vl3_rules
            base_rules = _vl3_rules(self.model_cfg)
        else:
            from gllm_tpu.models.qwen2_5_vl import _vl_rules
            base_rules = _vl_rules(self.model_cfg)

        def rules(name):
            r = base_rules(name)
            return r if r is not None and r[0][0] == "visual" else None

        return loader_mod._load_params(model_dir, template, rules)

    @property
    def feat_dim(self) -> int:
        return self.model_cfg.mm_embed_dim

    def process(self, modality: str, content) -> Dict:
        """Raw content → {pixels [n, patch_dim], grid_thw (t, h, w)}."""
        if isinstance(content, dict) and "pixel_values" in content:
            grid = np.asarray(content["grid_thw"]).reshape(-1)
            assert grid.size == 3, \
                f"one grid row per item, got shape {grid.shape}"
            out = {"pixels": np.asarray(content["pixel_values"],
                                        np.float32),
                   "grid_thw": tuple(int(v) for v in grid)}
            if content.get("second_per_grid_ts") is not None:
                out["second_per_grid_ts"] = float(
                    content["second_per_grid_ts"])
            return out
        if modality != "image":
            raise NotImplementedError(
                "video jobs must ship pre-processed pixels")
        img = load_raw_image(content)
        out = self.processor(images=[img], return_tensors="np")
        grid = np.asarray(out["image_grid_thw"]).reshape(-1)[:3]
        return {"pixels": np.asarray(out["pixel_values"], np.float32),
                "grid_thw": tuple(int(v) for v in grid)}

    def num_vis_tokens(self, grid_thw) -> int:
        t, h, w = grid_thw
        return t * h * w // self._merge_unit

    def encode(self, pixels: np.ndarray, grid_thw,
               content_hash: bytes) -> np.ndarray:
        cached = self._cache.get(content_hash)
        if cached is not None:
            return cached
        import jax.numpy as jnp
        out = self.model_def.embed_mm(
            self.params, self.model_cfg,
            jnp.asarray(pixels).astype(self.dtype), grid_thw)
        arr = np.asarray(out, np.float32)
        self._cache.put(content_hash, arr)
        return arr


class EncoderRuntime:
    """Job server + discovery client + worker thread (reference
    encoder_runtime.py:47-423)."""

    def __init__(self, engine: EncoderEngine, discovery_endpoint: str,
                 encoder_id: str = "enc0", advertise_host: str = "127.0.0.1",
                 processor_config_hash: str = "", port: int = 0):
        self.engine = engine
        self.encoder_id = encoder_id
        self._jobs: "queue.Queue[EncoderJob]" = queue.Queue()
        self._server = MsgServer("0.0.0.0", port, self._handle)
        self.port = self._server.port
        self._discovery = NetworkDiscovery(discovery_endpoint)
        self._payload = make_payload(
            role="encoder", addr=f"{advertise_host}:{self.port}",
            feat_dim=engine.feat_dim,
            processor_config_hash=processor_config_hash)
        self._transfer: Dict[str, TransferClient] = {}
        self._meta_socks: Dict[str, object] = {}
        self._fail_first_n = int(os.environ.get(
            "GLLM_TPU_ENC_FAIL_FIRST_N", "0"))
        self._jobs_seen = 0
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None

    def _handle(self, msg, sock) -> None:
        if isinstance(msg, EncoderJob):
            self._jobs.put(msg)
        else:
            logger.warning("encoder: unknown message %r", type(msg))

    def _send_meta(self, addr: str, obj) -> None:
        sock = self._meta_socks.get(addr)
        for attempt in (0, 1):
            try:
                if sock is None:
                    host, _, port = addr.rpartition(":")
                    sock = connect((host or "127.0.0.1", int(port)))
                    self._meta_socks[addr] = sock
                send_msg(sock, obj)
                return
            except (ConnectionError, OSError):
                if sock is not None:
                    sock.close()
                self._meta_socks.pop(addr, None)
                sock = None
                if attempt:
                    raise

    def _transfer_client(self, addr: str) -> TransferClient:
        cli = self._transfer.get(addr)
        if cli is None:
            cli = self._transfer[addr] = TransferClient(addr)
        return cli

    def _meta_phase(self, job: EncoderJob):
        """Cheap CPU half: processor + hash + meta send. Returns the prep
        dict for the ViT phase, or None (dropped / failed)."""
        self._jobs_seen += 1
        if self._jobs_seen <= self._fail_first_n:
            logger.warning("encoder %s: dropping job %d/%d (fail "
                           "injection)", self.encoder_id, self._jobs_seen,
                           self._fail_first_n)
            return None
        from gllm_tpu.engine.mm import content_hash
        try:
            prep = self.engine.process(job.modality, job.content)
        except Exception as e:  # bad image / IO error → tell the LM
            logger.exception("encoder %s: processing failed", self.encoder_id)
            self._send_meta(job.lm_meta_addr,
                            EncodeFailed(job.seq_id, job.item_idx, str(e)))
            return None
        grid = prep["grid_thw"]
        prep["hash"] = content_hash(prep["pixels"], grid)
        meta = MmItemMeta(
            seq_id=job.seq_id, item_idx=job.item_idx,
            modality=job.modality,
            num_tokens=self.engine.num_vis_tokens(grid),
            feat_dim=self.engine.feat_dim, grid_thw=grid,
            content_hash=prep["hash"], slot_id=job.slot_id,
            second_per_grid_ts=prep.get("second_per_grid_ts"))
        self._send_meta(job.lm_meta_addr, meta)       # control plane first
        return prep

    def _vit_phase(self, job: EncoderJob, prep) -> None:
        emb = self.engine.encode(prep["pixels"], prep["grid_thw"],
                                 prep["hash"])
        self._transfer_client(job.lm_transfer_addr).write(
            job.seq_id, job.item_idx, job.slot_id, emb)

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                batch = [self._jobs.get(timeout=0.1)]
            except queue.Empty:
                continue
            # Drain everything available so the cheap meta phase runs for
            # ALL queued jobs before any heavy ViT — metas unblock gate-A
            # admission on the LM (reference encoder_runtime.py:373-376).
            while True:
                try:
                    batch.append(self._jobs.get_nowait())
                except queue.Empty:
                    break
            preps = []
            for job in batch:
                try:
                    preps.append((job, self._meta_phase(job)))
                except Exception:
                    logger.exception("encoder %s: meta (%d, %d) failed",
                                     self.encoder_id, job.seq_id,
                                     job.item_idx)
                    preps.append((job, None))
            for job, prep in preps:
                if prep is None:
                    continue
                try:
                    self._vit_phase(job, prep)
                except Exception:
                    logger.exception("encoder %s: job (%d, %d) failed",
                                     self.encoder_id, job.seq_id,
                                     job.item_idx)

    def start(self) -> "EncoderRuntime":
        self._server.start()
        self._discovery.publish(self.encoder_id, self._payload)
        self._worker = threading.Thread(target=self._worker_loop,
                                        daemon=True)
        self._worker.start()
        return self

    def serve_forever(self) -> None:
        self.start()
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            self.stop()

    def stop(self) -> None:
        self._stop.set()
        self._discovery.close()
        self._server.stop()
        for cli in self._transfer.values():
            cli.close()
