"""MoE model definition for the registry."""

from __future__ import annotations

from gllm_tpu.models.registry import ModelDef


def deepseek_def() -> ModelDef:
    from gllm_tpu.models import deepseek, loader
    from gllm_tpu.parallel.shardings import (deepseek_param_specs,
                                             latent_kv_specs)
    return ModelDef(
        family="deepseek",
        init_params=deepseek.init_params,
        forward=deepseek.forward,
        compute_logits=deepseek.compute_logits,
        make_rope_table=deepseek.make_rope_table,
        load_params=loader.load_deepseek_params,
        init_kv_cache=deepseek.init_kv_cache,
        param_specs=deepseek_param_specs,
        kv_specs=latent_kv_specs,
    )


def moe_def() -> ModelDef:
    from gllm_tpu.models import loader, moe
    from gllm_tpu.parallel.shardings import (kv_cache_specs,
                                             moe_param_specs)
    return ModelDef(
        family="moe",
        init_params=moe.init_params,
        forward=moe.forward,
        compute_logits=moe.compute_logits,
        make_rope_table=moe.make_rope_table,
        load_params=loader.load_moe_params,
        init_kv_cache=moe.init_kv_cache,
        param_specs=moe_param_specs,
        kv_specs=kv_cache_specs,
    )
