"""Performance-attribution layer: request-scoped spans, a step FLOPs
model, peak-FLOPs tables, and the Chrome-trace converter (stdlib only).

ROADMAP item 1 says decode is host-loop-bound (MFU 0.086, BENCH_r05) —
but the steptrace ring only said how long each engine iteration's
*collect* took, not where the wall clock went (schedule vs batch-build
vs dispatch vs device vs collect) nor how much of the overlap
scheduling actually overlapped. This module holds the pure-host pieces
of the attribution stack:

- :class:`SpanTrace` — one span tree per request
  (queued → prefill chunks → decode chains → detokenize → finish),
  completed trees held in a bounded ring like the steptrace;
- :class:`StepFlopsModel` — matmul-path FLOPs per engine step from the
  model config (the per-step half of bench.py's workload MFU), feeding
  the ``gllm_step_mfu`` gauge and the per-window MFU in
  ``steptrace.summarize``;
- :func:`peak_flops` — dense-peak bf16 FLOP/s by TPU generation
  (single source of truth; bench.py's ``chip_peak_flops`` wraps it);
- :func:`chrome_trace` — steptrace step events + request spans →
  Chrome trace-event JSON (Perfetto/chrome://tracing loadable): one
  track per engine phase, one per request. Shared by ``GET /trace``
  and ``python -m gllm_tpu.obs.dump --format chrome``.

Same design constraints as the rest of ``gllm_tpu/obs``: no jax import,
no device work, no new jit static arguments; every recorded number is
host arithmetic the engine already had. Span recording is gated by
``EngineConfig.tracing`` (default ON — the acceptance bar is <2%
``--tiny`` throughput overhead and byte-identical token streams).
"""

from __future__ import annotations

import logging
import os
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional

logger = logging.getLogger(__name__)

__all__ = ["SpanTrace", "SPANS", "StepFlopsModel", "peak_flops",
           "chrome_trace", "SPAN_PHASES", "ENGINE_PHASES"]

# Span phase taxonomy (docs/observability.md span-phase catalog): the
# child spans a request tree may carry. ``queued`` = arrival → first
# schedule; ``prefill_chunk`` = one scheduled prompt chunk (dispatch →
# collect); ``decode_step`` = one UNfused decode dispatch carrying the
# request; ``decode_chain`` = one fused multi-step block (k sub-steps,
# ``k_exec`` executed under on-device finish); ``detokenize`` =
# accumulated host detokenization/stream time (one rolled-up span at
# finish).
SPAN_PHASES = ("queued", "prefill_chunk", "decode_step", "decode_chain",
               "detokenize")

# Engine-loop host phases recorded on every step event (``ph`` field):
# schedule (scheduler passes forming the batch/chain), build (runner
# host work up to the jit call: drains, batch build), dispatch (jit
# enqueue + async host-copy start), collect (host blocked on the
# handle). ``wait`` is derived — the slack between dispatch end and
# collect start while the handle rode the pipeline (device work hides
# here). ``device`` is the block-until-ready delta attributed back to
# the launching step.
ENGINE_PHASES = ("schedule", "build", "dispatch", "collect")


class SpanTrace:
    """Bounded per-request span trees.

    Open trees live in a dict keyed by seq_id (bounded by ``max_open``
    — beyond it new requests go untracked, counted in ``untracked``);
    ``finish`` moves a tree into a fixed-capacity completed ring.
    A tree caps its child-phase list at ``max_phases``; later events
    roll up into per-phase ``{n, ms}`` aggregates instead of growing
    without bound (a 10k-token decode must not hold 10k dicts).
    """

    def __init__(self, capacity: Optional[int] = None,
                 max_open: Optional[int] = None,
                 max_phases: Optional[int] = None):
        if capacity is None:
            capacity = int(os.environ.get("GLLM_OBS_SPAN_CAP", "1024"))
        if max_open is None:
            max_open = int(os.environ.get("GLLM_OBS_SPAN_OPEN", "4096"))
        if max_phases is None:
            max_phases = int(os.environ.get("GLLM_OBS_SPAN_PHASES",
                                            "512"))
        if capacity <= 0 or max_open <= 0 or max_phases <= 0:
            raise ValueError("span bounds must be positive")
        self.capacity = capacity
        self.max_open = max_open
        self.max_phases = max_phases
        self._lock = threading.Lock()
        self._open: Dict[int, dict] = {}
        self._done: deque = deque(maxlen=capacity)
        self._finished = 0          # lifetime completed-span count
        self.untracked = 0          # begins refused by the open bound

    # ---- lifecycle ---------------------------------------------------------

    def begin(self, seq_id: int, arrival_t: float, admitted_t: float,
              prompt_tokens: int = 0) -> None:
        """Open a request tree at admission; records the ``queued``
        phase [arrival → first schedule]. Idempotent per seq_id."""
        with self._lock:
            if seq_id in self._open:
                return
            if len(self._open) >= self.max_open:
                self.untracked += 1
                return
            rec = {"seq_id": seq_id, "t0": arrival_t, "t1": None,
                   "reason": None, "prompt_tokens": prompt_tokens,
                   "output_tokens": 0, "phases": [], "agg": {}}
            self._open[seq_id] = rec
        if admitted_t > arrival_t:
            self.event(seq_id, "queued", arrival_t,
                       (admitted_t - arrival_t) * 1e3)

    def event(self, seq_id: int, ph: str, t: float, dur_ms: float,
              **meta) -> None:
        """Append one child span (monotonic start ``t``, ``dur_ms``)
        to an open tree; silently dropped when the request is
        untracked (holes, bounded-out requests, tracing off)."""
        with self._lock:
            self._event_locked(seq_id, ph, t, dur_ms, meta)

    def event_many(self, seq_ids, ph: str, t: float, dur_ms: float,
                   meta: Optional[dict] = None) -> None:
        """One identical child span for many requests (a decode batch's
        rows all share one dispatch→collect interval) under a SINGLE
        lock acquisition — the engine hot path records one of these per
        step, so per-row locking would be the dominant tracing cost."""
        with self._lock:
            for sid in seq_ids:
                self._event_locked(sid, ph, t, dur_ms, meta)

    def _event_locked(self, seq_id, ph, t, dur_ms, meta) -> None:
        rec = self._open.get(seq_id)
        if rec is None:
            return
        if len(rec["phases"]) >= self.max_phases:
            agg = rec["agg"].setdefault(ph, {"n": 0, "ms": 0.0})
            agg["n"] += 1
            agg["ms"] += dur_ms
            return
        ev = {"ph": ph, "t": t, "dur_ms": round(dur_ms, 3)}
        if meta:
            ev.update(meta)
        rec["phases"].append(ev)

    def finish(self, seq_id: int, reason: str, t: float,
               output_tokens: int = 0, **meta) -> Optional[dict]:
        """Close a request tree (first close wins — abort/deadline/
        quarantine and the normal output path may race) and push it
        into the completed ring."""
        with self._lock:
            rec = self._open.pop(seq_id, None)
            if rec is None:
                return None
            rec["t1"] = t
            rec["reason"] = reason
            if output_tokens:
                rec["output_tokens"] = output_tokens
            rec.update(meta)
            for ph, agg in rec["agg"].items():
                agg["ms"] = round(agg["ms"], 3)
            if not rec["agg"]:
                del rec["agg"]
            self._done.append(rec)
            self._finished += 1
            return rec

    # ---- reads -------------------------------------------------------------

    @property
    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    @property
    def dropped(self) -> int:
        """Completed spans lost to ring rollover."""
        with self._lock:
            return max(0, self._finished - len(self._done))

    def spans(self) -> List[dict]:
        """Completed request trees, oldest first."""
        with self._lock:
            return list(self._done)

    def open_spans(self) -> List[dict]:
        """Still-open trees (shallow copies; phases shared)."""
        with self._lock:
            return [dict(r) for r in self._open.values()]

    def clear(self) -> None:
        with self._lock:
            self._open.clear()
            self._done.clear()
            self._finished = 0
            self.untracked = 0


# Default/standalone instance. Engine code uses a PER-LLM ``SpanTrace``
# (``LLM.spans``) — seq_ids are a per-engine counter starting at 0, so
# two co-resident engines sharing one ring would silently merge each
# other's trees (begin() idempotence absorbs the second engine's open,
# its events land in the first engine's tree). This global remains the
# fallback for components constructed without an engine.
SPANS = SpanTrace()


# ---- FLOPs / peak models ---------------------------------------------------

# Dense-peak bf16 TFLOP/s by TPU generation (public spec sheets) — the
# MFU denominator. Single source of truth: bench.py's chip_peak_flops
# wraps peak_flops() below. Matched by substring against
# ``jax.Device.device_kind`` (lowercased).
PEAK_TFLOPS = (("v5 lite", 197.0), ("v5e", 197.0), ("v6", 918.0),
               ("trillium", 918.0), ("v5p", 459.0), ("v5", 459.0),
               ("v4", 275.0), ("v3", 123.0))


def peak_flops(device_kind: str = "") -> float:
    """Peak dense bf16 FLOP/s for a device kind string, or 0.0 when
    unknown (CPU). ``GLLM_TPU_PEAK_TFLOPS`` overrides — also the lever
    that makes the MFU plumbing testable on CPU."""
    ov = os.environ.get("GLLM_TPU_PEAK_TFLOPS")
    if ov:
        try:
            return float(ov) * 1e12
        except ValueError:
            # fall through to the table — but SAY so, or every MFU
            # field silently nulls while the operator believes the
            # override is honored
            logger.warning("ignoring malformed GLLM_TPU_PEAK_TFLOPS=%r",
                           ov)
    kind = (device_kind or "").lower()
    for tag, tf in PEAK_TFLOPS:
        if tag in kind:
            return tf * 1e12
    return 0.0


class StepFlopsModel:
    """Matmul-path FLOPs per engine step from the model config.

    The per-step counterpart of bench.py's workload-level
    ``model_flops`` — same decomposition (2×params on the matmul body
    per processed token, one lm_head row per sampling sequence,
    causal token×context attention at 4·Hq·D·L FLOPs per key), so a
    measured pass's per-step sum reconciles with the workload total.
    MoE configs count only the activated expert width (an estimator,
    not an audit). Pure integer arithmetic on counts the scheduler
    already tracks — never touches the device.
    """

    def __init__(self, num_layers: int, hidden_size: int, num_heads: int,
                 num_kv_heads: int, head_dim: int,
                 intermediate_size: int, vocab_size: int):
        qkv = hidden_size * (num_heads + 2 * num_kv_heads) * head_dim
        o_proj = num_heads * head_dim * hidden_size
        mlp = 3 * hidden_size * intermediate_size
        self.body_per_token = 2 * num_layers * (qkv + o_proj + mlp)
        self.lm_head_per_row = 2 * vocab_size * hidden_size
        # FLOPs per (query token × context token): QK^T + PV
        self.attn_coeff = 4 * num_layers * num_heads * head_dim

    @classmethod
    def from_model_config(cls, mc) -> "StepFlopsModel":
        inter = mc.intermediate_size
        experts = getattr(mc, "num_experts_per_tok", 0) or 0
        moe_inter = getattr(mc, "moe_intermediate_size", 0) or 0
        if experts and moe_inter:
            inter = experts * moe_inter       # activated width only
        return cls(mc.num_layers, mc.hidden_size, mc.num_heads,
                   mc.num_kv_heads, mc.head_dim or 0, inter,
                   mc.vocab_size)

    def step_flops(self, rows: Iterable[tuple]) -> float:
        """One dispatch of mixed prefill/decode rows.

        ``rows``: (new_tokens, ctx_before, samples) per scheduled item
        — token j of a chunk attends ctx_before + j + 1 keys; a
        sampling row pays one lm_head projection (the runner gathers
        last-token rows before the vocab GEMM).
        """
        f = 0.0
        for n, ctx, samples in rows:
            f += n * self.body_per_token
            if samples:
                f += self.lm_head_per_row
            f += self.attn_coeff * (n * ctx + n * (n + 1) / 2.0)
        return f

    def block_flops(self, ctx_before: Iterable[int], k: int) -> float:
        """One fused decode block: ``k`` executed sub-steps over live
        rows whose contexts start at ``ctx_before`` and grow by one
        per sub-step. Dead/hole rows should not be passed (their
        forward work is real but their attention reads the dummy page
        — close enough for an estimator to skip)."""
        f = 0.0
        for ctx in ctx_before:
            f += k * (self.body_per_token + self.lm_head_per_row)
            f += self.attn_coeff * (k * ctx + k * (k + 1) / 2.0)
        return f


# ---- Chrome trace-event export ---------------------------------------------

# Track (tid) layout of the engine process row in the exported trace;
# ``wait`` and ``device`` are derived tracks (see chrome_trace).
_ENGINE_TIDS = {"schedule": 1, "build": 2, "dispatch": 3, "wait": 4,
                "collect": 5, "device": 6}
_PID_ENGINE = 1
_PID_REQUESTS = 2


def _meta(pid: int, name: str, tid: Optional[int] = None,
          thread: Optional[str] = None) -> dict:
    if tid is None:
        return {"ph": "M", "name": "process_name", "pid": pid,
                "args": {"name": name}}
    return {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": thread or name}}


def _x(name: str, ts_s: float, dur_s: float, pid: int, tid: int,
       args: Optional[dict] = None) -> dict:
    ev = {"name": name, "ph": "X", "ts": round(ts_s * 1e6, 1),
          "dur": round(max(0.0, dur_s) * 1e6, 1), "pid": pid, "tid": tid}
    if args:
        ev["args"] = args
    return ev


def chrome_trace(step_events: Iterable[dict], spans: Iterable[dict] = (),
                 span_t0: float = 0.0) -> dict:
    """steptrace step events + request span trees → Chrome trace-event
    JSON (the ``{"traceEvents": [...]}`` object format; load in
    Perfetto or chrome://tracing).

    Engine phases are reconstructed backwards from each step event's
    collect-end timestamp ``t`` using the recorded phase walls:
    ``[t - step_wall, t]`` holds schedule → build → dispatch → wait →
    collect in order (wait = the pipelined slack between dispatch end
    and collect start), and the device track shows ``[t - dev_ms, t]``.
    Request spans use absolute monotonic times; ``span_t0`` (the
    steptrace ring's epoch) rebases them onto the same axis.
    """
    events: List[dict] = [
        _meta(_PID_ENGINE, "engine loop"),
        _meta(_PID_REQUESTS, "requests"),
    ]
    for name, tid in _ENGINE_TIDS.items():
        events.append(_meta(_PID_ENGINE, name, tid=tid))

    for e in step_events:
        ph = e.get("ph")
        if not isinstance(ph, dict):
            continue                   # compile/chain_break/... events
        end = float(e.get("t", 0.0))
        sched = float(ph.get("schedule", 0.0)) / 1e3
        build = float(ph.get("build", 0.0)) / 1e3
        disp = float(ph.get("dispatch", 0.0)) / 1e3
        coll = float(ph.get("collect", e.get("wall_ms", 0.0))) / 1e3
        wall = float(e.get("step_wall_ms",
                           (sched + build + disp + coll) * 1e3)) / 1e3
        wait = max(0.0, wall - (sched + build + disp + coll))
        args = {"kind": e.get("kind"), "seq": e.get("seq"),
                "num_seqs": e.get("num_seqs"),
                "tokens": e.get("tokens")}
        if "k" in e:
            args["k"] = e["k"]
        t = end - wall
        for name, dur in (("schedule", sched), ("build", build),
                          ("dispatch", disp), ("wait", wait),
                          ("collect", coll)):
            if dur > 0:
                events.append(_x(f"{e.get('kind', 'step')}:{name}", t,
                                 dur, _PID_ENGINE, _ENGINE_TIDS[name],
                                 args if name == "collect" else None))
            t += dur
        dev = float(e.get("dev_ms", 0.0)) / 1e3
        if dev > 0:
            dargs = dict(args)
            if e.get("mfu") is not None:
                dargs["mfu"] = e["mfu"]
            events.append(_x(f"{e.get('kind', 'step')}:device",
                             end - dev, dev, _PID_ENGINE,
                             _ENGINE_TIDS["device"], dargs))

    for rec in spans:
        sid = int(rec.get("seq_id", 0))
        t0 = float(rec.get("t0", 0.0)) - span_t0
        t1 = rec.get("t1")
        t1 = (float(t1) - span_t0) if t1 is not None else None
        events.append(_meta(_PID_REQUESTS, f"req {sid}", tid=sid))
        if t1 is not None and t1 > t0:
            events.append(_x(
                f"request {sid} ({rec.get('reason') or 'open'})", t0,
                t1 - t0, _PID_REQUESTS, sid,
                {"prompt_tokens": rec.get("prompt_tokens"),
                 "output_tokens": rec.get("output_tokens"),
                 "reason": rec.get("reason")}))
        for c in rec.get("phases", ()):
            args = {k: v for k, v in c.items()
                    if k not in ("ph", "t", "dur_ms")}
            events.append(_x(c["ph"], float(c["t"]) - span_t0,
                             float(c["dur_ms"]) / 1e3, _PID_REQUESTS,
                             sid, args or None))
    return {"traceEvents": events, "displayTimeUnit": "ms"}
