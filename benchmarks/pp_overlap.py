"""Measure pipeline-parallel microbatch overlap (VERDICT r02 weak #6).

The PP engine asserts that keeping ``pp`` microbatches in flight lets
XLA's per-device execution overlap consecutive stage programs (the role
of the reference's explicit pp_size-batches-running scheduler policy,
scheduler.py:358-364). This script measures it instead of asserting it:
the SAME pp=2 workload runs twice —

  serial:    ``pp_pipeline_depth=1``  (launch → collect every microbatch;
             stage 1 idles while stage 0 runs and vice versa)
  pipelined: ``pp_pipeline_depth=None`` (= pp in flight, the default)

and reports wall times + the speedup. Overlap fraction =
(t_serial - t_pipelined) / (t_serial / 2): 0 → stages serialize, 1 →
perfect two-stage overlap. Optionally writes a jax.profiler trace of the
pipelined run for timeline inspection.

Runs anywhere (CPU mesh via the force-host-device env, or real chips):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/pp_overlap.py [--trace-dir DIR]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_llm(depth):
    from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                                 SchedulerConfig)
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.models.config import ModelConfig

    # Big enough per-stage programs that overlap is measurable over
    # dispatch noise; small enough to stay a quick check.
    mcfg = ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=2048, hidden_size=512,
        num_layers=8, num_heads=8, num_kv_heads=8, head_dim=64,
        intermediate_size=1536, max_position=512)
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=256,
        max_num_seqs=64, pp_pipeline_depth=depth,
        scheduler=SchedulerConfig(schedule_method="token_throttling",
                                  max_prefill_tokens=256,
                                  min_prefill_tokens=64,
                                  max_decode_seqs=16),
        cache=CacheConfig(page_size=16, num_pages=512),
        parallel=ParallelConfig(pp=2, tp=1))
    return LLM(config=cfg, model_cfg=mcfg)


def run(llm, n_seqs=32, max_tokens=48):
    from gllm_tpu.sampling_params import SamplingParams
    prompts = [[(7 * i + j) % 2000 for j in range(8)] for i in range(n_seqs)]
    t0 = time.monotonic()
    outs = llm.generate(prompt_token_ids=prompts,
                        sampling_params=SamplingParams(
                            temperature=0.0, max_tokens=max_tokens,
                            ignore_eos=True))
    dt = time.monotonic() - t0
    assert all(len(o.output_token_ids) == max_tokens for o in outs)
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", default=None,
                    help="write a jax.profiler trace of the pipelined run")
    args = ap.parse_args()

    results = {}
    for label, depth in (("serial", 1), ("pipelined", None)):
        llm = build_llm(depth)
        run(llm, n_seqs=8, max_tokens=8)            # warmup / compile
        if label == "pipelined" and args.trace_dir:
            import jax
            with jax.profiler.trace(args.trace_dir):
                results[label] = run(llm)
        else:
            results[label] = run(llm)
        print(f"{label:10s} {results[label]:.3f}s", file=sys.stderr)
        del llm

    speedup = results["serial"] / results["pipelined"]
    # perfect 2-stage overlap halves the serial time
    overlap_frac = (results["serial"] - results["pipelined"]) \
        / (results["serial"] / 2)
    print(json.dumps({"t_serial_s": round(results["serial"], 3),
                      "t_pipelined_s": round(results["pipelined"], 3),
                      "speedup": round(speedup, 3),
                      "overlap_fraction": round(overlap_frac, 3)}))


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
