"""DeepSeek-V3.2 chat rendering via the checkpoint's bundled encoder.

V3.2 checkpoints ship no usable Jinja ``chat_template``; the model-native
DSML prompt markup (user/assistant sentinels, ``<think>`` gating, DSML tool
invocations) is produced by a Python encoder the checkpoint bundles at
``<model_path>/encoding/encoding_dsv32.py``. The reference loads that file
at runtime and adapts its OpenAI-style call sites to it
(/root/reference/gllm/tokenizers/deepseek_v32.py); we do the same so chat
requests render exactly the markup the model was trained on. When the file
is absent (or fails to import) callers fall back to
``apply_chat_template``.
"""

from __future__ import annotations

import importlib.util
import json
import os
from typing import Any, Dict, List, Optional

# model_path → imported encoder module, or None when unavailable (negative
# results are cached too: the common non-DSv32 case must stay zero-cost).
_CACHE: Dict[str, Optional[Any]] = {}


def load_encoder(model_path: str) -> Optional[Any]:
    """Import ``<model_path>/encoding/encoding_dsv32.py`` once per path.

    The module must expose ``encode_messages``; ``None`` means "use the
    generic chat template instead"."""
    if model_path in _CACHE:
        return _CACHE[model_path]
    mod: Optional[Any] = None
    path = os.path.join(model_path, "encoding", "encoding_dsv32.py")
    if os.path.isfile(path):
        try:
            spec = importlib.util.spec_from_file_location(
                "gllm_tpu_dsv32_encoding", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            if not callable(getattr(mod, "encode_messages", None)):
                mod = None
        except Exception:
            mod = None
    _CACHE[model_path] = mod
    return mod


def _normalize(messages: List[Any]) -> List[dict]:
    """Realize request messages as plain JSON dicts: pydantic models and
    lazy iterators (e.g. tool_calls validators) break the encoder's
    ``len``/iteration, so round-trip through JSON."""
    out = []
    for m in messages:
        if hasattr(m, "model_dump"):
            out.append(m.model_dump(mode="json", exclude_none=True))
        else:
            out.append(json.loads(json.dumps(m, default=list)))
    return out


def render_chat(encoder: Any, messages: List[Any], tokenizer: Any = None,
                *, tools: Optional[List[dict]] = None, tokenize: bool = True,
                **kwargs: Any):
    """Render a chat request with the bundled encoder.

    - ``thinking`` / ``enable_thinking`` request kwargs select the
      encoder's thinking mode (default plain chat).
    - ``tools`` ride on a leading system message, which is how the
      encoder expects tool declarations.
    - a trailing user turn drops prior-turn reasoning (the model's
      convention: reasoning only persists mid-assistant-turn).
    - the encoder emits BOS itself → tokenize without special tokens.

    Returns token ids when ``tokenize`` (requires ``tokenizer``), else the
    prompt string."""
    thinking = bool(kwargs.get("thinking")
                    or kwargs.get("enable_thinking"))
    messages = _normalize(messages)
    if tools:
        messages.insert(0, {"role": "system",
                            "tools": _normalize(tools)})
    drop_thinking = bool(messages) and messages[-1].get("role") == "user"
    prompt = encoder.encode_messages(messages,
                                     thinking_mode=("thinking" if thinking
                                                    else "chat"),
                                     drop_thinking=drop_thinking)
    if not tokenize:
        return prompt
    return tokenizer.encode(prompt, add_special_tokens=False)


def parse_completion(encoder: Any, text: str):
    """Parse a completion back into message structure via the encoder's
    own parser when it ships one; ``None`` → caller keeps its generic
    tool/content parsing."""
    fn = getattr(encoder, "parse_message_from_completion_text", None)
    if not callable(fn):
        return None
    try:
        return fn(text)
    except Exception:
        return None
