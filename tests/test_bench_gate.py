"""Bench regression gate (ISSUE 20): GLLM_BENCH_BASELINE=<path>.

The measured pass is compared against a committed BENCH JSON; a metric
that regresses beyond tolerance fails the run with a NONZERO exit and
names the offender — the trajectory's perf floor is enforced, not just
reported.
"""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bench import check_bench_regression, run_bench_gate  # noqa: E402

BASE = {"bubble_frac": 0.10, "mfu": 0.30, "tokens_per_dispatch": 4.0}


def test_gate_passes_at_baseline_and_within_tolerance():
    assert check_bench_regression(dict(BASE), BASE) == []
    # within tolerance: 10% relative (or 0.02 absolute) slack per metric
    ok = {"bubble_frac": 0.11, "mfu": 0.28, "tokens_per_dispatch": 3.7}
    assert check_bench_regression(ok, BASE) == []
    # improvements never fail, however large
    better = {"bubble_frac": 0.0, "mfu": 0.9, "tokens_per_dispatch": 9.0}
    assert check_bench_regression(better, BASE) == []


@pytest.mark.parametrize("metric,bad", [
    ("bubble_frac", 0.30),          # lower-is-better metric went up
    ("mfu", 0.20),                  # higher-is-better metric went down
    ("tokens_per_dispatch", 2.0),
])
def test_gate_names_the_offending_metric(metric, bad):
    result = dict(BASE, **{metric: bad})
    failures = check_bench_regression(result, BASE)
    assert len(failures) == 1
    assert metric in failures[0]
    assert "regressed" in failures[0]


def test_gate_skips_metrics_absent_from_either_side():
    # profile mismatch (e.g. a rung without spec_fused has no
    # tokens_per_dispatch): skipped, not failed
    assert check_bench_regression({"bubble_frac": 0.1}, BASE) == []
    assert check_bench_regression(dict(BASE), {"mfu": 0.3}) == []


def test_run_bench_gate_records_verdict(tmp_path):
    bp = tmp_path / "BENCH_baseline.json"
    bp.write_text(json.dumps(BASE))
    ok = dict(BASE)
    assert run_bench_gate(ok, str(bp)) == 0
    assert ok["baseline_gate"]["failures"] == []
    bad = dict(BASE, bubble_frac=0.5)
    assert run_bench_gate(bad, str(bp)) == 1
    assert any("bubble_frac" in f
               for f in bad["baseline_gate"]["failures"])


def test_injected_regression_exits_nonzero_naming_metric(tmp_path):
    """The process-level contract: an injected regression makes the gate
    exit NONZERO with the offending metric named on stderr (bench.py's
    report tail wires run_bench_gate's rc into sys.exit)."""
    bp = tmp_path / "BENCH_baseline.json"
    bp.write_text(json.dumps(BASE))
    rp = tmp_path / "result.json"
    rp.write_text(json.dumps(dict(BASE, bubble_frac=0.5, mfu=0.05)))
    code = (
        "import json, sys\n"
        "from bench import run_bench_gate\n"
        f"result = json.load(open({str(rp)!r}))\n"
        f"rc = run_bench_gate(result, {str(bp)!r})\n"
        "print(json.dumps(result))\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode != 0
    assert "bubble_frac" in proc.stderr and "mfu" in proc.stderr
    assert "REGRESSION" in proc.stderr
    # the result JSON still lands, carrying the verdict
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["baseline_gate"]["failures"]
