"""Parallelism: device meshes, parameter shardings, collective layout.

TPU-native replacement for the reference's NCCL process-group machinery
(/root/reference/gllm/dist_utils.py): instead of per-GPU processes with
explicit communicators, one controller process lays a
``jax.sharding.Mesh`` over the chips and annotates shardings; XLA inserts
the ICI collectives (psum / all-gather / reduce-scatter / collective-permute)
that NCCL calls performed by hand. The reference's dual-communicator trick,
custom NVLink all-reduce, and zmq TP fan-out all collapse into GSPMD.
"""

from gllm_tpu.parallel.mesh import make_mesh, mesh_context, shard_hint
from gllm_tpu.parallel.shardings import (dense_param_specs, kv_cache_specs,
                                         shard_params)

__all__ = [
    "dense_param_specs",
    "kv_cache_specs",
    "make_mesh",
    "mesh_context",
    "shard_hint",
    "shard_params",
]
