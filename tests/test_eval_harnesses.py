"""Scorer/parser units of the offline eval harnesses (reference
benchmarks/evaluate_bfcl.py + evaluate_mmmu.py drivers)."""

import importlib.util
import os

import pytest


def _load(name):
    path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                        name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bfcl = _load("evaluate_bfcl")
mmmu = _load("evaluate_mmmu")


def test_parse_prompt_calls():
    calls = bfcl.parse_prompt_calls(
        "Sure: [get_weather(city='Paris', days=3), noop()]")
    assert calls == [("get_weather", {"city": "Paris", "days": 3}),
                     ("noop", {})]
    assert bfcl.parse_prompt_calls("no calls here") == []
    assert bfcl.parse_prompt_calls("[broken(") == []


def test_parse_native_calls():
    msg = {"tool_calls": [{"function": {
        "name": "f", "arguments": "{\"x\": 1}"}}]}
    assert bfcl.parse_native_calls(msg) == [("f", {"x": 1})]


@pytest.mark.parametrize("calls,expect,irr,want", [
    ([("f", {"a": 1})],
     [{"name": "f", "args": {"a": [1, 2]}, "required": ["a"]}], False, True),
    ([("f", {"a": 3})],
     [{"name": "f", "args": {"a": [1, 2]}, "required": ["a"]}], False, False),
    ([("f", {})],                                   # missing required
     [{"name": "f", "args": {"a": [1]}, "required": ["a"]}], False, False),
    ([("f", {})],                                   # "" ⇒ omittable
     [{"name": "f", "args": {"a": [1, ""]}, "required": ["a"]}], False, True),
    ([("f", {"a": 1, "z": 9})],                     # undeclared arg
     [{"name": "f", "args": {"a": [1]}, "required": ["a"]}], False, False),
    ([], [], True, True),                           # irrelevance detection
    ([("f", {})], [], True, False),
    ([("f", {"a": "PARIS"})],                       # case-folded strings
     [{"name": "f", "args": {"a": ["Paris"]}, "required": ["a"]}],
     False, True),
    ([("g", {"b": 2}), ("f", {"a": 1})],            # order-free parallel
     [{"name": "f", "args": {"a": [1]}, "required": ["a"]},
      {"name": "g", "args": {"b": [2]}, "required": ["b"]}], False, True),
])
def test_bfcl_score(calls, expect, irr, want):
    assert bfcl.score(calls, expect, irr) is want


def test_mmmu_choice_extraction():
    assert mmmu.extract_choice("The answer is B.") == "B"
    assert mmmu.extract_choice(" c") == "C"
    assert mmmu.extract_choice("unclear") is None


def test_parse_prompt_calls_with_leading_prose_brackets():
    calls = bfcl.parse_prompt_calls(
        "[Note] I'll call it now: [get_weather(city='Paris')]")
    assert calls == [("get_weather", {"city": "Paris"})]


def test_extract_choice_ignores_english_words():
    assert mmmu.extract_choice("I think the answer is B") == "B"
    assert mmmu.extract_choice("I cannot see the image") is None
    assert mmmu.extract_choice("A") == "A"
    assert mmmu.extract_choice("(C) because ...") == "C"


def test_extract_choice_a_and_i_phrasings():
    assert mmmu.extract_choice("Option A.") == "A"
    assert mmmu.extract_choice("A is correct") == "A"
    assert mmmu.extract_choice("I would say B") == "B"  # answer-ish verb,
    # but B is the standalone choice mentioned
    assert mmmu.extract_choice("choice (I)") == "I"


# ---- concurrent eval client (VERDICT r03 weak #6) --------------------------

def _stub_server(handler_fn):
    """Tiny threaded HTTP server answering POSTs with handler_fn(path,
    body_dict) -> (status, dict)."""
    import http.server
    import json as _json
    import socketserver
    import threading

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = _json.loads(self.rfile.read(n) or b"{}")
            status, resp = handler_fn(self.path, body)
            data = _json.dumps(resp).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    class S(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True

    srv = S(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_post_json_retries_5xx_then_succeeds():
    ec = _load("eval_client")
    calls = []

    def handler(path, body):
        calls.append(path)
        if len(calls) < 3:
            return 503, {"error": "warming up"}
        return 200, {"ok": True, "echo": body["x"]}

    srv = _stub_server(handler)
    try:
        d = ec.post_json("127.0.0.1", srv.server_address[1], "/t",
                         {"x": 7}, retries=3)
        assert d == {"ok": True, "echo": 7}
        assert len(calls) == 3
    finally:
        srv.shutdown()


def test_post_json_4xx_no_retry():
    ec = _load("eval_client")
    calls = []

    def handler(path, body):
        calls.append(1)
        return 400, {"error": "bad"}

    srv = _stub_server(handler)
    try:
        with pytest.raises(RuntimeError):
            ec.post_json("127.0.0.1", srv.server_address[1], "/t", {},
                         retries=3)
        assert len(calls) == 1, "4xx must not be retried"
    finally:
        srv.shutdown()


def test_mmlu_pro_concurrent_run(tmp_path, capsys, monkeypatch):
    """The harness drives N questions concurrently against a stub server
    and scores the canned answers."""
    import json as _json
    import threading

    data = tmp_path / "q.jsonl"
    qs = [{"question": f"q{i}", "options": ["x", "y", "z"],
           "answer": i % 3} for i in range(20)]
    data.write_text("\n".join(_json.dumps(q) for q in qs))

    seen = set()
    lock = threading.Lock()

    def handler(path, body):
        q = body["messages"][0]["content"]
        i = int(q.split("q", 1)[1].split("\n", 1)[0])
        with lock:
            seen.add(i)
        return 200, {"choices": [{"message":
                                  {"content": f"Answer: {'ABC'[i % 3]}"}}]}

    srv = _stub_server(handler)
    try:
        mm = _load("evaluate_mmlu_pro")
        monkeypatch.setattr("sys.argv", [
            "evaluate_mmlu_pro.py", "--data-path", str(data),
            "--port", str(srv.server_address[1]), "--concurrency", "8"])
        mm.main()
    finally:
        srv.shutdown()
    out = [ln for ln in capsys.readouterr().out.splitlines()
           if ln.startswith("{")]
    d = _json.loads(out[-1])
    assert d["metric"] == "mmlu_pro_accuracy"
    assert d["value"] == 1.0 and d["n"] == 20
    assert seen == set(range(20))


def test_serve_bench_summary_and_poisson(tmp_path, capsys, monkeypatch):
    """serve_bench drives a streaming stub server with poisson arrivals
    and reports the full latency distribution shape."""
    import http.server
    import json as _json
    import socketserver
    import threading
    import time as _time

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.end_headers()
            for i in range(4):
                ev = {"choices": [{"index": 0, "text": f"t{i}",
                                   "finish_reason": None}]}
                self.wfile.write(b"data: " + _json.dumps(ev).encode()
                                 + b"\n\n")
                self.wfile.flush()
                _time.sleep(0.01)
            self.wfile.write(b"data: [DONE]\n\n")

        def log_message(self, *a):
            pass

    class S(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True

    srv = S(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        sb = _load("serve_bench")
        monkeypatch.setattr("sys.argv", [
            "serve_bench.py", "--port", str(srv.server_address[1]),
            "--num-prompts", "6", "--concurrency", "3",
            "--prompt-len", "16", "--output-len", "4",
            "--request-rate", "50"])
        sb.main()
    finally:
        srv.shutdown()
    out = capsys.readouterr().out
    d = _json.loads(out)
    assert d["completed"] == 6 and d["failed"] == 0
    assert d["output_tokens"] == 24
    for k in ("ttft_ms", "tpot_ms", "itl_ms", "e2e_ms"):
        assert set(d[k]) == {"mean", "p50", "p90", "p99"}, d[k]
    assert d["e2e_ms"]["p50"] > 0
    # events arrive INCREMENTALLY (read1-based client): the stub staggers
    # chunks 10 ms apart, so SOME nonzero inter-arrival must be observed —
    # the old blocking read(4096) batched every event into one read and
    # reported exactly 0 (regression: it faked TTFT/ITL until r5). A
    # loaded CI box may coalesce some intervals, so only >0 is asserted.
    assert d["itl_ms"]["mean"] > 0, d["itl_ms"]


def test_latency_bench_tiny_cpu():
    """latency_bench CLI end-to-end on CPU: in-process server + Poisson
    client threads → one JSON line with TTFT/TPOT/ITL percentiles and
    vs_baseline against the 500 ms TTFT target."""
    import json as _json
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=root)
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks",
                                      "latency_bench.py"), "--tiny"],
        env=env, cwd=root, timeout=420, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    assert proc.returncode == 0
    d = _json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["metric"] == "ttft_p50_ms" and d["value"] > 0
    det = d["detail"]
    assert det["failed"] == 0 and det["completed"] == 8
    assert det["itl_ms"]["mean"] > 0


def test_bfcl_native_mode_qwen35_xml_chain():
    """BFCL native mode over the Qwen3.5 XML markup: model output →
    Qwen3XmlToolParser (schema coercion) → OpenAI message shape →
    bfcl.parse_native_calls → AST scorer. Proves the whole native-mode
    chain the reference exercises with its qwen3 parser
    (tool_parsers.py:346-425)."""
    from gllm_tpu.entrypoints.tool_parsers import (Qwen3XmlToolParser,
                                                   schemas_from_tools)
    tools = [{"type": "function", "function": {
        "name": "get_weather", "parameters": {
            "properties": {"city": {"type": "string"},
                           "days": {"type": "integer"}}}}}]
    model_out = ("<tool_call>\n<function=get_weather>\n"
                 "<parameter=city>\nParis\n</parameter>\n"
                 "<parameter=days>\n3\n</parameter>\n"
                 "</function>\n</tool_call>")
    _, calls = Qwen3XmlToolParser().parse(model_out,
                                          schemas_from_tools(tools))
    message = {"tool_calls": [c.to_openai() for c in calls]}
    parsed = bfcl.parse_native_calls(message)
    assert parsed == [("get_weather", {"city": "Paris", "days": 3})]
    assert bfcl.score(
        parsed,
        [{"name": "get_weather",
          "args": {"city": ["Paris"], "days": [3]},
          "required": ["city", "days"]}], False) is True


def test_host_overhead_bench_cpu():
    """Control-plane microbenchmark runs and reports all four host-path
    costs (pure host code, no device work)."""
    import json as _json
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=root)
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks",
                                      "host_overhead.py"),
         "--seqs", "16", "--iters", "10"],
        env=env, cwd=root, timeout=240, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    assert proc.returncode == 0
    d = _json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["metric"] == "host_step_overhead_us" and d["value"] > 0
    det = d["detail"]
    for k in ("schedule_us", "prepare_us", "prefix_match_us",
              "dp_route_probe_us"):
        assert det[k] > 0, (k, det)
