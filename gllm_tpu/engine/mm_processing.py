"""Chat-message → (token_ids, pixels) encoding for VL models.

Primary path: the checkpoint's HF AutoProcessor. Fallback (used when the
processor can't load — e.g. its video processor needs torchvision, absent
on TPU serving hosts): the reference's skeleton-tokenization design
(/root/reference/gllm/mm_common.py + model_runner.py encode_skeleton) —
apply the *tokenizer* chat template with one ``<|image_pad|>`` sentinel per
item, run the standalone image processor for pixels + grids, then expand
the i-th sentinel to that item's visual token count.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def processor_config_hash(model_dir: str, min_pixels=None,
                          max_pixels=None) -> str:
    """Digest of the checkpoint's processor configs — encoder and LM must
    agree on preprocessing for disagg (reference mm_common.py:23-58).
    Runtime pixel-bound overrides change the effective preprocessing, so
    they are folded into the digest: an encoder capped with
    --mm-processor-max-pixels and an uncapped LM frontend must NOT pass
    the agreement check (their placeholder grids would disagree)."""
    import hashlib
    import os
    h = hashlib.sha256()
    for fname in ("preprocessor_config.json", "processor_config.json",
                  "video_preprocessor_config.json"):
        path = os.path.join(model_dir, fname)
        if os.path.exists(path):
            with open(path, "rb") as f:
                h.update(fname.encode())
                h.update(f.read())
    if min_pixels is not None or max_pixels is not None:
        h.update(f"pixel_bounds:{min_pixels}:{max_pixels}".encode())
    return h.hexdigest()[:16]


def extract_mm_items(messages: List[dict]) -> List[Tuple[str, object]]:
    """Ordered [(modality, content), ...] from normalized messages
    (reference extract_mm_items_ordered)."""
    items = []
    for message in messages:
        contents = message.get("content")
        if not isinstance(contents, list):
            continue
        for content in contents:
            if content.get("type") == "image":
                items.append(("image", content["image"]))
            elif content.get("type") == "video":
                items.append(("video", content["video"]))
    return items


def apply_pixel_bounds(processor, min_pixels=None, max_pixels=None):
    """Clamp the pixel budget of an HF (image/video) processor in place
    (reference --mm-processor-min/max-pixels, encoder_engine.py:67-74):
    the smart-resize logic reads ``min_pixels``/``max_pixels`` (newer
    processors read ``size['shortest_edge'/'longest_edge']`` instead, so
    both spellings are set). Accepts an AutoProcessor (bounds applied to
    its image and video sub-processors) or a bare image processor."""
    subs = [s for s in (getattr(processor, "image_processor", None),
                        getattr(processor, "video_processor", None))
            if s is not None] or [processor]
    for sub in subs:
        if min_pixels is not None:
            sub.min_pixels = min_pixels
            if isinstance(getattr(sub, "size", None), dict):
                sub.size["shortest_edge"] = min_pixels
        if max_pixels is not None:
            sub.max_pixels = max_pixels
            if isinstance(getattr(sub, "size", None), dict):
                sub.size["longest_edge"] = max_pixels
    return processor


def load_image_processor(model_dir: str, vision_config: Dict,
                         min_pixels=None, max_pixels=None):
    """The checkpoint's image processor, or a config-derived default."""
    from transformers.models.qwen2_vl.image_processing_qwen2_vl import (
        Qwen2VLImageProcessor)
    try:
        proc = Qwen2VLImageProcessor.from_pretrained(
            model_dir, local_files_only=True)
    except Exception:
        proc = Qwen2VLImageProcessor(
            patch_size=vision_config.get("patch_size", 14),
            temporal_patch_size=vision_config.get("temporal_patch_size", 2),
            merge_size=vision_config.get("spatial_merge_size", 2))
    return apply_pixel_bounds(proc, min_pixels, max_pixels)


def encode_mm_fallback(tokenizer, image_processor, messages: List[dict],
                       cfg, **template_kwargs):
    """(token_ids, mm_input) without a working AutoProcessor.

    The tokenizer chat template must emit exactly one image/video
    placeholder token per item (the standard Qwen-VL templates do).
    """
    items = extract_mm_items(messages)
    ids = tokenizer.apply_chat_template(messages,
                                        add_generation_prompt=True,
                                        **template_kwargs)
    if not items:
        return list(ids), None

    images = [c for m, c in items if m == "image"]
    if any(m == "video" for m, _ in items):
        raise NotImplementedError(
            "video input requires the checkpoint's AutoProcessor")
    out = image_processor(images=images, return_tensors="np")
    pixel_values = out["pixel_values"]
    grid_thw = np.asarray(out["image_grid_thw"])
    merge = image_processor.merge_size ** 2
    counts = [int(t * h * w) // merge for t, h, w in grid_thw]

    expanded: List[int] = []
    item_i = 0
    for tok in ids:
        if tok == cfg.image_token_id:
            if item_i >= len(counts):
                raise ValueError("more image placeholders than images")
            expanded.extend([tok] * counts[item_i])
            item_i += 1
        else:
            expanded.append(int(tok))
    if item_i != len(counts):
        raise ValueError(f"{len(counts)} images but {item_i} placeholders "
                         "in the chat template output")
    return expanded, {"pixel_values": pixel_values,
                      "image_grid_thw": grid_thw}


def encode_mm_messages(llm, messages: List[dict], **kwargs):
    """Dispatch: AutoProcessor when available, fallback otherwise."""
    processor = None
    try:
        processor = llm.processor
    except Exception as e:
        logger.info("AutoProcessor unavailable (%s); using fallback "
                    "skeleton tokenization", e)
    if processor is not None:
        out = processor.apply_chat_template(
            messages, add_generation_prompt=True, tokenize=True,
            return_dict=True, return_tensors="np", **kwargs)
        ids = [int(t) for t in out["input_ids"][0]]
        mm_input = {}
        if out.get("pixel_values") is not None:
            mm_input["pixel_values"] = out["pixel_values"]
            # Kimi's processor names the grids "grid_thws"
            if out.get("grid_thws") is not None:
                mm_input["grid_thws"] = out["grid_thws"]
            else:
                mm_input["image_grid_thw"] = out.get("image_grid_thw")
        if out.get("pixel_values_videos") is not None:
            mm_input["video_pixel_values"] = out["pixel_values_videos"]
            mm_input["video_grid_thw"] = out.get("video_grid_thw")
            if out.get("second_per_grid_ts") is not None:
                mm_input["second_per_grid_ts"] = [
                    float(v) for v in out["second_per_grid_ts"]]
        return ids, (mm_input or None)

    if llm.tokenizer is None:
        raise ValueError("multimodal chat requires a tokenizer")
    if getattr(llm, "_mm_image_processor", None) is None:
        llm._mm_image_processor = load_image_processor(
            llm.config.model, llm.model_cfg.vision_config or {},
            min_pixels=llm.config.mm_processor_min_pixels,
            max_pixels=llm.config.mm_processor_max_pixels)
    return encode_mm_fallback(llm.tokenizer, llm._mm_image_processor,
                              messages, llm.model_cfg, **kwargs)
