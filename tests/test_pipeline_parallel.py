"""Pipeline parallelism on the 8-virtual-device CPU mesh.

Greedy byte-identity across pp configurations is the oracle (the same
discipline the reference applies to its distributed modes, SURVEY.md §4).
"""

import pytest
import torch

from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                             SchedulerConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.runner.pp_runner import split_layers
from gllm_tpu.sampling_params import SamplingParams

TINY = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=4,
    num_attention_heads=8, num_key_value_heads=4, intermediate_size=96,
    max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0,
)


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(21)
    d = tmp_path_factory.mktemp("pp_llama")
    LlamaForCausalLM(LlamaConfig(**TINY, attention_bias=False)
                     ).save_pretrained(d, safe_serialization=True)
    return str(d)


def run(model_dir, pp=1, tp=1, dp=1, method="chunked_prefill",
        assigned=None, n_prompts=4, attention_impl="auto"):
    cfg = EngineConfig(
        model=model_dir, dtype="float32", max_model_len=128,
        attention_impl=attention_impl,
        scheduler=SchedulerConfig(schedule_method=method,
                                  max_prefill_tokens=32,
                                  min_prefill_tokens=8,
                                  max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=256),
        parallel=ParallelConfig(pp=pp, tp=tp, dp=dp,
                                assigned_layers=assigned),
    )
    llm = LLM(config=cfg)
    prompts = [[3, 14, 15, 92, 6], [53, 58], [9, 7, 9, 3, 2, 3, 8, 4],
               [27, 1, 82][:max(1, n_prompts)]][:n_prompts]
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=10,
                                       ignore_eos=True))
    return [o.output_token_ids for o in outs]


def test_split_layers():
    assert split_layers(10, 4) == [(0, 3), (3, 6), (6, 8), (8, 10)]
    assert split_layers(4, 2, [1, 3]) == [(0, 1), (1, 4)]
    with pytest.raises(ValueError):
        split_layers(4, 2, [1, 1])


def test_pp2_matches_single(ckpt):
    assert run(ckpt, pp=2) == run(ckpt, pp=1)


def test_pp4_matches_single(ckpt):
    assert run(ckpt, pp=4) == run(ckpt, pp=1)


def test_pp2_tp2_matches_single(ckpt):
    assert run(ckpt, pp=2, tp=2) == run(ckpt, pp=1)


def test_pp_with_token_throttling(ckpt):
    got = run(ckpt, pp=2, method="token_throttling")
    assert got == run(ckpt, pp=1)


def test_pp_assigned_layers(ckpt):
    assert run(ckpt, pp=2, assigned=[1, 3]) == run(ckpt, pp=1)


def test_pp_pipeline_keeps_batches_in_flight(ckpt):
    # spy on step_async/collect interleaving: with pp=2 and several decode
    # sub-batches, at least one moment must have 2 batches in flight.
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128,
        scheduler=SchedulerConfig(schedule_method="token_throttling",
                                  max_prefill_tokens=32,
                                  max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=256),
        parallel=ParallelConfig(pp=2),
    )
    llm = LLM(config=cfg)
    max_depth = 0
    orig_launch = llm.runner.step_async

    def spy_launch(batch):
        nonlocal max_depth
        # at launch time the new batch joins len(_in_flight) others
        max_depth = max(max_depth, len(llm._in_flight) + 1)
        return orig_launch(batch)

    llm.runner.step_async = spy_launch
    llm.generate(
        prompt_token_ids=[[i + 2, i + 3, i + 4] for i in range(6)],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
    # pp=2 must actually keep TWO microbatches in flight at some moment —
    # the pipelining claim, not just "a batch existed" (VERDICT r1 weak 7)
    assert max_depth >= 2, max_depth


def test_pp2_pallas_matches_single(ckpt):
    """pp=2 with the Pallas engine path (interpret kernels on CPU)."""
    assert run(ckpt, pp=2, attention_impl="pallas") == run(ckpt, pp=1)


def test_pp2_tp2_pallas_matches_single(ckpt):
    """pp×tp with Pallas attention: each stage's trace nests the tp
    shard_map over that stage's own mesh (the context mesh) — the
    reference bar is FA3 under every parallel mode
    (layers/attention.py:92-140)."""
    assert run(ckpt, pp=2, tp=2, attention_impl="pallas") == run(ckpt,
                                                                 pp=1)


def test_pp2_dp2_matches_single(ckpt):
    """dp×pp grid: two private pipelines on disjoint device blocks
    (reference worker.py:831-889 runs the full pp×dp×tp grid)."""
    assert run(ckpt, pp=2, dp=2) == run(ckpt, pp=1)


def test_pp2_dp2_tp2_matches_single(ckpt):
    assert run(ckpt, pp=2, dp=2, tp=2) == run(ckpt, pp=1)


def test_pp2_logprobs_match_pp1():
    """Output + prompt logprobs computed on the last PP stage match the
    single-runner values (reference sampler runs on every last-stage
    rank, sampler.py:71-91)."""
    import numpy as np
    import tempfile
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(22)
    with tempfile.TemporaryDirectory() as d:
        LlamaForCausalLM(LlamaConfig(**TINY, attention_bias=False)
                         ).save_pretrained(d, safe_serialization=True)

        def go(pp):
            cfg = EngineConfig(
                model=d, dtype="float32", max_model_len=128,
                cache=CacheConfig(page_size=4, num_pages=256),
                parallel=ParallelConfig(pp=pp))
            sps = [SamplingParams(temperature=0.0, max_tokens=5,
                                  ignore_eos=True, logprobs=3,
                                  prompt_logprobs=2),
                   SamplingParams(temperature=0.0, max_tokens=5,
                                  ignore_eos=True, logprobs=2)]
            return LLM(config=cfg).generate(
                prompt_token_ids=[[3, 14, 15, 92, 6], [53, 58, 9, 21]],
                sampling_params=sps)

        base, pp2 = go(1), go(2)
        for a, b in zip(base, pp2):
            assert a.output_token_ids == b.output_token_ids
            for (ca, ia, la), (cb, ib, lb) in zip(a.logprobs, b.logprobs):
                assert ia == ib
                np.testing.assert_allclose([ca] + la, [cb] + lb,
                                           rtol=1e-5, atol=1e-6)
            assert (a.prompt_logprobs is None) == (b.prompt_logprobs
                                                  is None)
            if a.prompt_logprobs is not None:
                for pa, pb in zip(a.prompt_logprobs, b.prompt_logprobs):
                    assert (pa is None) == (pb is None)
                    if pa is not None:
                        assert pa[1] == pb[1]
                        np.testing.assert_allclose(
                            [pa[0]] + pa[2], [pb[0]] + pb[2],
                            rtol=1e-5, atol=1e-6)


def test_pp2_hybrid_gdn_matches_pp1(tmp_path):
    """Hybrid (GDN) model over pp=2: stage bounds align to the layer-type
    period; each stage owns its layers' paged KV + GDN slot pools
    (reference builds per-stage qwen3_5 layers via get_pp_layers,
    dist_utils.py:494-528)."""
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from test_hybrid_qwen3next import BASE, make_ckpt
    make_ckpt(tmp_path, num_hidden_layers=8,
              layer_types=list(BASE["layer_types"]) * 2)

    def go(pp):
        cfg = EngineConfig(
            model=str(tmp_path), dtype="float32", max_model_len=128,
            cache=CacheConfig(page_size=4, num_pages=128),
            parallel=ParallelConfig(pp=pp))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=[[3, 14, 15, 92, 6], [53, 58, 9],
                              [9, 7, 9, 3, 2, 3, 8, 4]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                           ignore_eos=True))]

    assert go(2) == go(1)


def test_pp_hybrid_stage_bounds_respect_period():
    assert split_layers(8, 2, multiple=4) == [(0, 4), (4, 8)]
    assert split_layers(12, 2, multiple=4) == [(0, 8), (8, 12)]
    with pytest.raises(ValueError):
        split_layers(4, 2, multiple=4)     # fewer period-units than pp
    with pytest.raises(ValueError):
        split_layers(8, 2, [2, 6], multiple=4)


def test_pp_quantized_matches_pp1_quantized(ckpt):
    """--quantization must reach the per-stage params (VERDICT r1 weak 5:
    it was silently dropped under pp)."""
    def run(pp):
        cfg = EngineConfig(
            model=ckpt, dtype="float32", max_model_len=128,
            quantization="int8",
            cache=CacheConfig(page_size=4, num_pages=256),
            parallel=ParallelConfig(pp=pp))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=[[5, 9, 23], [7, 7, 2]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))]

    assert run(2) == run(1)


def test_pp_stage_params_actually_quantized(ckpt):
    from gllm_tpu.ops.quant import Quantized
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128,
        quantization="int8",
        cache=CacheConfig(page_size=4, num_pages=64),
        parallel=ParallelConfig(pp=2))
    llm = LLM(config=cfg)
    import jax
    for stage in llm.runner.stages:
        leaves = jax.tree.leaves(
            stage.params,
            is_leaf=lambda x: isinstance(x, Quantized))
        assert any(isinstance(leaf, Quantized) for leaf in leaves)
