"""Pretty-print or convert a steptrace JSONL for bench post-mortems.

Usage:
  python -m gllm_tpu.obs.dump trace.jsonl            # event table + summary
  python -m gllm_tpu.obs.dump trace.jsonl --summary  # summary only
  python -m gllm_tpu.obs.dump trace.jsonl --format chrome > t.json
                                  # Chrome trace-event JSON (Perfetto)
  python -m gllm_tpu.obs.dump t.jsonl --since 1200 --kind decode,fused_block
  curl -s host:8000/steptrace | python -m gllm_tpu.obs.dump -  # live dump

The input is one JSON event per line (``StepTrace.to_jsonl``) or a single
JSON object with an ``events`` list (the ``GET /steptrace`` payload).
``--format chrome`` runs the same event→trace-event converter the
``GET /trace`` endpoint uses (gllm_tpu/obs/spans.py chrome_trace).
"""

from __future__ import annotations

import argparse
import json
import sys

from gllm_tpu.obs.steptrace import summarize

# ``reason`` is carried by chain_break events (waiting/pages/shape/
# spec/finish — docs/overlap_scheduling.md); blank for step events
_COLS = ("seq", "t", "kind", "reason", "num_seqs", "tokens", "k",
         "wall_ms")


def load_events(stream) -> list:
    text = stream.read()
    text = text.strip()
    if not text:
        return []
    if text.startswith("{") and "\n" not in text.split("}", 1)[0]:
        try:
            obj = json.loads(text)
            if isinstance(obj, dict) and "events" in obj:
                return obj["events"]
        except json.JSONDecodeError:
            pass
    events = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            events.append(json.loads(line))
    return events


def format_table(events: list) -> str:
    rows = [[str(e.get(c, "")) for c in _COLS] for e in events]
    widths = [max([len(c)] + [len(r[i]) for r in rows])
              for i, c in enumerate(_COLS)]
    lines = ["  ".join(c.ljust(w) for c, w in zip(_COLS, widths))]
    for r in rows:
        lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gllm_tpu.obs.dump",
        description="pretty-print or convert a steptrace JSONL")
    ap.add_argument("path", help="JSONL file, or - for stdin")
    ap.add_argument("--summary", action="store_true",
                    help="print only the by-kind wall-time summary")
    ap.add_argument("--format", choices=("table", "chrome"),
                    default="table",
                    help="chrome: emit Chrome trace-event JSON "
                         "(Perfetto-loadable; the GET /trace converter)")
    ap.add_argument("--since", type=int, default=0,
                    help="drop events whose ring seq is below this")
    ap.add_argument("--kind", default=None,
                    help="comma-separated event kinds to keep")
    args = ap.parse_args(argv)
    if args.path == "-":
        events = load_events(sys.stdin)
    else:
        with open(args.path) as f:
            events = load_events(f)
    if args.since:
        events = [e for e in events if e.get("seq", 0) >= args.since]
    if args.kind:
        keep = {k for k in args.kind.split(",") if k}
        events = [e for e in events if e.get("kind") in keep]
    if args.format == "chrome":
        from gllm_tpu.obs.spans import chrome_trace
        print(json.dumps(chrome_trace(events)))
        return 0
    if not args.summary:
        print(format_table(events))
        print()
    print(json.dumps(summarize(events), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
