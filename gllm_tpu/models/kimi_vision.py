"""Kimi K2.5 vision tower (MoonViT3d) + patch-merger projector.

TPU-native re-design of the reference tower
(/root/reference/gllm/models/kimi_k25_vision.py): patch embed (conv as a
flattened matmul), learnable 2-D spatial pos-emb bicubically interpolated
to the live grid plus a fixed sincos temporal embedding, 27 pre-LN blocks
with fused wqkv and an x/y-interleaved complex 2-D rotary, full attention
within one item (each image / video chunk is a single varlen segment),
then 2×2 spatial merge + temporal MEAN pooling and the PatchMergerMLP
(LayerNorm → Linear(k·C → k·C) → GELU → Linear(k·C → text_hidden)).

The tower runs replicated (no TP) like the reference — per-item batches
are small and the 2-D rope / fused packing don't shard usefully.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class KimiVisionConfig:
    hidden_size: int               # vt_hidden_size
    num_layers: int                # vt_num_hidden_layers
    num_heads: int                 # vt_num_attention_heads
    intermediate_size: int         # vt_intermediate_size
    patch_size: int
    merge_kernel: Tuple[int, int]  # merge_kernel_size (kh, kw)
    pos_emb_height: int            # init_pos_emb_height
    pos_emb_width: int
    pos_emb_time: int
    mm_hidden_size: int
    text_hidden_size: int
    projector_ln_eps: float = 1e-5
    in_channels: int = 3

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def merge_unit(self) -> int:
        return self.merge_kernel[0] * self.merge_kernel[1]

    @property
    def patch_input_dim(self) -> int:
        return self.in_channels * self.patch_size ** 2


def from_hf_vision_config(d: Dict[str, Any],
                          text_hidden: int) -> KimiVisionConfig:
    mk = d.get("merge_kernel_size", (2, 2))
    return KimiVisionConfig(
        hidden_size=d.get("vt_hidden_size", 1152),
        num_layers=d.get("vt_num_hidden_layers", 27),
        num_heads=d.get("vt_num_attention_heads", 16),
        intermediate_size=d.get("vt_intermediate_size", 4304),
        patch_size=(d.get("patch_size", 14)
                    if not isinstance(d.get("patch_size"), (list, tuple))
                    else int(d["patch_size"][0])),
        merge_kernel=(int(mk[0]), int(mk[1])),
        pos_emb_height=d.get("init_pos_emb_height", 64),
        pos_emb_width=d.get("init_pos_emb_width", 64),
        pos_emb_time=d.get("init_pos_emb_time", 4),
        mm_hidden_size=d.get("mm_hidden_size", d.get("vt_hidden_size",
                                                     1152)),
        text_hidden_size=d.get("text_hidden_size", text_hidden),
        projector_ln_eps=d.get("projector_ln_eps", 1e-5),
    )


def init_vision_params(cfg: KimiVisionConfig, seed: int = 0,
                       dtype=jnp.float32) -> Params:
    L, C, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    k = cfg.merge_unit
    mm, text = cfg.mm_hidden_size, cfg.text_hidden_size
    key = jax.random.key(seed + 17)
    ks = iter(jax.random.split(key, 12))

    def w(kk, shape, scale):
        return (jax.random.normal(kk, shape, jnp.float32)
                * scale).astype(dtype)

    s = C ** -0.5
    return {
        "patch_w": w(next(ks), (cfg.patch_input_dim, C),
                     cfg.patch_input_dim ** -0.5),
        "patch_b": jnp.zeros((C,), dtype),
        "pos_emb": w(next(ks), (cfg.pos_emb_height, cfg.pos_emb_width, C),
                     0.02),
        "blocks": {
            "norm0_w": jnp.ones((L, C), dtype),
            "norm0_b": jnp.zeros((L, C), dtype),
            "norm1_w": jnp.ones((L, C), dtype),
            "norm1_b": jnp.zeros((L, C), dtype),
            "wqkv_w": w(next(ks), (L, C, 3 * C), s),
            "wqkv_b": jnp.zeros((L, 3 * C), dtype),
            "wo_w": w(next(ks), (L, C, C), s),
            "wo_b": jnp.zeros((L, C), dtype),
            "fc0_w": w(next(ks), (L, C, I), s),
            "fc0_b": jnp.zeros((L, I), dtype),
            "fc1_w": w(next(ks), (L, I, C), I ** -0.5),
            "fc1_b": jnp.zeros((L, C), dtype),
        },
        "final_ln_w": jnp.ones((C,), dtype),
        "final_ln_b": jnp.zeros((C,), dtype),
        "merger": {
            "pre_norm_w": jnp.ones((mm,), dtype),
            "pre_norm_b": jnp.zeros((mm,), dtype),
            "fc1_w": w(next(ks), (k * mm, k * mm), (k * mm) ** -0.5),
            "fc1_b": jnp.zeros((k * mm,), dtype),
            "fc2_w": w(next(ks), (k * mm, text), (k * mm) ** -0.5),
            "fc2_b": jnp.zeros((text,), dtype),
        },
    }


# ---------------------------------------------------------------------------
# Host precompute per grid
# ---------------------------------------------------------------------------

def _sincos_1d(dim: int, t: int) -> np.ndarray:
    """Fixed sincos temporal embedding (reference
    _get_1d_sincos_pos_embed)."""
    omega = np.arange(dim // 2, dtype=np.float32) / (dim / 2.0)
    omega = 1.0 / 10000 ** omega
    out = np.arange(t, dtype=np.float32)[:, None] * omega[None, :]
    return np.concatenate([np.sin(out), np.cos(out)], axis=1)  # [t, dim]


@functools.lru_cache(maxsize=512)
def _rope2d_cos_sin(h: int, w: int, t: int, head_dim: int,
                    theta: float = 10000.0):
    """cos/sin [t*h*w, head_dim/2] for the x/y-interleaved complex rope
    (reference Rope2DPosEmb): complex slot c rotates by
    (c even → x_pos, c odd → y_pos) * freqs[c//2]."""
    flat = np.arange(h * w)
    x_pos = (flat % w).astype(np.float64)
    y_pos = (flat // w).astype(np.float64)
    nfreq = head_dim // 4
    dim_range = np.arange(0, head_dim, 4, dtype=np.float64)[:nfreq]
    freqs = 1.0 / theta ** (dim_range / head_dim)
    x_ang = x_pos[:, None] * freqs[None, :]      # [hw, hd/4]
    y_ang = y_pos[:, None] * freqs[None, :]
    ang = np.stack([x_ang, y_ang], axis=-1).reshape(h * w, -1)  # [hw, hd/2]
    ang = np.tile(ang, (t, 1))
    return np.cos(ang).astype(np.float32), np.sin(ang).astype(np.float32)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _ln(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return (((xf - mu) * jax.lax.rsqrt(var + eps)) * w + b).astype(x.dtype)


def _rope2d(a, cos, sin):
    """a: [L, nh, hd] — rotate real pairs (2c, 2c+1) by angle c."""
    L, nh, hd = a.shape
    af = a.astype(jnp.float32).reshape(L, nh, hd // 2, 2)
    re, im = af[..., 0], af[..., 1]
    c = cos[:, None, :]
    s = sin[:, None, :]
    out = jnp.stack([re * c - im * s, re * s + im * c], axis=-1)
    return out.reshape(L, nh, hd).astype(a.dtype)


def _vit_jit(params, pixels, pos, cos, sin, cfg: KimiVisionConfig,
             t: int, h: int, w: int):
    C, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    kh, kw = cfg.merge_kernel
    x = pixels @ params["patch_w"] + params["patch_b"]     # [t*h*w, C]
    x = x + pos.astype(x.dtype)
    L = x.shape[0]

    for i in range(cfg.num_layers):
        bp = jax.tree.map(lambda a: a[i], params["blocks"])
        hst = _ln(x, bp["norm0_w"], bp["norm0_b"])
        qkv = hst @ bp["wqkv_w"] + bp["wqkv_b"]
        # reference packs [L, 3, nh, hd]
        qkv = qkv.reshape(L, 3, nh, hd)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        q, k = _rope2d(q, cos, sin), _rope2d(k, cos, sin)
        scores = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * hd ** -0.5
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("hqk,khd->qhd", probs, v.astype(jnp.float32))
        attn = attn.reshape(L, C).astype(x.dtype)
        x = x + (attn @ bp["wo_w"] + bp["wo_b"])
        hst = _ln(x, bp["norm1_w"], bp["norm1_b"])
        hst = hst @ bp["fc0_w"] + bp["fc0_b"]
        hst = jax.nn.gelu(hst.astype(jnp.float32),
                          approximate=True).astype(x.dtype)
        x = x + (hst @ bp["fc1_w"] + bp["fc1_b"])

    x = _ln(x, params["final_ln_w"], params["final_ln_b"])

    # 2x2 spatial merge + temporal mean pool (reference _tpool_patch_merger)
    nhh, nww = h // kh, w // kw
    x = x.reshape(t, nhh, kh, nww, kw, C).transpose(0, 1, 3, 2, 4, 5)
    x = x.mean(axis=0).reshape(nhh * nww, kh * kw, C)

    m = params["merger"]
    x = _ln(x, m["pre_norm_w"], m["pre_norm_b"], cfg.projector_ln_eps)
    x = x.reshape(nhh * nww, -1)
    x = x @ m["fc1_w"] + m["fc1_b"]
    x = jax.nn.gelu(x.astype(jnp.float32), approximate=False).astype(x.dtype)
    return x @ m["fc2_w"] + m["fc2_b"]                 # [nhh*nww, text]


_vit_jit = jax.jit(_vit_jit, static_argnames=("cfg", "t", "h", "w"))


def _pos_embed(params, cfg: KimiVisionConfig, t: int, h: int, w: int):
    """Spatial grid interpolated to (h, w) + sincos temporal for t > 1."""
    pe = params["pos_emb"].astype(jnp.float32)           # [H0, W0, C]
    if (h, w) != (cfg.pos_emb_height, cfg.pos_emb_width):
        pe = jax.image.resize(pe, (h, w, pe.shape[-1]), method="bicubic")
    pe = pe.reshape(h * w, -1)
    if t == 1:
        return pe
    tw = jnp.asarray(_sincos_1d(cfg.hidden_size, t))     # [t, C]
    return (pe[None, :, :] + tw[:, None, :]).reshape(t * h * w, -1)


def embed_single(params: Params, cfg: KimiVisionConfig, pixels,
                 grid_thw: Tuple[int, int, int]) -> jnp.ndarray:
    """One image / video chunk: pixels [t*h*w, C·ps²] → projected
    embeddings [(h/kh)·(w/kw), text_hidden] (temporal pooling collapses
    the frame axis)."""
    t, h, w = (int(v) for v in grid_thw)
    cos, sin = _rope2d_cos_sin(h, w, t, cfg.head_dim)
    pos = _pos_embed(params, cfg, t, h, w)
    return _vit_jit(params, jnp.asarray(pixels), pos, jnp.asarray(cos),
                    jnp.asarray(sin), cfg, t, h, w)
