"""Async device<->host KV page mover.

Two tiny jit programs over the runner's whole KV pytree (leaves
``[L, P, page_size, ...]``; the page axis is axis 1 for every model
family — dense K/V stacks, MLA latent + DSA index caches):

- **gather**: ``kv[:, idx]`` → a fresh ``[L, n, page_size, ...]`` batch
  per leaf. Dispatched BEFORE the step program that may overwrite the
  source pages; per-device program order guarantees it reads
  pre-overwrite data, so the scheduler may free+remint the device pages
  the moment the intent is recorded.
- **scatter**: ``kv.at[:, idx].set(data)`` with buffer donation — an
  in-place page restore dispatched before the forward that reads it.
  Padding columns target page 0 (the dummy page, which absorbs garbage
  writes by design).

Transfers are batched per drain and padded to power-of-two page counts
so the jit cache stays logarithmic. Gathers are double-buffered: the
device->host copy starts async at dispatch and materializes into the
host pool one drain later (or on demand when the data is needed
earlier), keeping the fetch off the hot path.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Set

import jax
import jax.numpy as jnp
import numpy as np

from gllm_tpu import faults
from gllm_tpu.utils import next_pow2


@jax.jit
def _gather_pages(kv, idx):
    return jax.tree.map(lambda a: a[:, idx], kv)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_pages(kv, idx, data):
    return jax.tree.map(lambda a, d: a.at[:, idx].set(d.astype(a.dtype)),
                        kv, data)


def _pad_idx(pages: Sequence[int]) -> np.ndarray:
    n = len(pages)
    idx = np.zeros(next_pow2(n, 1), np.int32)   # pad → dummy page 0
    idx[:n] = pages
    return idx


class SwapEngine:
    """Stateless transfer programs + the pending-gather double buffer."""

    def __init__(self):
        # [(device leaves [L, n_pad, ...], host page ids, n)]
        self._pending: List[tuple] = []

    # ---- device -> host ---------------------------------------------------

    def gather(self, kv, dev_pages: Sequence[int],
               host_pages: Sequence[int]) -> None:
        """Dispatch a page gather and start its async host copy; the data
        lands in the pool at the next :meth:`materialize`."""
        # chaos point (docs/robustness.md): a failed device→host transfer
        # — the manager catches it and reverts the intents to recompute
        faults.FAULTS.maybe_raise("kvswap_transfer_fail")
        out = _gather_pages(kv, jnp.asarray(_pad_idx(dev_pages)))
        leaves = jax.tree.leaves(out)
        for leaf in leaves:
            try:
                leaf.copy_to_host_async()
            except (AttributeError, RuntimeError, TypeError):
                pass   # backend without async copies: np.asarray later
        self._pending.append((leaves, list(host_pages), len(dev_pages)))

    def pending_host_pages(self) -> Set[int]:
        return {h for _, hosts, _ in self._pending for h in hosts}

    def materialize(self, pool, skip_free: Optional[Set[int]] = None) -> int:
        """Land every pending gather in the host pool; returns the number
        of pages written. ``skip_free``: host pages released while their
        fetch was in flight — their slots may already belong to a new
        tenant, so the stale data is dropped instead of written."""
        moved = 0
        pending, self._pending = self._pending, []
        for leaves, host_pages, n in pending:
            np_leaves = [np.asarray(leaf) for leaf in leaves]
            for col, page in enumerate(host_pages[:n]):
                if skip_free and page in skip_free:
                    continue
                pool.write_page(page, np_leaves, col)
                moved += 1
        return moved

    # ---- host -> device ---------------------------------------------------

    def scatter(self, kv, dev_pages: Sequence[int], pool,
                host_pages: Sequence[int]):
        """Restore host pages into device pages; returns the new kv."""
        # chaos point: a failed host→device restore poisons the batch
        # that needed the pages — it propagates and the serving engine
        # quarantines that batch (docs/robustness.md)
        faults.FAULTS.maybe_raise("kvswap_transfer_fail")
        idx = _pad_idx(dev_pages)
        data = pool.read_pages(host_pages, pad_to=len(idx))
        tree = jax.tree.unflatten(jax.tree.structure(kv), data)
        return _scatter_pages(kv, jnp.asarray(idx), tree)
