"""Performance-attribution layer (ISSUE 10, docs/observability.md#tracing):

- SpanTrace lifecycle units: begin/event/finish, ring bound/eviction,
  open-bound untracking, phase-cap rollup, idempotent close;
- summarize() attribution math (host_ms_by_phase, overlap_efficiency,
  bubble_frac, MFU) on synthetic events;
- chrome_trace JSON schema (engine-phase tracks + request tracks,
  phase slices reconstruct the step wall);
- engine e2e on a dummy-weight CPU model: step events carry the phase
  breakdown, the phase-sum ≈ step-wall invariant holds on the
  synchronous engine, span trees complete for every request
  (queued → prefill → decode → finish) and fused chains record
  decode_chain spans;
- terminal paths (abort / deadline / quarantine) close spans;
- tracing=False: zero spans recorded, token streams byte-identical;
- /trace + /steptrace?kind= + POST /profile HTTP surface;
- obs.dump --format chrome / --kind / --since;
- the bench --tiny CPU smoke: attribution fields present and
  non-degenerate in the result JSON, ATTRIBUTION salvage line, chrome
  trace artifact (GLLM_BENCH_TRACE=1).
"""

import http.client
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.obs.spans import (SPANS, SpanTrace, StepFlopsModel,
                                chrome_trace, peak_flops)
from gllm_tpu.obs.steptrace import StepTrace, summarize
from gllm_tpu.sampling_params import SamplingParams

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_spans():
    SPANS.clear()
    yield
    SPANS.clear()


# ---- SpanTrace units -------------------------------------------------------

def test_span_lifecycle_and_ring_bound():
    tr = SpanTrace(capacity=4, max_open=8, max_phases=64)
    tr.begin(1, arrival_t=10.0, admitted_t=10.5, prompt_tokens=7)
    assert tr.open_count == 1
    tr.begin(1, arrival_t=99.0, admitted_t=99.5)     # idempotent
    assert tr.open_count == 1
    tr.event(1, "prefill_chunk", 10.6, 3.0, tokens=7)
    tr.event(999, "decode_step", 0.0, 1.0)           # untracked: no-op
    rec = tr.finish(1, "stop", 11.0, output_tokens=3)
    assert rec["reason"] == "stop" and rec["output_tokens"] == 3
    assert rec["phases"][0]["ph"] == "queued"
    assert rec["phases"][0]["dur_ms"] == pytest.approx(500.0)
    assert [p["ph"] for p in rec["phases"]] == ["queued", "prefill_chunk"]
    assert tr.open_count == 0
    assert tr.finish(1, "stop", 12.0) is None        # second close: no-op
    # ring eviction: capacity 4 keeps the newest 4 completed trees
    for sid in range(2, 9):
        tr.begin(sid, sid * 1.0, sid * 1.0 + 0.1)
        tr.finish(sid, "length", sid * 1.0 + 1)
    assert [r["seq_id"] for r in tr.spans()] == [5, 6, 7, 8]
    assert tr.dropped == 4


def test_span_open_bound_and_phase_cap():
    tr = SpanTrace(capacity=8, max_open=2, max_phases=3)
    tr.begin(1, 0.0, 0.1)
    tr.begin(2, 0.0, 0.1)
    tr.begin(3, 0.0, 0.1)                            # over the bound
    assert tr.open_count == 2 and tr.untracked == 1
    # phase cap: later events roll up into per-phase aggregates
    for i in range(6):
        tr.event(1, "decode_step", float(i), 2.0)
    rec = tr.finish(1, "length", 10.0)
    assert len(rec["phases"]) == 3                   # queued + 2 decode
    agg = rec["agg"]["decode_step"]
    assert agg["n"] == 4 and agg["ms"] == pytest.approx(8.0)


def test_flops_model_and_peak():
    fm = StepFlopsModel(num_layers=2, hidden_size=8, num_heads=2,
                        num_kv_heads=1, head_dim=4, intermediate_size=16,
                        vocab_size=32)
    # one decode row at context 10: body + lm_head + attn over 11 keys
    f = fm.step_flops([(1, 10, True)])
    attn = fm.attn_coeff * (10 + 1)
    assert f == fm.body_per_token + fm.lm_head_per_row + attn
    # a 4-step block over the same row reconciles with 4 single steps
    f4 = fm.block_flops([10], 4)
    singles = sum(fm.step_flops([(1, 10 + j, True)]) for j in range(4))
    assert f4 == pytest.approx(singles)
    assert peak_flops("TPU v5e") == pytest.approx(197e12)
    assert peak_flops("weird accelerator") == 0.0
    os.environ["GLLM_TPU_PEAK_TFLOPS"] = "2.5"
    try:
        assert peak_flops("anything") == pytest.approx(2.5e12)
    finally:
        del os.environ["GLLM_TPU_PEAK_TFLOPS"]


# ---- summarize() attribution math ------------------------------------------

def _step_event(tr, kind, t, sched, build, disp, coll, wall, dev,
                mfu=None, **extra):
    tr.record(kind, num_seqs=2, tokens=2, wall_ms=coll, rtt_ms=wall,
              ph={"schedule": sched, "build": build, "dispatch": disp,
                  "collect": coll},
              step_wall_ms=wall, dev_ms=dev,
              **({"mfu": mfu} if mfu is not None else {}), **extra)
    # pin the event's t for deterministic window math
    tr._buf[(tr._next_seq - 1) % tr.capacity]["t"] = t


def test_summarize_attribution_window():
    tr = StepTrace(capacity=64)
    # two decode steps: 10ms wall each, device 8ms, collect 2ms
    _step_event(tr, "decode", 0.010, 1.0, 2.0, 1.0, 2.0,
                wall=10.0, dev=8.0, mfu=0.5)
    _step_event(tr, "decode", 0.020, 1.0, 2.0, 1.0, 2.0,
                wall=10.0, dev=8.0, mfu=0.5)
    s = summarize(tr.events())
    assert s["host_ms_by_phase"] == {"schedule": 2.0, "build": 4.0,
                                     "dispatch": 2.0, "collect": 4.0}
    assert s["device_ms_by_kind"] == {"decode": 16.0}
    # hidden = (8-2)*2 of 16 device ms
    assert s["overlap_efficiency"] == pytest.approx(12 / 16)
    # window: first start 0.000 → last end 0.020 = 20ms; 16ms device
    assert s["bubble_frac"] == pytest.approx(1 - 16 / 20, abs=1e-4)
    # wall mfu: Σ(mfu·dev)/elapsed = 0.5*16/20; device mfu = 0.5
    assert s["mfu"] == pytest.approx(0.4, abs=1e-4)
    assert s["device_mfu"] == pytest.approx(0.5, abs=1e-4)


def test_summarize_without_attribution_fields_is_none():
    tr = StepTrace(capacity=8)
    tr.record("decode", tokens=4, wall_ms=2.0, num_seqs=1)
    s = summarize(tr.events())
    assert s["host_ms_by_phase"] is None
    assert s["overlap_efficiency"] is None
    assert s["bubble_frac"] is None and s["mfu"] is None


# ---- chrome_trace schema ---------------------------------------------------

def test_chrome_trace_schema_and_phase_reconstruction():
    tr = StepTrace(capacity=16)
    _step_event(tr, "prefill", 0.050, 2.0, 3.0, 1.0, 4.0,
                wall=12.0, dev=5.0)
    spans = [{"seq_id": 7, "t0": 100.0, "t1": 100.2, "reason": "stop",
              "prompt_tokens": 5, "output_tokens": 3,
              "phases": [{"ph": "queued", "t": 100.0, "dur_ms": 10.0},
                         {"ph": "decode_chain", "t": 100.05,
                          "dur_ms": 20.0, "k": 8}]}]
    doc = chrome_trace(tr.events(), spans, span_t0=100.0)
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    for e in evs:
        assert e["ph"] in ("X", "M")
        assert "name" in e and "pid" in e
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] >= 0
    xs = [e for e in evs if e["ph"] == "X"]
    eng = [e for e in xs if e["pid"] == 1]
    req = [e for e in xs if e["pid"] == 2]
    # engine phase slices: schedule..collect are contiguous and span
    # exactly step_wall, ending at the event's t
    by_name = {e["name"]: e for e in eng}
    order = ["prefill:schedule", "prefill:build", "prefill:dispatch",
             "prefill:wait", "prefill:collect"]
    present = [n for n in order if n in by_name]
    assert present[0] == "prefill:schedule"
    first = by_name[present[0]]
    last = by_name[present[-1]]
    span_us = (last["ts"] + last["dur"]) - first["ts"]
    assert span_us == pytest.approx(12.0 * 1e3, rel=0.10)
    assert last["ts"] + last["dur"] == pytest.approx(0.050 * 1e6, abs=2)
    assert "prefill:device" in by_name
    # request track: root slice + children on tid 7
    assert all(e["tid"] == 7 for e in req)
    names = {e["name"] for e in req}
    assert "queued" in names and "decode_chain" in names
    assert any(n.startswith("request 7") for n in names)
    json.dumps(doc)                                   # serializable


# ---- engine e2e (dummy weights, CPU) ---------------------------------------

TINY_MODEL = dict(architecture="LlamaForCausalLM", vocab_size=256,
                  hidden_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, head_dim=16, intermediate_size=128,
                  max_position=256)


def make_llm(**over):
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.models.config import ModelConfig
    cfg = EngineConfig(load_format="dummy", dtype="float32",
                       max_model_len=128, max_num_seqs=8,
                       scheduler=SchedulerConfig(max_prefill_tokens=64,
                                                 max_decode_seqs=8),
                       cache=CacheConfig(page_size=4, num_pages=128))
    for k, v in over.items():
        setattr(cfg, k, v)
    cfg.validate()
    return LLM(config=cfg, model_cfg=ModelConfig(**TINY_MODEL))


GREEDY = dict(temperature=0.0, ignore_eos=True)


def test_sync_engine_phase_breakdown_and_spans():
    llm = make_llm()
    from gllm_tpu.obs.steptrace import TRACE
    mark = TRACE.mark()
    outs = llm.generate(prompt_token_ids=[[3, 5, 7, 9], [11, 13]],
                        sampling_params=[
                            SamplingParams(max_tokens=6, **GREEDY),
                            SamplingParams(max_tokens=4, **GREEDY)])
    assert all(o.finish_reason == "length" for o in outs)
    steps = [e for e in TRACE.events(since=mark)
             if e["kind"] in ("prefill", "decode", "fused_block")]
    assert steps, "no step events recorded"
    tot_ph = tot_wall = 0.0
    for e in steps:
        assert set(e["ph"]) == {"schedule", "build", "dispatch",
                                "collect"}
        assert e["dev_ms"] >= 0 and e["step_wall_ms"] > 0
        ph_sum = sum(e["ph"].values())
        # phases never exceed the step wall (small scheduling jitter
        # allowed); the aggregate invariant below is the 10% criterion
        assert ph_sum <= e["step_wall_ms"] * 1.10 + 0.5
        tot_ph += ph_sum
        tot_wall += e["step_wall_ms"]
    # synchronous engine (no overlap): phase sums reconstruct the
    # measured step wall within 10%
    assert tot_ph == pytest.approx(tot_wall, rel=0.10)
    s = summarize(steps)
    assert s["host_ms_by_phase"] is not None
    assert set(s["device_ms_by_kind"]) <= {"prefill", "decode",
                                           "fused_block"}
    assert 0.0 <= s["overlap_efficiency"] <= 1.0
    assert s["bubble_frac"] is None or 0.0 <= s["bubble_frac"] <= 1.0
    # span trees: one completed tree per request, none left open
    # (per-ENGINE ring: seq_ids restart per LLM, so each engine owns one)
    assert llm.spans.open_count == 0
    recs = {r["seq_id"]: r for r in llm.spans.spans()}
    assert len(recs) == 2
    for r in recs.values():
        assert r["reason"] == "length"
        phs = [p["ph"] for p in r["phases"]]
        assert phs[0] == "queued"
        assert "prefill_chunk" in phs and "decode_step" in phs
        assert r["t1"] > r["t0"]


def test_fused_engine_records_decode_chain_spans():
    llm = make_llm(overlap_scheduling=True, multi_step_decode=4)
    outs = llm.generate(prompt_token_ids=[[2, 4, 6, 8]],
                        sampling_params=SamplingParams(max_tokens=12,
                                                       **GREEDY))
    assert outs[0].num_output_tokens == 12
    (rec,) = llm.spans.spans()
    chains = [p for p in rec["phases"] if p["ph"] == "decode_chain"]
    assert chains and all(c["k"] >= 1 for c in chains)
    assert llm.spans.open_count == 0


def test_tracing_off_is_byte_identical_and_records_nothing():
    prompts = [[3, 5, 7, 9], [2, 4, 6]]
    sps = [SamplingParams(max_tokens=8, **GREEDY) for _ in prompts]
    import dataclasses as dc
    want = [o.output_token_ids for o in make_llm().generate(
        prompt_token_ids=prompts,
        sampling_params=[dc.replace(s) for s in sps])]
    llm_off = make_llm(tracing=False)
    assert llm_off.tracing is False
    got = [o.output_token_ids for o in llm_off.generate(
        prompt_token_ids=prompts,
        sampling_params=[dc.replace(s) for s in sps])]
    assert got == want
    assert llm_off.spans.spans() == []
    assert llm_off.spans.open_count == 0


def test_terminal_paths_close_spans():
    """abort / deadline / quarantine all close the request's span tree
    with the terminal reason (no tree may leak open)."""
    from gllm_tpu.engine.serving_engine import ServingEngine
    from gllm_tpu.faults import FAULTS
    FAULTS.reset()
    llm = make_llm()
    eng = ServingEngine(llm)
    try:
        # abort mid-stream (the model may hit the length cap first on a
        # fast box — either way the span closes with the chunk's reason)
        ha = eng.submit([5, 6, 7], SamplingParams(max_tokens=5000,
                                                  **GREEDY))
        last = ha.chunks.get(timeout=60)      # at least one token flowed
        eng.abort(ha.seq_id)
        while last.finish_reason is None:
            last = ha.chunks.get(timeout=60)
        assert last.finish_reason in ("abort", "length")
        spans = llm.spans
        deadline = time.monotonic() + 10
        while not any(r["seq_id"] == ha.seq_id for r in spans.spans()):
            assert time.monotonic() < deadline, "span never closed"
            time.sleep(0.01)
        rec = [r for r in spans.spans() if r["seq_id"] == ha.seq_id][-1]
        assert rec["reason"] == last.finish_reason
        # deadline mid-generation
        hb = eng.submit([9, 9, 9], SamplingParams(max_tokens=10000,
                                                  **GREEDY),
                        deadline_s=0.25)
        for c in hb:
            last = c
        assert last.finish_reason == "deadline"
        rec = [r for r in spans.spans() if r["seq_id"] == hb.seq_id][-1]
        assert rec["reason"] == "deadline"
        # quarantine (injected step exception)
        FAULTS.arm("step_exception:0:1")
        hc = eng.submit([1, 2, 3], SamplingParams(max_tokens=8,
                                                  **GREEDY))
        for c in hc:
            last = c
        assert last.finish_reason == "error"
        recs = [r for r in spans.spans() if r["seq_id"] == hc.seq_id]
        if recs:                        # quarantined after admission
            assert recs[-1]["reason"] == "error"
        assert spans.open_count == 0
    finally:
        FAULTS.reset()
        eng.shutdown()


# ---- HTTP surface ----------------------------------------------------------

def _drive_completion(port):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/v1/completions", body=json.dumps({
        "prompt": [5, 6, 7, 8], "max_tokens": 6, "temperature": 0,
        "ignore_eos": True}),
        headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 200, r.read()
    r.read()
    conn.close()


@pytest.fixture(scope="module")
def trace_server():
    from gllm_tpu.entrypoints.api_server import serve
    llm = make_llm()
    httpd = serve(llm, "127.0.0.1", 0, served_model="trace-smoke")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    # drive one request so the steptrace ring has content (the span
    # ring is cleared per test — span-needing tests drive their own)
    _drive_completion(port)
    yield port
    httpd.shutdown()
    httpd.state.engine.shutdown()


def _req(port, method, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request(method, path)
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, body


def test_trace_endpoint_serves_chrome_json(trace_server):
    _drive_completion(trace_server)     # fresh spans (ring cleared per test)
    status, body = _req(trace_server, "GET", "/trace")
    assert status == 200
    doc = json.loads(body)
    evs = doc["traceEvents"]
    pids = {e["pid"] for e in evs if e["ph"] == "X"}
    assert 1 in pids and 2 in pids      # engine + request tracks
    assert any(e["name"].endswith(":collect") for e in evs
               if e["ph"] == "X")


def test_steptrace_kind_filter(trace_server):
    status, body = _req(trace_server, "GET", "/steptrace?kind=prefill")
    assert status == 200
    d = json.loads(body)
    assert d["events"] and all(e["kind"] == "prefill"
                               for e in d["events"])
    status, body = _req(trace_server, "GET",
                        "/steptrace?kind=prefill,decode")
    kinds = {e["kind"] for e in json.loads(body)["events"]}
    assert kinds <= {"prefill", "decode"}


def test_profile_oneshot_endpoint(trace_server, tmp_path, monkeypatch):
    monkeypatch.setenv("GLLM_PROFILE_DIR", str(tmp_path))
    status, body = _req(trace_server, "POST", "/profile?seconds=0.1")
    assert status == 200, body
    d = json.loads(body)
    assert d["status"] == "ok" and d["trace_dir"] == str(tmp_path)
    assert os.path.isdir(str(tmp_path))
    # artifact landed (jax profiler writes plugins/profile/<run>/)
    assert any(os.scandir(str(tmp_path)))
    status, body = _req(trace_server, "POST", "/profile?seconds=0")
    assert status == 400
    status, body = _req(trace_server, "POST", "/profile?seconds=bogus")
    assert status == 400
    # a legacy /stop_profile must NOT truncate an in-flight one-shot
    box = {}

    def oneshot():
        box["r"] = _req(trace_server, "POST", "/profile?seconds=4")

    th = threading.Thread(target=oneshot)
    th.start()
    # poll: before the capture starts /stop_profile is a harmless noop
    # (200); once the one-shot owns the profiler it must refuse (409)
    deadline = time.monotonic() + 3.0
    saw_409 = False
    while time.monotonic() < deadline:
        status, _ = _req(trace_server, "POST", "/stop_profile")
        if status == 409:
            saw_409 = True
            break
        time.sleep(0.1)
    th.join()
    assert saw_409, "stop_profile never refused during the one-shot"
    assert box["r"][0] == 200, box["r"][1]


# ---- dump CLI --------------------------------------------------------------

def test_dump_chrome_format_and_filters(tmp_path, capsys):
    from gllm_tpu.obs import dump
    tr = StepTrace(capacity=16)
    _step_event(tr, "decode", 0.010, 1.0, 1.0, 1.0, 1.0,
                wall=5.0, dev=3.0)
    tr.record("compile", dispatch="step")
    p = tmp_path / "t.jsonl"
    tr.to_jsonl(str(p))
    assert dump.main([str(p), "--format", "chrome"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert any(e.get("name") == "decode:collect"
               for e in doc["traceEvents"])
    # kind/since filters drop events before formatting
    assert dump.main([str(p), "--kind", "compile", "--summary"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out[out.index("{"):])["compiles"] == 1
    assert dump.main([str(p), "--since", "2", "--summary"]) == 0


# ---- bench --tiny CPU smoke (the attribution acceptance gate) --------------

@pytest.mark.obs_smoke
def test_bench_tiny_attribution_smoke(tmp_path):
    """bench.py --tiny (inner, 4 requests) must emit non-degenerate
    attribution: host_ms_by_phase / device_ms_by_kind /
    overlap_efficiency / mfu in the result JSON, a salvageable
    ATTRIBUTION line, and a loadable Chrome trace artifact — the bench
    trajectory must never again have numbers without a why."""
    env = dict(os.environ,
               GLLM_BENCH_SAMPLED="0", GLLM_BENCH_TRACE="1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--tiny",
         "--inner", "--requests", "4"],
        cwd=str(tmp_path), env=env, text=True, timeout=540,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    assert proc.returncode == 0, proc.stdout[-3000:]
    lines = proc.stdout.strip().splitlines()
    result = json.loads(
        [ln for ln in lines if ln.startswith("{")][-1])
    # salvage line rides right behind RESULT
    attr_lines = [ln for ln in lines if ln.startswith("ATTRIBUTION ")]
    assert attr_lines
    attr = json.loads(attr_lines[-1][len("ATTRIBUTION "):])
    for blob in (result, attr):
        hp = blob["host_ms_by_phase"]
        assert hp and sum(hp.values()) > 0
        assert set(hp) == {"schedule", "build", "dispatch", "collect"}
        dm = blob["device_ms_by_kind"]
        assert dm and sum(dm.values()) > 0
        assert blob["overlap_efficiency"] is not None
        assert 0.0 <= blob["overlap_efficiency"] <= 1.0
    # --tiny declares a nominal CPU peak so both MFU estimators are
    # exercised; the salvage line keeps the window estimator under its
    # OWN key (never swapped for the workload-level result mfu)
    assert result["mfu"] is not None and result["mfu"] > 0
    assert attr["window_mfu"] is not None and attr["window_mfu"] > 0
    assert result["bubble_frac"] is None \
        or 0.0 <= result["bubble_frac"] <= 1.0
    # chrome artifact loads and has engine + request tracks
    doc = json.load(open(result["trace_path"]))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} >= {1, 2}
    assert all(e["dur"] >= 0 for e in xs)
