"""Threaded serving core: continuous-batching loop + per-request streams.

The reference splits this across PipeAsyncLLM (asyncio streams,
/root/reference/gllm/async_llm_engine.py:11-139) and the worker processes it
talks to over zmq. Our single-controller design needs neither asyncio nor
IPC: one engine thread owns the scheduler + runner and runs the continuous
batching loop; HTTP handler threads submit requests through a thread-safe
queue and block on per-sequence output queues (SSE streams one queue item
per token). Client disconnects abort the sequence mid-flight, matching the
reference's disconnect→abort propagation.

Request-lifecycle robustness (docs/robustness.md): the reference survives
faults by process supervision — a crashed worker is restarted from
outside. A single-controller engine must survive them in-process instead:

- **admission control**: bounded intake queue + max-resident-requests;
  over-limit submits raise :class:`RequestRejected` (HTTP 429/503 with
  Retry-After in api_server) instead of growing an unbounded queue.
- **deadlines**: per-request wall-clock budgets (``SamplingParams.
  deadline_s`` / submit kwarg / ``config.request_deadline_s`` TTL) abort
  requests stuck in the waiting queue or overrunning, with a terminal
  ``deadline`` chunk.
- **fault isolation**: a step exception quarantines only the scheduled
  batch (``LLM.quarantine_step_failure``) — those requests get terminal
  error chunks, everything else reschedules, and the engine returns to
  idle instead of hot-retrying the failed step forever. N consecutive
  failures escalate to a latched unhealthy state (readiness 503,
  admission closed, liveness still up).
- **watchdog**: the engine thread updates a heartbeat every loop pass; a
  watchdog thread flips readiness while the heartbeat is stale (a hung
  device dispatch blocks the loop inside collect) and restores it on
  recovery.
- **graceful drain**: ``shutdown(drain=True)`` stops admitting, lets
  in-flight requests finish (bounded), then closes every open handle
  with a terminal chunk before joining — no client blocks forever.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import List, Optional

from gllm_tpu import faults
from gllm_tpu.engine.llm import LLM
from gllm_tpu.obs import metrics as obs
from gllm_tpu.obs.steptrace import TRACE
from gllm_tpu.sampling_params import SamplingParams

logger = logging.getLogger(__name__)

_M_SUBMITTED = obs.counter("gllm_requests_submitted_total",
                           "requests submitted to the serving engine")
_M_ACTIVE = obs.gauge("gllm_requests_active",
                      "requests with an open output stream")
_M_ABORTED = obs.counter("gllm_requests_aborted_total",
                         "requests aborted (client disconnect or error)")
_M_REJECTED = obs.counter(
    "gllm_requests_rejected_total",
    "submits rejected by admission control, by reason "
    "(queue_full/resident_limit/unhealthy/draining)", ("reason",))
_M_DEADLINE = obs.counter(
    "gllm_request_deadline_exceeded_total",
    "requests aborted because their wall-clock deadline/TTL expired")
_M_STEP_FAIL = obs.counter(
    "gllm_engine_step_failures_total",
    "engine iterations that raised (each quarantines its batch)")
_M_HEALTHY = obs.gauge(
    "gllm_engine_healthy",
    "1 while the engine accepts work; 0 after the unhealthy latch")
_M_HB_AGE = obs.gauge(
    "gllm_engine_heartbeat_age_seconds",
    "age of the engine thread's last loop-iteration heartbeat")


class RequestRejected(Exception):
    """Admission control refused a submit. ``status`` is the HTTP code
    the api_server maps it to (429 over-capacity, 503 unavailable) and
    ``retry_after`` the Retry-After hint in seconds."""

    def __init__(self, reason: str, message: str, status: int = 429,
                 retry_after: float = 1.0):
        super().__init__(message)
        self.reason = reason
        self.status = status
        self.retry_after = retry_after


@dataclasses.dataclass
class StreamChunk:
    token_id: Optional[int]
    text: str
    finish_reason: Optional[str]
    # cumulative counts for usage reporting
    num_prompt_tokens: int = 0
    num_output_tokens: int = 0
    # (chosen_logprob, top_ids, top_logprobs) for this token, when the
    # request asked for logprobs
    logprob: Optional[tuple] = None
    # full per-position prompt logprobs, attached on the finishing chunk
    prompt_logprobs: Optional[list] = None
    # authoritative full output text on the finishing chunk (stop-string
    # truncation may shorten it relative to the streamed deltas)
    final_text: Optional[str] = None
    # terminal failure detail (quarantine / shutdown / engine death) —
    # the finish_reason says what class of end this is, error says why
    error: Optional[str] = None


class RequestHandle:
    # liveness poll interval for the bounded get below
    POLL_S = 0.5

    def __init__(self, seq_id: int, prompt_len: int, engine=None):
        self.seq_id = seq_id
        self.prompt_len = prompt_len
        self.chunks: "queue.Queue[StreamChunk]" = queue.Queue()
        # when set, __iter__ polls engine liveness instead of blocking
        # forever on a queue a dead engine thread will never feed
        self._engine = engine

    def __iter__(self):
        while True:
            if self._engine is None:
                chunk = self.chunks.get()
            else:
                try:
                    chunk = self.chunks.get(timeout=self.POLL_S)
                except queue.Empty:
                    if not self._engine.is_alive:
                        # drain anything that raced in before declaring
                        # the stream dead
                        try:
                            chunk = self.chunks.get_nowait()
                        except queue.Empty:
                            yield StreamChunk(None, "", "error",
                                              error="engine thread died")
                            return
                    else:
                        continue
            yield chunk
            if chunk.finish_reason is not None:
                return


def deliver_output(llm: LLM, out, handle: RequestHandle,
                   emitted: dict) -> None:
    """Turn one SeqOutput into a StreamChunk on the request's queue
    (shared by the single-host and multi-host serving engines)."""
    text = ""
    final_text = None
    if llm.tokenizer is not None:
        # the engine step may already have detokenized (stop strings) —
        # emit the delta of seq.output_text beyond what this handle
        # already streamed
        if out.new_token_id is not None:
            llm._stream_detokenize(out.seq)
        if out.finish_reason is not None:
            final_text = llm._finalize(out.seq).text
        full = out.seq.output_text
        text = full[emitted.get(out.seq.seq_id, 0):]
        emitted[out.seq.seq_id] = len(full)
    if out.new_token_id is not None or out.finish_reason:
        lp = None
        if out.new_token_id is not None and out.seq.output_logprobs:
            lp = out.seq.output_logprobs[-1]
        handle.chunks.put(StreamChunk(
            token_id=out.new_token_id,
            text=text,
            finish_reason=out.finish_reason,
            num_prompt_tokens=out.seq.prompt_len,
            num_output_tokens=out.seq.num_output_tokens,
            logprob=lp,
            prompt_logprobs=(out.seq.prompt_logprobs
                             if out.finish_reason else None),
            final_text=final_text))
    if out.finish_reason is not None:
        emitted.pop(out.seq.seq_id, None)


class ServingEngine:
    """Owns the LLM on a dedicated thread; thread-safe submit/abort."""

    def __init__(self, llm: LLM, *,
                 max_queued_requests: Optional[int] = None,
                 max_resident_requests: Optional[int] = None,
                 request_deadline_s: Optional[float] = None,
                 max_step_failures: Optional[int] = None,
                 watchdog_stall_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None):
        self.llm = llm
        cfg = getattr(llm, "config", None)

        def knob(override, name, default):
            if override is not None:
                return override
            return getattr(cfg, name, default) if cfg is not None \
                else default

        # 0 = unbounded/disabled (byte-identical legacy behavior)
        self.max_queued_requests = knob(max_queued_requests,
                                        "max_queued_requests", 0)
        self.max_resident_requests = knob(max_resident_requests,
                                          "max_resident_requests", 0)
        self.request_deadline_s = knob(request_deadline_s,
                                       "request_deadline_s", 0.0)
        self.max_step_failures = max(1, knob(max_step_failures,
                                             "max_step_failures", 3))
        self.watchdog_stall_s = knob(watchdog_stall_s,
                                     "watchdog_stall_s", 0.0)
        self.drain_timeout_s = knob(drain_timeout_s, "drain_timeout_s",
                                    5.0)
        if cfg is not None and getattr(cfg, "fault_inject", ""):
            faults.FAULTS.arm(cfg.fault_inject)

        self._intake: "queue.Queue" = queue.Queue()
        self._handles: dict[int, RequestHandle] = {}
        self._seqs: dict[int, object] = {}
        self._emitted: dict[int, int] = {}   # seq_id → chars streamed
        self._deadlines: dict[int, float] = {}  # seq_id → abs monotonic
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._draining = False
        self._healthy = True
        self._stalled = False
        self._failed_steps = 0          # consecutive; reset on success
        self._heartbeat = time.monotonic()
        _M_HEALTHY.set(1)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gllm-engine")
        self._thread.start()
        self._watchdog: Optional[threading.Thread] = None
        if self.watchdog_stall_s > 0:
            self._watchdog = threading.Thread(target=self._watch,
                                              daemon=True,
                                              name="gllm-watchdog")
            self._watchdog.start()

    # ---- health / readiness (any thread) -----------------------------------

    @property
    def is_alive(self) -> bool:
        """Liveness: the engine thread is running (/healthz)."""
        return self._thread.is_alive() and not self._stop

    @property
    def heartbeat_age(self) -> float:
        return time.monotonic() - self._heartbeat

    def readiness(self) -> tuple:
        """(ready, reason) — admission-facing readiness (/readyz). An
        unready engine still serves liveness: a load balancer drains it,
        the supervisor does not kill it unless /healthz also fails."""
        if not self.is_alive:
            return False, "dead"
        if not self._healthy:
            return False, "unhealthy"
        if self._draining:
            return False, "draining"
        if self._stalled:
            return False, "stalled"
        return True, "ok"

    def health(self) -> dict:
        age = self.heartbeat_age
        _M_HB_AGE.set(age)
        ready, why = self.readiness()
        with self._lock:
            resident = len(self._handles)
        return {"alive": self.is_alive, "ready": ready, "reason": why,
                "healthy": self._healthy, "draining": self._draining,
                "stalled": self._stalled,
                "heartbeat_age_s": round(age, 3),
                "consecutive_step_failures": self._failed_steps,
                "resident_requests": resident,
                "queued_requests": self._intake.qsize()}

    # ---- client-facing (any thread) ---------------------------------------

    def _admit(self) -> None:
        """Admission control; raises RequestRejected instead of letting
        the intake queue grow without bound. Limits of 0 = legacy
        unbounded behavior."""
        if faults.FAULTS.fire("intake_burst"):
            _M_REJECTED.inc(reason="queue_full")
            raise RequestRejected(
                "queue_full", "intake queue full (injected burst)",
                status=429, retry_after=1.0)
        if not self._healthy:
            _M_REJECTED.inc(reason="unhealthy")
            raise RequestRejected(
                "unhealthy", "engine is unhealthy (latched after "
                "repeated step failures)", status=503, retry_after=30.0)
        if self._draining or self._stop:
            _M_REJECTED.inc(reason="draining")
            raise RequestRejected("draining", "engine is shutting down",
                                  status=503, retry_after=5.0)
        if self.max_resident_requests:
            with self._lock:
                resident = len(self._handles)
            if resident >= self.max_resident_requests:
                _M_REJECTED.inc(reason="resident_limit")
                raise RequestRejected(
                    "resident_limit",
                    f"{resident} requests resident (limit "
                    f"{self.max_resident_requests})",
                    status=429, retry_after=1.0)
        if self.max_queued_requests \
                and self._intake.qsize() >= self.max_queued_requests:
            _M_REJECTED.inc(reason="queue_full")
            raise RequestRejected(
                "queue_full",
                f"intake queue full (limit {self.max_queued_requests})",
                status=429, retry_after=1.0)

    def submit(self, token_ids: List[int],
               sampling_params: SamplingParams,
               mm_input: Optional[dict] = None,
               disagg_items: Optional[list] = None,
               target_dp: Optional[int] = None,
               deadline_s: Optional[float] = None) -> RequestHandle:
        sampling_params.validate()
        self._admit()
        mm_state = None
        if mm_input:
            # Hashing + position building over full pixel arrays is
            # hundreds of ms for big images — do it before taking the
            # engine-wide lock.
            from gllm_tpu.engine.mm import build_mm_state
            mm_state = build_mm_state(token_ids, self.llm.model_cfg,
                                      **mm_input)
        ttl = (deadline_s if deadline_s is not None
               else sampling_params.deadline_s
               if sampling_params.deadline_s is not None
               else self.request_deadline_s)
        with self._lock:
            seq = self.llm._allocate_seq(token_ids, sampling_params)
            seq.mm = mm_state
            if target_dp is not None:
                # per-DP-endpoint pinning (reference --endpoint-per-dp,
                # llm_engine.py:121-133 + sequence.py:79-83): the endpoint
                # that received the request pins its KV/prefix-cache to
                # that replica
                seq.target_dp = target_dp
            if disagg_items is not None:
                # skeleton request → coordinator (gate A admits it later)
                seq._disagg_items = disagg_items
            handle = RequestHandle(seq.seq_id, len(token_ids),
                                   engine=self)
            self._handles[seq.seq_id] = handle
            self._seqs[seq.seq_id] = seq
            if ttl and ttl > 0:
                self._deadlines[seq.seq_id] = time.monotonic() + ttl
            _M_SUBMITTED.inc()
            _M_ACTIVE.set(len(self._handles))
        self._intake.put(seq)
        self._wake.set()
        return handle

    def abort(self, seq_id: int) -> None:
        self.llm.abort(seq_id)
        self._wake.set()

    def shutdown(self, drain: bool = False,
                 timeout: Optional[float] = None) -> None:
        """Stop the engine. ``drain=True`` first stops admitting and
        waits (bounded by ``timeout``/``drain_timeout_s``) for in-flight
        requests to finish; either way every still-open handle gets a
        terminal chunk so no HTTP thread blocks forever on a stream the
        engine will never feed."""
        self._draining = True
        if drain:
            limit = time.monotonic() + (timeout if timeout is not None
                                        else self.drain_timeout_s)
            while time.monotonic() < limit:
                with self._lock:
                    if not self._handles and self._intake.empty():
                        break
                time.sleep(0.01)
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5)
        # the loop's finally already closed the handles if the thread
        # exited; this is the backstop for a hung/killed thread
        self._close_open_handles("abort", "engine shutdown")
        tiers = getattr(self.llm, "prefix_tiers", None)
        if tiers is not None:
            # stop serving peers, drain pending disk writes; host-tier
            # pages are NOT force-demoted here (an operator who wants
            # the warm cache persisted calls flush_host_to_disk first)
            try:
                tiers.close()
            except Exception:  # pragma: no cover - shutdown must finish
                logger.exception("prefix store close failed")

    # ---- engine thread ----------------------------------------------------

    def _run(self) -> None:
        try:
            self._run_loop()
        except Exception:  # pragma: no cover - last-resort containment
            logger.exception("engine loop died")
            self._healthy = False
            _M_HEALTHY.set(0)
        finally:
            self._close_open_handles("abort", "engine stopped")

    def _run_loop(self) -> None:
        llm = self.llm
        while not self._stop:
            self._heartbeat = time.monotonic()
            drained = False
            while True:
                try:
                    seq = self._intake.get_nowait()
                except queue.Empty:
                    break
                try:
                    items = getattr(seq, "_disagg_items", None)
                    if items is not None:
                        llm.submit_disagg(seq, items)
                    else:
                        llm.add_seq(seq)
                except ValueError as e:
                    self._deliver_error(seq.seq_id, str(e))
                drained = True
            self._expire_deadlines()
            if not llm.has_unfinished:
                if not drained:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                continue
            try:
                outputs = llm.step()
            except Exception as e:
                logger.exception("engine step failed")
                self._on_step_failure(e)
                continue
            self._failed_steps = 0
            for out in outputs:
                handle = self._handles.get(out.seq.seq_id)
                if handle is None:
                    continue
                deliver_output(llm, out, handle, self._emitted)
                if out.finish_reason is not None:
                    with self._lock:
                        self._handles.pop(out.seq.seq_id, None)
                        self._seqs.pop(out.seq.seq_id, None)
                        self._deadlines.pop(out.seq.seq_id, None)
                        _M_ACTIVE.set(len(self._handles))
                    self._emitted.pop(out.seq.seq_id, None)
            # aborted sequences never produce a SeqOutput → close their
            # streams here
            self._reap_aborted()

    # ---- fault isolation ---------------------------------------------------

    def _on_step_failure(self, exc: BaseException) -> None:
        """Quarantine the failed step's batch; escalate to the latched
        unhealthy state after max_step_failures consecutive failures
        (the old behavior failed EVERY request and then hot-retried the
        broken step forever because the failing sequences stayed
        scheduler-resident)."""
        _M_STEP_FAIL.inc()
        self._failed_steps += 1
        detail = f"{type(exc).__name__}: {exc}"
        try:
            failed = self.llm.quarantine_step_failure()
        except Exception:
            logger.exception("quarantine after step failure failed")
            self._latch_unhealthy(f"unrecoverable step failure: {detail}")
            return
        # Latch BEFORE delivering the terminal chunks: a client whose
        # failed request just returned may immediately probe /readyz,
        # and readiness must already reflect the escalation by the time
        # any client can observe the failure (the pre-fix order lost
        # that race — the order-dependent healthz-vs-readyz flake).
        if self._failed_steps >= self.max_step_failures:
            self._latch_unhealthy(
                f"{self._failed_steps} consecutive step failures "
                f"(last: {detail})")
        for sid in failed:
            self._deliver_error(sid, "error", detail)

    def _latch_unhealthy(self, why: str) -> None:
        if not self._healthy:
            return
        logger.error("engine latched unhealthy: %s", why)
        self._healthy = False
        _M_HEALTHY.set(0)
        TRACE.record("fault", point="engine_unhealthy", error=why[:200])
        try:
            self.llm.quarantine_step_failure(everything=True)
        except Exception:  # pragma: no cover
            logger.exception("full quarantine failed")
        self._close_open_handles("error", why)

    def _expire_deadlines(self) -> None:
        """Abort requests past their wall-clock budget — including ones
        still sitting unscheduled in the waiting queue, which the
        per-step output path would never touch."""
        if not self._deadlines:
            return
        now = time.monotonic()
        with self._lock:
            expired = [sid for sid, t in self._deadlines.items()
                       if now >= t]
        for sid in expired:
            self.llm.abort(sid)
            _M_DEADLINE.inc()
            self._deliver_error(sid, "deadline")

    def _reap_aborted(self):
        with self._lock:
            dead = [sid for sid, seq in self._seqs.items()
                    if seq.is_finished]
            for sid in dead:
                self._seqs.pop(sid, None)
        for sid in dead:
            self._deliver_error(sid, "abort")

    def _deliver_error(self, seq_id: int, reason: str,
                       detail: Optional[str] = None) -> None:
        if getattr(self.llm.config, "tracing", True):
            # abort/deadline/shutdown requests never reach the engine's
            # normal finish path — close their span tree with the same
            # reason the terminal chunk carries (first close wins)
            self.llm.spans.finish(seq_id, reason or "error",
                                  time.monotonic())
        with self._lock:
            handle = self._handles.pop(seq_id, None)
            self._seqs.pop(seq_id, None)
            self._deadlines.pop(seq_id, None)
            _M_ACTIVE.set(len(self._handles))
        self._emitted.pop(seq_id, None)
        if handle is not None:
            _M_ABORTED.inc()
            handle.chunks.put(StreamChunk(None, "", reason or "error",
                                          error=detail))

    def _close_open_handles(self, reason: str,
                            detail: Optional[str] = None) -> None:
        """Terminal chunk for every open stream (engine-wide failure or
        shutdown) — replaces the old _fail_all, which leaked the
        scheduler state that caused the hot-retry loop."""
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
            self._seqs.clear()
            self._emitted.clear()
            self._deadlines.clear()
            _M_ACTIVE.set(0)
        if handles:
            _M_ABORTED.inc(len(handles))
        if getattr(self.llm.config, "tracing", True):
            now = time.monotonic()
            for h in handles:
                self.llm.spans.finish(h.seq_id, reason or "error",
                                      now)
        for h in handles:
            h.chunks.put(StreamChunk(None, "", reason, error=detail))

    # ---- watchdog ----------------------------------------------------------

    def _watch(self) -> None:
        """Detect a wedged engine thread (hung device dispatch blocks the
        loop inside collect, so the heartbeat goes stale) and flip
        readiness while it lasts. Liveness is untouched: the supervisor
        restarts on /healthz, the balancer routes on /readyz."""
        stall = self.watchdog_stall_s
        interval = max(0.02, min(stall / 4.0, 1.0))
        while not self._stop and self._thread.is_alive():
            time.sleep(interval)
            age = time.monotonic() - self._heartbeat
            _M_HB_AGE.set(age)
            if age > stall:
                if not self._stalled:
                    self._stalled = True
                    TRACE.record("fault", point="dispatch_stall_detected",
                                 age_s=round(age, 3))
                    logger.error(
                        "engine heartbeat stale %.2fs (> %.2fs) — "
                        "readiness off", age, stall)
            elif self._stalled:
                self._stalled = False
                logger.info("engine heartbeat recovered — readiness on")
