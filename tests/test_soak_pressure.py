"""Sustained-pressure soak: speculative decoding + preemption + mixed
sampling under high occupancy (VERDICT r04 next #7).

A randomized (seeded) workload of greedy / seeded-sampled / penalized /
logprobs / stop-string requests runs on a dp=2 engine with speculative
decoding and deliberately scarce KV pages, forcing preemption cycles and
draft drops. Invariants:

- no page leak: every replica's allocator returns to its initial free
  count once all requests finish;
- no starvation: every request finishes (bounded by the suite timeout);
- acceptance stats sane: 0 <= accepted <= proposed, and drafts were
  actually proposed despite the pressure;
- the greedy subset is byte-identical to a no-spec rerun of the same
  workload (spec decoding must never COST correctness under pressure).
"""

import random

import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, ParallelConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(11)
    d = str(tmp_path_factory.mktemp("soak_model"))
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=512, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    return d


def _workload(n=22, seed=0):
    rng = random.Random(seed)
    prompts, sps = [], []
    for i in range(n):
        if rng.random() < 0.5:      # draft-friendly (repetitive)
            unit = [rng.randrange(1, 120) for _ in range(rng.randrange(2, 4))]
            prompt = (unit * 8)[:rng.randrange(6, 20)]
        else:                        # cold
            prompt = [rng.randrange(1, 120) for _ in range(
                rng.randrange(4, 28))]
        kind = rng.randrange(4)
        if kind == 0:               # plain greedy
            sp = SamplingParams(temperature=0.0, ignore_eos=True,
                                max_tokens=rng.randrange(8, 28))
        elif kind == 1:             # penalized greedy (+ bias)
            sp = SamplingParams(temperature=0.0, ignore_eos=True,
                                max_tokens=rng.randrange(8, 24),
                                repetition_penalty=1.2,
                                presence_penalty=0.3,
                                logit_bias={rng.randrange(1, 120): 2.0})
        elif kind == 2:             # seeded sampled
            sp = SamplingParams(temperature=0.8, seed=rng.randrange(100),
                                ignore_eos=True,
                                max_tokens=rng.randrange(8, 24))
        else:                        # greedy + logprobs or stop
            sp = SamplingParams(temperature=0.0, ignore_eos=True,
                                max_tokens=rng.randrange(8, 24),
                                logprobs=(2 if rng.random() < 0.5
                                          else None),
                                stop=(["xq!"] if rng.random() < 0.5
                                      else []))
        prompts.append(prompt)
        sps.append(sp)
    return prompts, sps


def _run(ckpt, spec, prompts, sps):
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=256,
        spec_decode="ngram" if spec else None, spec_k=4, spec_ngram=2,
        cache=CacheConfig(page_size=4, num_pages=56),  # scarce → preempt
        parallel=ParallelConfig(dp=2))
    llm = LLM(config=cfg)
    outs = llm.generate(
        prompt_token_ids=[list(p) for p in prompts],
        sampling_params=[SamplingParams(**vars(sp)) for sp in sps])
    return llm, outs


def test_soak_spec_preemption_pressure(ckpt):
    prompts, sps = _workload()
    llm, outs = _run(ckpt, True, prompts, sps)

    # every request finished with a real finish reason
    assert len(outs) == len(prompts)
    assert all(o.finish_reason in ("length", "stop") for o in outs)

    # pressure actually happened, speculation actually ran
    total_preempt = sum(s.num_preemptions for s in llm.schedulers)
    assert total_preempt > 0, "workload did not create memory pressure"
    st = [s.spec_stats for s in llm.schedulers]
    proposed = sum(x["proposed"] for x in st)
    accepted = sum(x["accepted"] for x in st)
    assert proposed > 0
    assert 0 <= accepted <= proposed

    # no page leak on either replica (page 0 is the permanent dummy)
    for s in llm.schedulers:
        assert s.mm.num_free_pages == s.mm.num_pages - 1, \
            (s.mm.num_free_pages, s.mm.num_pages)

    # greedy subset byte-identical to a no-spec rerun under the same
    # pressure (different batch composition over time is allowed — greedy
    # outputs must not depend on it)
    _, base_outs = _run(ckpt, False, prompts, sps)
    for sp, a, b in zip(sps, outs, base_outs):
        if sp.temperature == 0.0:
            assert a.output_token_ids == b.output_token_ids, sp
