"""Vision tower vs the HF Qwen2.5-VL ViT (unit-level oracle).

Covers window/full attention block alternation, multi-frame grids,
non-square grids, edge windows (grid not divisible by the window side),
and the q-chunked full-attention path used for large images.
"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from gllm_tpu.models import vision

VD = dict(depth=4, hidden_size=32, intermediate_size=48, num_heads=4,
          patch_size=2, temporal_patch_size=2, in_channels=3,
          spatial_merge_size=2, out_hidden_size=24, window_size=8,
          fullatt_block_indexes=[1, 3], hidden_act="silu")


@pytest.fixture(scope="module")
def hf_and_params():
    from transformers import Qwen2_5_VLConfig
    from transformers.models.qwen2_5_vl.modeling_qwen2_5_vl import (
        Qwen2_5_VisionTransformerPretrainedModel)
    torch.manual_seed(0)
    hf = Qwen2_5_VisionTransformerPretrainedModel._from_config(
        Qwen2_5_VLConfig(vision_config=VD).vision_config)
    hf.eval().float()
    vcfg = vision.from_hf_vision_config(VD)
    sd = hf.state_dict()
    L, H = vcfg.depth, vcfg.hidden_size

    def stack(fmt, trans=True):
        ws = np.stack([sd[fmt.format(i)].numpy() for i in range(L)])
        return jnp.asarray(ws.transpose(0, 2, 1) if trans else ws)

    params = {
        "patch_embed": jnp.asarray(
            sd["patch_embed.proj.weight"].reshape(H, -1).numpy().T),
        "blocks": {
            "norm1": stack("blocks.{}.norm1.weight", False),
            "norm2": stack("blocks.{}.norm2.weight", False),
            "qkv_w": stack("blocks.{}.attn.qkv.weight"),
            "qkv_b": stack("blocks.{}.attn.qkv.bias", False),
            "proj_w": stack("blocks.{}.attn.proj.weight"),
            "proj_b": stack("blocks.{}.attn.proj.bias", False),
            "gate_w": stack("blocks.{}.mlp.gate_proj.weight"),
            "gate_b": stack("blocks.{}.mlp.gate_proj.bias", False),
            "up_w": stack("blocks.{}.mlp.up_proj.weight"),
            "up_b": stack("blocks.{}.mlp.up_proj.bias", False),
            "down_w": stack("blocks.{}.mlp.down_proj.weight"),
            "down_b": stack("blocks.{}.mlp.down_proj.bias", False),
        },
        "merger": {
            "ln_q": jnp.asarray(sd["merger.ln_q.weight"].numpy()),
            "fc1_w": jnp.asarray(sd["merger.mlp.0.weight"].numpy().T),
            "fc1_b": jnp.asarray(sd["merger.mlp.0.bias"].numpy()),
            "fc2_w": jnp.asarray(sd["merger.mlp.2.weight"].numpy().T),
            "fc2_b": jnp.asarray(sd["merger.mlp.2.bias"].numpy()),
        },
    }
    return hf, vcfg, params


@pytest.mark.parametrize("grid", [
    (1, 4, 8),      # multi-window
    (1, 8, 8),
    (2, 4, 4),      # multi-frame (full attention is per-frame)
    (1, 6, 10),     # edge windows (not divisible by window side)
])
def test_vit_matches_hf(hf_and_params, grid):
    hf, vcfg, params = hf_and_params
    t, h, w = grid
    rng = np.random.default_rng(1)
    pixels = rng.standard_normal(
        (t * h * w, vcfg.patch_input_dim)).astype(np.float32)
    with torch.no_grad():
        want = hf(torch.tensor(pixels),
                  grid_thw=torch.tensor([list(grid)])).numpy()
    got = np.asarray(vision.embed_single(params, vcfg, pixels, grid))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_vit_chunked_full_attention(hf_and_params, monkeypatch):
    """Force the q-chunked full-attention path (used for large images) and
    check it is exact vs HF."""
    hf, vcfg, params = hf_and_params
    monkeypatch.setattr(vision, "_FULL_DENSE_MAX", 8)
    monkeypatch.setattr(vision, "_FULL_CHUNK", 16)
    grid = (1, 6, 10)
    rng = np.random.default_rng(4)
    pixels = rng.standard_normal(
        (60, vcfg.patch_input_dim)).astype(np.float32)
    with torch.no_grad():
        want = hf(torch.tensor(pixels),
                  grid_thw=torch.tensor([list(grid)])).numpy()
    vision._vit_jit.clear_cache()
    got = np.asarray(vision.embed_single(params, vcfg, pixels, grid))
    vision._vit_jit.clear_cache()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
