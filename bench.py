#!/usr/bin/env python
"""Headline benchmark: synthetic-ShareGPT offline throughput.

Mirrors the reference's measurement harness
(/root/reference/examples/batch_inference.py:56-74 — offline ShareGPT
reqs/s + output tok/s) with a synthetic, zero-egress workload: a
Llama-3.2-1B-shaped dummy-weight model served by the full engine
(continuous batching + chunked prefill + paged KV) on one chip.

Prints exactly ONE JSON line to stdout:
  {"metric": "sharegpt_output_tok_s_per_chip", "value": N, "unit": "tok/s",
   "vs_baseline": N / 2000.0}

vs_baseline denominator: BASELINE.json's flagship target (2000 output tok/s
for Llama-3-70B PP=8 on v5e-8 — i.e. ~250 tok/s/chip × 8; a 1B model on one
chip should beat it by a wide margin; it is the round-over-round yardstick).

Robustness (the rounds 1-2 history: one backend-init crash, one device-side
stall that wedged the single-tenant tunnel for >40 min):
 - the default invocation is a supervisor; the measurement runs in a child
   process under a hard deadline;
 - before EVERY chip-touching attempt the supervisor probes the tunnel with
   a fresh short-lived subprocess (``timeout``-bounded ``jax.devices()``)
   and polls until it answers — a wedged tunnel burns probe time, not
   measurement time;
 - attempts run a DEGRADE LADDER: the first profile is the simplest serving
   loop (multi_step_decode=1, no overlap) to get ANY number; only if that
   succeeds and budget remains is the full-featured profile tried, and the
   best successful number wins;
 - the inner process emits ``[bench phase] <name>`` markers so a timeout's
   error JSON says *where* it died, and faulthandler dumps stacks every
   300 s for device-side stalls.

Usage: python bench.py            # real chip (axon/tpu)
       python bench.py --tiny     # CPU smoke (small model, small workload)
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import subprocess
import sys
import time

METRIC = "sharegpt_output_tok_s_per_chip"
PHASE_TAG = "[bench phase] "
# vs_baseline denominator: BASELINE.json's flagship target (see module
# docstring) — one constant so the salvage and report paths can't drift
BASELINE_TOK_S = 2000.0

# Degrade ladder: ``minimal`` first to get ANY number on a freshly
# recovered tunnel (its bucket surface — decode seqs ≤64, model_len 1024,
# prefill chunk 512 — compiles in minutes, and every compile lands in the
# persistent XLA cache so later rungs start warm), then ``full`` (the
# headline rung: fused multi-step blocks + overlap) BEFORE conservative —
# the budget must reach the rung that matters even if the middle rung's
# compiles would not fit (r5: conservative cold-compiles ran past the
# supervisor deadline while full was already cache-warm).
PROFILES = ("minimal", "full", "conservative")

# Regression gate (ISSUE 20, GLLM_BENCH_BASELINE=<committed BENCH JSON>):
# the efficiency metrics a perf PR must not silently give back, with the
# direction that counts as better. Gated with tolerance — these are
# measured quantities, not counters.
GATE_METRICS = (
    ("bubble_frac", "lower"),
    ("mfu", "higher"),
    ("tokens_per_dispatch", "higher"),
)


def check_bench_regression(result, baseline, rel_tol=0.10, abs_tol=0.02):
    """Compare a measured result dict against a committed baseline BENCH
    JSON. Returns a list of human-readable offender strings, each naming
    the regressed metric — empty when the run holds the line. A metric
    absent from either side is skipped (profiles differ in what they
    measure), never failed: the gate flags regressions, not coverage."""
    failures = []
    for name, direction in GATE_METRICS:
        base, got = baseline.get(name), result.get(name)
        if base is None or got is None:
            continue
        slack = max(abs(base) * rel_tol, abs_tol)
        if direction == "lower" and got > base + slack:
            failures.append(
                f"{name} regressed: {got} vs baseline {base} "
                f"(lower is better, tolerance {slack:.4f})")
        elif direction == "higher" and got < base - slack:
            failures.append(
                f"{name} regressed: {got} vs baseline {base} "
                f"(higher is better, tolerance {slack:.4f})")
    return failures


def run_bench_gate(result, baseline_path):
    """GLLM_BENCH_BASELINE gate: compare the measured pass against the
    committed baseline, record the verdict in the result JSON, and
    return the process exit code (nonzero on regression, with every
    offending metric named on stderr)."""
    with open(baseline_path) as f:
        baseline = json.load(f)
    failures = check_bench_regression(result, baseline)
    result["baseline_gate"] = {
        "baseline": os.path.abspath(baseline_path),
        "failures": failures,
    }
    for m in failures:
        log(f"[bench] REGRESSION {m}")
    return 1 if failures else 0


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def probe_tunnel(deadline, interval=30):
    """Poll the axon tunnel with fresh bounded subprocesses until
    ``jax.devices()`` answers. Single-tenant relay: a probe is the only
    safe way to learn whether the lease is free without wedging a real
    attempt. Returns True when the tunnel answered."""
    while time.monotonic() < deadline:
        t0 = time.monotonic()
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d=jax.devices(); "
                 "print(jax.default_backend(), len(d))"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, timeout=120)
            if r.returncode == 0:
                log(f"[bench supervisor] tunnel probe ok "
                    f"({time.monotonic()-t0:.0f}s): {r.stdout.strip()!r}")
                return True
            log(f"[bench supervisor] tunnel probe rc={r.returncode}: "
                f"{r.stdout[-300:]!r}")
        except subprocess.TimeoutExpired:
            log("[bench supervisor] tunnel probe timed out (120s); "
                "tunnel busy/wedged, polling again")
        time.sleep(max(0, interval - (time.monotonic() - t0)))
    return False


def last_phase(text):
    ph = "start"
    for line in text.splitlines():
        if line.startswith(PHASE_TAG):
            ph = line[len(PHASE_TAG):].strip()
    return ph


def salvage_result(text):
    """tok/s from a ``RESULT <value>`` line the inner process prints the
    moment the measured pass ends (benchmarks/kernel_tune.py run_inner's
    salvage pattern): a child that measured but then wedged or died in
    the sampled pass / report / teardown still yields its number instead
    of reading as a silent 0.0 regression. None when no RESULT landed."""
    for line in reversed((text or "").strip().splitlines()):
        if line.startswith("RESULT "):
            try:
                return float(line.split()[1])
            except (IndexError, ValueError):
                continue   # truncated by the kill mid-write; scan on
    return None


def salvage_attribution(text):
    """The ``ATTRIBUTION <json>`` line the inner process prints right
    after RESULT (measured-pass phase/device/overlap/MFU attribution,
    ISSUE 10): a salvaged run keeps its attribution instead of going
    blind — the r02-r04 trajectory had numbers with no *why*. None when
    no parseable line landed."""
    for line in reversed((text or "").strip().splitlines()):
        if line.startswith("ATTRIBUTION "):
            try:
                return json.loads(line[len("ATTRIBUTION "):])
            except json.JSONDecodeError:
                continue   # truncated mid-write; scan on
    return None


def supervise(args, argv):
    """Degrade-ladder supervisor; always prints one JSON line.

    Each attempt's jit compiles land in the persistent XLA cache
    (``.jax_cache/``) even when the attempt itself is killed, so a
    timed-out profile is retried once: the retry replays every compile
    the first attempt finished and spends its budget measuring. The
    ladder therefore makes forward progress across wedges instead of
    starting from scratch.
    """
    deadline = time.monotonic() + (1020 if not args.tiny else 420)
    best = None          # best successful (rank, profile, parsed)
    last_tail, phase = "", "start"
    last_rc = None       # rc of the last failed attempt ("timeout" for
                         # a deadline kill) — carried into failure JSON
    on_chip = not args.tiny
    ladder = [[p, 0] for p in PROFILES]   # [profile, attempts_so_far]

    def consider(rank, profile, parsed):
        nonlocal best
        if best is None or rank > best[0]:
            best = (rank, profile, parsed)

    def consider_salvage(out_text, profile, how):
        """A measured-pass RESULT that outlived its process: rank below
        any COMPLETE json of the same rung class (no metrics snapshot),
        above nothing."""
        v = salvage_result(out_text)
        if v is None:
            return False
        log(f"[bench supervisor] salvaged RESULT {v:.1f} tok/s from "
            f"{how} {profile} attempt")
        parsed = {"metric": METRIC, "value": round(v, 2), "unit": "tok/s",
                  "vs_baseline": round(v / BASELINE_TOK_S, 4),
                  "salvaged": True,
                  "salvaged_from": how}
        attr = salvage_attribution(out_text)
        if attr:
            # attribution survives the salvage: the measured pass's
            # phase/overlap/MFU fields ride the ATTRIBUTION line
            parsed.update(attr)
        consider((0 if profile == "minimal" else 1, 0, v), profile,
                 parsed)
        return True

    while ladder:
        profile, tried = ladder[0]
        remaining = deadline - time.monotonic()
        if remaining < 120:
            break
        if best is not None and remaining < 360:
            # don't risk a wedge chasing a bigger profile on a thin budget
            break
        if on_chip and not probe_tunnel(
                min(deadline - 60, time.monotonic() + remaining / 2)):
            log("[bench supervisor] tunnel never answered; stopping")
            break
        budget = max(60, min(deadline - time.monotonic(), 640))
        log(f"[bench supervisor] profile={profile} attempt {tried + 1}, "
            f"budget {budget:.0f}s")
        ladder[0][1] += 1
        timed_out = crashed = False
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--inner",
                 "--profile", profile] + argv,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, timeout=budget)
            tail = proc.stdout[-8000:]
            sys.stderr.write(tail)
            sys.stderr.flush()
            phase = last_phase(proc.stdout)
            if proc.returncode == 0:
                for line in reversed(proc.stdout.strip().splitlines()):
                    line = line.strip()
                    if line.startswith("{"):
                        try:
                            parsed = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if parsed.get("metric") == METRIC:
                            # minimal's shorter-context workload is not
                            # comparable to the other rungs: any
                            # conservative/full number outranks it; a
                            # complete JSON outranks a same-class salvage
                            consider((0 if profile == "minimal" else 1,
                                      1, parsed["value"]), profile, parsed)
                            break
                if best is None:
                    last_tail = tail[-1500:]
            else:
                # a baseline-gate failure is a COMPLETED measurement with
                # a regression verdict, not a crash: the child printed its
                # full result JSON (baseline_gate.failures non-empty) and
                # then exited nonzero.  Forward both verbatim — no salvage,
                # no retry (a retry would re-measure and could mask the
                # regression behind run-to-run noise).
                for line in reversed(proc.stdout.strip().splitlines()):
                    line = line.strip()
                    if line.startswith("{"):
                        try:
                            parsed = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if (parsed.get("metric") == METRIC
                                and parsed.get("baseline_gate", {})
                                          .get("failures")):
                            parsed["profile"] = profile
                            log("[bench supervisor] baseline gate failed; "
                                "propagating nonzero exit")
                            print(json.dumps(parsed))
                            return proc.returncode
                        break
                crashed = True
                last_rc = proc.returncode
                last_tail = tail[-1500:]
                log(f"[bench supervisor] profile={profile} exited "
                    f"rc={proc.returncode} in phase '{phase}'")
                consider_salvage(proc.stdout, profile,
                                 f"rc={proc.returncode}")
        except subprocess.TimeoutExpired as e:
            out = (e.stdout or b"")
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            phase = last_phase(out)
            last_rc = "timeout"
            last_tail = (out[-1500:]
                         + f"\n[timeout after {budget:.0f}s in phase "
                           f"'{phase}' profile={profile}]")
            log(f"[bench supervisor] profile={profile} timed out in "
                f"phase '{phase}'")
            timed_out = True
            consider_salvage(out, profile, "timeout")
            # a timeout on chip very likely wedged the tunnel; the next
            # loop iteration's probe will wait it out
        if (timed_out or crashed) and ladder[0][1] < 2:
            if crashed:
                # bounded backoff before the retry: a crash right after
                # device init (tunnel lease race, transient backend
                # error) usually clears in seconds, and the retry replays
                # every finished compile from the persistent cache
                back = min(30.0, max(0.0, deadline - time.monotonic()
                                     - 120))
                if back > 0:
                    log(f"[bench supervisor] backing off {back:.0f}s "
                        "before retry")
                    time.sleep(back)
            continue          # retry same profile, now cache-warm
        ladder.pop(0)
    if best is not None:
        _, profile, parsed = best
        parsed["profile"] = profile
        if profile == "minimal":
            # shorter-context fallback workload; don't read this as the
            # round-over-round headline (see PROFILES docstring)
            parsed["comparable"] = False
        print(json.dumps(parsed))
        return 0
    # No number at all: NEVER a bare 0.0 — the JSON carries failed=true,
    # the child's rc (or "timeout"), the last phase marker, and the
    # output tail so a harness/tunnel failure is distinguishable from a
    # real regression (the r02-r04 blindness class).
    print(json.dumps({
        "metric": METRIC, "value": 0.0, "unit": "tok/s",
        "vs_baseline": 0.0, "failed": True, "rc": last_rc,
        "phase": phase,
        "error": f"no profile produced a number; last phase '{phase}': "
                 + last_tail[-900:],
    }))
    return 0


def build_workload(rng, n_requests, max_model_len, tiny=False):
    """Synthetic ShareGPT-like length distribution."""
    from gllm_tpu.sampling_params import SamplingParams
    prompts, params = [], []
    for _ in range(n_requests):
        if tiny:
            p_len = int(rng.integers(8, 64))
            o_len = int(rng.integers(8, 32))
        else:
            p_len = int(min(max(rng.lognormal(5.2, 0.8), 16), 1024))
            o_len = int(min(max(rng.lognormal(4.8, 0.7), 16), 512))
        p_len = min(p_len, max_model_len - o_len - 1)
        prompts.append(rng.integers(1, 30000, size=p_len).tolist())
        params.append(SamplingParams(temperature=0.0, max_tokens=o_len,
                                     ignore_eos=True))
    return prompts, params


# The dense-peak bf16 TFLOP/s table moved to gllm_tpu/obs/spans.py
# (PEAK_TFLOPS) — the per-step MFU gauge needs it too, and two copies
# would drift. It turns measured tok/s into an MFU so rounds compare
# efficiency, not just absolute rate (VERDICT r03 next #3).
def chip_peak_flops() -> float:
    """Peak bf16 FLOP/s of device 0, or 0.0 when unknown (CPU).
    Thin wrapper over the obs-layer table (obs/spans.py peak_flops)
    so bench and the per-step MFU gauge can never disagree; the
    GLLM_TPU_PEAK_TFLOPS override lives there too."""
    from gllm_tpu.obs.spans import peak_flops
    import jax
    return peak_flops(jax.devices()[0].device_kind)


def model_flops(mc, prompts, params, prefill_chunk: int) -> float:
    """Total forward matmul FLOPs for the workload on the dense
    Llama-family bench model.

    Per processed token: 2·(weight params on the matmul path); embedding
    gather excluded. The lm_head projection runs once per engine step per
    sequence (the runner gathers last-token rows before the vocab GEMM,
    models/dense.py compute_logits), i.e. ~once per output token plus once
    per prefill chunk — NOT once per prompt token. Attention is
    token-weighted causally — a prefill token at position i attends i keys
    (Σ over the prompt = p²/2), a decode token at output position j attends
    p+j keys (Σ = o·p + o²/2) — at 2·2·ctx·Hq·D FLOPs per token (QKᵀ+PV).
    """
    import math
    qkv = mc.hidden_size * (mc.num_heads + 2 * mc.num_kv_heads) * mc.head_dim
    o_proj = mc.num_heads * mc.head_dim * mc.hidden_size
    mlp = 3 * mc.hidden_size * mc.intermediate_size
    body_tok = 2 * mc.num_layers * (qkv + o_proj + mlp)
    lm_head = 2 * mc.vocab_size * mc.hidden_size
    n_tok = sum(len(p) + s.max_tokens for p, s in zip(prompts, params))
    n_head_rows = sum(s.max_tokens + math.ceil(len(p) / prefill_chunk)
                      for p, s in zip(prompts, params))
    ctx_sum = sum(len(p) ** 2 / 2
                  + s.max_tokens * len(p) + s.max_tokens ** 2 / 2
                  for p, s in zip(prompts, params))
    attn = mc.num_layers * 4 * mc.num_heads * mc.head_dim * ctx_sum
    return n_tok * body_tok + n_head_rows * lm_head + attn


def flagship_model_cfg():
    """Llama-3.2-1B shape (BASELINE config 1), dummy weights — shared by
    every on-chip ladder rung so all rungs benchmark the same model."""
    from gllm_tpu.models.config import ModelConfig
    return ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=128256,
        hidden_size=2048, num_layers=16, num_heads=32, num_kv_heads=8,
        head_dim=64, intermediate_size=8192, max_position=4096,
        rope_theta=500000.0, tie_word_embeddings=True)


def phase(name):
    print(PHASE_TAG + name, flush=True)


def kv_bytes_per_step(kv_read: float, summary: dict):
    """Effective KV bytes streamed per engine step over a measured
    window: the runner's gllm_kv_bytes_read_total delta divided by the
    window's step count (fused blocks count their sub-steps — each
    sub-step re-reads the context). This is the decode bandwidth-floor
    numerator the int8 cache halves; per-device estimate."""
    steps = sum(r["steps"] for k, r in summary.get("by_kind", {}).items()
                if k != "fused_block")
    steps += summary.get("decode_substeps_fused", 0)
    return round(kv_read / steps) if steps else None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="CPU smoke test (small model/workload)")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--profile", choices=PROFILES, default="full",
                    help="serving-loop feature level (degrade ladder)")
    ap.add_argument("--attn", choices=("auto", "pallas", "xla"),
                    default="auto",
                    help="attention_impl override (A/B the decode paths "
                         "on chip without editing profiles)")
    ap.add_argument("--inner", action="store_true",
                    help="(internal) run the measurement directly; without"
                         " this flag a supervisor child-process wrapper"
                         " with tunnel probe + deadline + degrade ladder"
                         " is used")
    args = ap.parse_args()

    if not args.inner:
        # forward argv minus --inner and any user --profile: the degrade
        # ladder owns the child's profile flag (last-wins in argparse)
        argv, skip = [], False
        for a in sys.argv[1:]:
            if skip:
                skip = False
                continue
            if a == "--inner" or a.startswith("--profile="):
                continue
            if a == "--profile":
                skip = True
                continue
            argv.append(a)
        sys.exit(supervise(args, argv))

    # Stall forensics: dump all thread stacks to stderr every 5 minutes so
    # a wedged run (tunnel stall, compile hang, deadlock) leaves evidence.
    import faulthandler
    faulthandler.dump_traceback_later(300, repeat=True, file=sys.stderr)

    if args.tiny:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        # CPU has no spec-sheet peak, which would null every MFU field
        # and leave the attribution smoke blind — assume a declared
        # 1 TFLOP/s nominal peak so the --tiny MFU numbers exercise the
        # full plumbing (they are relative to this declared peak, not a
        # real chip; the on-chip rungs use the real table).
        os.environ.setdefault("GLLM_TPU_PEAK_TFLOPS", "1")

    phase("import_jax")
    import numpy as np
    import jax
    if args.tiny:
        jax.config.update("jax_platforms", "cpu")
    from gllm_tpu.utils import enable_compilation_cache
    enable_compilation_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)

    from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.models.config import ModelConfig

    full = args.profile == "full"
    minimal = args.profile == "minimal"
    # KV-cache dtype A/B lever (same discipline as GLLM_BENCH_SLOTS):
    # GLLM_BENCH_KV_DTYPE=int8 stores quantized KV with in-kernel dequant
    # on every rung; the default arm stays byte-identical legacy.
    kv_dtype = os.environ.get("GLLM_BENCH_KV_DTYPE", "auto") or "auto"
    # Tiered-prefix-store lever (GLLM_BENCH_PREFIX=1): configure prefix
    # caching + host pool + disk tier and run a repeated-system-prompt
    # pass reporting per-tier hit rate and TTFT with/without the disk
    # tier (docs/kv_offload.md). Off by default: the headline engine
    # stays byte-identical (random ShareGPT prompts share no prefixes,
    # but the A/B discipline is the same as the other levers).
    prefix_bench = os.environ.get("GLLM_BENCH_PREFIX", "0") not in ("", "0")
    if args.tiny:
        model_cfg = ModelConfig(
            architecture="LlamaForCausalLM", vocab_size=2048,
            hidden_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=32, intermediate_size=256, max_position=512)
        # same A/B levers as the on-chip full profile: GLLM_BENCH_SLOTS=0
        # reverts to legacy chain membership, GLLM_BENCH_ODF=0 to
        # host-side finish detection, GLLM_BENCH_PIPELINED=0 to the
        # drain-on-break engine loop, on the CPU pass
        slots = os.environ.get("GLLM_BENCH_SLOTS", "1") not in ("", "0")
        odf = os.environ.get("GLLM_BENCH_ODF", "1") not in ("", "0")
        pipelined = os.environ.get("GLLM_BENCH_PIPELINED",
                                   "1") not in ("", "0")
        # Unified-step A/B (GLLM_BENCH_UNIFIED=0 reverts to the split
        # prefill/decode dispatch + per-kind shape families; the
        # unfused_frac / mixed_step_frac / warmed_buckets fields below
        # are the comparison axes)
        unified = os.environ.get("GLLM_BENCH_UNIFIED",
                                 "1") not in ("", "0")
        # Fused-speculation A/B (GLLM_BENCH_SPEC_FUSED=0 reverts to the
        # no-speculation engine; greedy streams are byte-identical
        # either way, so the workload tokens match across arms — the
        # spec_accept_rate / tokens_per_dispatch fields below are the
        # comparison axes)
        spec_fused = os.environ.get("GLLM_BENCH_SPEC_FUSED",
                                    "1") not in ("", "0")
        engine_cfg = EngineConfig(
            load_format="dummy", dtype="float32", max_model_len=512,
            max_num_seqs=32,
            overlap_scheduling=full, multi_step_decode=8 if full else 1,
            pipelined_loop=full and pipelined,
            unified_step=full and unified,
            spec_decode="ngram" if full and spec_fused else None,
            spec_fused=full and spec_fused,
            ondevice_finish=full and odf,
            decode_slot_batching=full and slots,
            chain_under_prefill=(8 if full and slots and not unified
                                 else 0),
            scheduler=SchedulerConfig(max_prefill_tokens=128,
                                      max_decode_seqs=16),
            cache=CacheConfig(page_size=4, num_pages=512,
                              kv_cache_dtype=kv_dtype))
        n_requests = args.requests or 8
    elif minimal:
        # Same Llama-3.2-1B model, smallest serviceable bucket surface:
        # decode buckets {8..64}, page buckets {4..64}, one 512-token
        # prefill chunk bucket — roughly half the conservative profile's
        # compile count, for a first number on a fresh tunnel. NOTE: the
        # shorter-context workload is NOT comparable to the conservative/
        # full rungs; the supervisor only reports it when no comparable
        # rung produced a number, and tags the JSON.
        model_cfg = flagship_model_cfg()
        engine_cfg = EngineConfig(
            load_format="dummy", dtype="bfloat16", max_model_len=1024,
            max_num_seqs=64, overlap_scheduling=False, multi_step_decode=1,
            scheduler=SchedulerConfig(max_prefill_tokens=512,
                                      max_decode_seqs=64),
            cache=CacheConfig(page_size=16, num_pages=4096,
                              kv_cache_dtype=kv_dtype))
        n_requests = args.requests or 64
    else:
        model_cfg = flagship_model_cfg()
        # experiment overrides for on-chip A/B tuning of the full profile
        # (committed defaults are the measured winners)
        msd = int(os.environ.get("GLLM_BENCH_MSD", "32"))
        depth = int(os.environ.get("GLLM_BENCH_DEPTH", "4"))
        chunk = int(os.environ.get("GLLM_BENCH_PREFILL", "2048"))
        # persistent-slot decode chains + on-device finish (A/B levers:
        # GLLM_BENCH_SLOTS=0 reverts the full profile to legacy chain
        # membership, GLLM_BENCH_ODF=0 to host-side finish detection)
        slots = os.environ.get("GLLM_BENCH_SLOTS", "1") not in ("", "0")
        odf = os.environ.get("GLLM_BENCH_ODF", "1") not in ("", "0")
        # Pipelined-loop A/B (GLLM_BENCH_PIPELINED=0 reverts the full
        # profile to the drain-on-break loop; the bubble_frac /
        # mean_inflight_depth fields below are the comparison axes)
        pipelined = os.environ.get("GLLM_BENCH_PIPELINED",
                                   "1") not in ("", "0")
        # Unified-step A/B lever, same discipline as the tiny profile
        unified = os.environ.get("GLLM_BENCH_UNIFIED",
                                 "1") not in ("", "0")
        # Fused-speculation A/B lever (GLLM_BENCH_SPEC_FUSED=0): the
        # ShareGPT-shaped random workload is draft-hostile, so the
        # headline mostly measures that the drafting machinery never
        # slows the chain down; the draft-friendly win shows in the
        # --tiny in-process A/B below.
        spec_fused = os.environ.get("GLLM_BENCH_SPEC_FUSED",
                                    "1") not in ("", "0")
        cup = int(os.environ.get("GLLM_BENCH_CUP", str(msd)))
        engine_cfg = EngineConfig(
            load_format="dummy", dtype="bfloat16", max_model_len=2048,
            # conservative halves the decode width: fewer/smaller decode
            # buckets to compile, so the first (budget-bounded) attempt
            # spends its time measuring, not compiling
            max_num_seqs=256 if full else 128,
            overlap_scheduling=full,
            pipelined_loop=full and pipelined,
            unified_step=full and unified,
            spec_decode="ngram" if full and spec_fused else None,
            spec_fused=full and spec_fused,
            overlap_depth=depth if full else 1,
            multi_step_decode=msd if full else 1,
            ondevice_finish=full and odf,
            decode_slot_batching=full and slots,
            # gated on slots too: the GLLM_BENCH_SLOTS=0 arm must be the
            # byte-identical legacy baseline, not legacy-with-ramp-policy
            # (and the unified step retires the ramp policy entirely)
            chain_under_prefill=(cup if full and slots and not unified
                                 else 0),
            scheduler=SchedulerConfig(max_prefill_tokens=chunk,
                                      max_decode_seqs=256 if full
                                      else 128),
            # explicit pool (4 GB KV bf16; int8 halves the bytes at the
            # same page count): the axon-attached chip advertises no
            # memory_stats and over-allocating hangs device init
            cache=CacheConfig(page_size=16, num_pages=8192,
                              kv_cache_dtype=kv_dtype))
        n_requests = args.requests or 160

    if prefix_bench:
        import tempfile
        c = engine_cfg.cache
        c.enable_prefix_caching = True
        if not c.kv_host_pool_pages and c.kv_host_pool_gb <= 0:
            c.kv_host_pool_pages = 256 if args.tiny else 2048
        c.kv_disk_path = tempfile.mkdtemp(prefix="gllm_bench_kvdisk_")
        c.kv_disk_gb = 2.0

    # Tracing A/B lever (ISSUE 10 acceptance gate: default-on tracing
    # must cost <2% --tiny throughput and keep token streams
    # byte-identical): GLLM_BENCH_TRACING=0 runs the flag-off arm.
    engine_cfg.tracing = (os.environ.get("GLLM_BENCH_TRACING", "1")
                          not in ("", "0"))

    # pp topology lever (ISSUE 20, GLLM_BENCH_PP=2): run the measured
    # pass over a pp-stage pipeline — the fast-path flags (pipelined +
    # unified) now ride per-stage dispatch. Fused speculation and the
    # slot/fused-block machinery are single-program features the config
    # rejects / the engine ignores under pp, so the pp arm switches them
    # off EXPLICITLY here (the bench choosing its config, loudly — never
    # the engine dropping a flag).
    bench_pp = int(os.environ.get("GLLM_BENCH_PP", "1") or "1")
    if bench_pp > 1:
        engine_cfg.parallel.pp = bench_pp
        engine_cfg.spec_fused = False
        engine_cfg.spec_decode = None
        engine_cfg.multi_step_decode = 1
        engine_cfg.decode_slot_batching = False
        engine_cfg.ondevice_finish = False
        engine_cfg.chain_under_prefill = 0
        log(f"[bench] GLLM_BENCH_PP={bench_pp}: pp pipeline arm "
            f"(spec_fused / fused-block / slot levers off — "
            f"single-program features)")

    phase("backend_init")
    log(f"backend={jax.default_backend()} devices={jax.devices()} "
        f"profile={args.profile}")
    if args.attn != "auto":
        engine_cfg.attention_impl = args.attn
    phase("engine_build")
    t0 = time.monotonic()
    llm = LLM(config=engine_cfg, model_cfg=model_cfg)
    log(f"engine up in {time.monotonic() - t0:.1f}s "
        f"({llm.runner.num_pages} KV pages)")

    rng = np.random.default_rng(args.seed)
    prompts, params = build_workload(rng, n_requests,
                                     engine_cfg.max_model_len,
                                     tiny=args.tiny)
    total_out = sum(p.max_tokens for p in params)
    total_in = sum(len(p) for p in prompts)
    log(f"workload: {n_requests} reqs, {total_in} prompt tokens, "
        f"{total_out} output tokens")

    # Warmup pass: same workload → compiles every bucket the measured pass
    # will hit (the reference warms its CUDA graphs the same way).
    phase("warmup_pass")
    t0 = time.monotonic()
    llm.generate(prompt_token_ids=prompts, sampling_params=params)
    log(f"warmup pass: {time.monotonic() - t0:.1f}s")

    # Bracket the measured pass in the obs layer: steptrace mark +
    # request-histogram snapshots so the summary excludes warmup.
    from gllm_tpu.obs import metrics as obs_metrics
    from gllm_tpu.obs.steptrace import TRACE, summarize
    trace_mark = TRACE.mark()
    hist_names = ("gllm_request_ttft_seconds", "gllm_request_itl_seconds",
                  "gllm_request_e2e_seconds", "gllm_request_tpot_seconds")
    hist_before = {n: obs_metrics.REGISTRY.get(n).snapshot()
                   for n in hist_names}
    kv_read_metric = obs_metrics.REGISTRY.get("gllm_kv_bytes_read_total")
    kv_read0 = kv_read_metric.get() if kv_read_metric else 0.0

    phase("measured_pass")
    t0 = time.monotonic()
    outs = llm.generate(prompt_token_ids=prompts, sampling_params=params)
    dt = time.monotonic() - t0

    # Salvageable headline the moment it exists (the supervisor's
    # salvage_result pattern): the sampled pass / report / teardown can
    # still wedge or crash without losing the measured number.
    out_tokens = sum(o.num_output_tokens for o in outs)
    assert out_tokens == total_out, (out_tokens, total_out)
    value = out_tokens / dt
    print(f"RESULT {value:.3f}", flush=True)

    # Machine-readable measured-pass attribution (step-kind wall time,
    # fused/unfused decode split, compile events, request latency
    # percentiles) — the "18/59 unfused steps" class of finding reads
    # straight out of BENCH_r*.json now instead of log archaeology.
    events = TRACE.events(since=trace_mark)
    step_summary = summarize(events)
    # Unified-step acceptance (ISSUE 12): with the flag on, prefill
    # arrivals are absorbed into mixed re-formed batches — the 'waiting'
    # break class is retired and MUST stay at zero, on every profile
    # (the flag is inert for hybrid models, where legacy yields remain).
    if engine_cfg.unified_step and not model_cfg.use_hybrid:
        waiting = (step_summary.get("chain_breaks_by_reason")
                   or {}).get("waiting", 0)
        assert not waiting, (
            f"--unified-step run recorded {waiting} chain_breaks with "
            f"reason='waiting' — the retired break class fired")
    # Salvageable attribution right behind RESULT (ISSUE 10): a run the
    # supervisor kills in the sampled pass / report / teardown keeps its
    # WHY, not just its number — the supervisor merges this line into
    # the salvaged JSON.
    # NOTE window_mfu (the steptrace-window estimator) is deliberately
    # NOT named "mfu": the result JSON's mfu is the workload-level
    # model_flops/dt/peak, and a salvage merge must never swap one
    # definition for the other under the same key mid-trajectory.
    print("ATTRIBUTION " + json.dumps({
        "host_ms_by_phase": step_summary.get("host_ms_by_phase"),
        "device_ms_by_kind": step_summary.get("device_ms_by_kind"),
        "overlap_efficiency": step_summary.get("overlap_efficiency"),
        "bubble_frac": step_summary.get("bubble_frac"),
        "window_mfu": step_summary.get("mfu"),
        # pipelined loop (ISSUE 11): the sustained run-ahead depth and
        # why the loop failed to run further ahead — a salvaged run
        # keeps the bubble story, not just the bubble number
        "mean_inflight_depth": step_summary.get("mean_inflight_depth"),
        "loop_stalls": step_summary.get("loop_stalls_by_reason"),
        "pipelined_loop": bool(engine_cfg.pipelined_loop),
        # unified step (ISSUE 12): the dispatch-shape story — share of
        # steps that were mixed unified batches and the shape-bucket
        # population the runner compiled/warmed over the whole run
        "unified_step": bool(engine_cfg.unified_step),
        "mixed_step_frac": step_summary.get("mixed_step_frac"),
        "warmed_buckets": getattr(llm.runner, "num_shape_signatures",
                                  None),
        # fused speculation (ISSUE 13): the dispatch-amortization story
        "spec_fused": bool(engine_cfg.spec_fused),
        "spec_accept_rate": step_summary.get("spec_accept_rate"),
        "tokens_per_dispatch": step_summary.get("tokens_per_dispatch"),
    }), flush=True)


    # On-demand Chrome trace artifact of the measured pass
    # (GLLM_BENCH_TRACE=1): engine-phase tracks + per-request span
    # tracks, loadable in Perfetto (docs/observability.md#tracing).
    trace_path = None
    if os.environ.get("GLLM_BENCH_TRACE", "0") not in ("", "0"):
        from gllm_tpu.obs.spans import chrome_trace
        trace_path = os.path.abspath(f"bench_trace_{args.profile}.json")
        with open(trace_path, "w") as f:
            json.dump(chrome_trace(events, llm.spans.spans(),
                                   span_t0=TRACE.t0), f)
        log(f"[bench] chrome trace written to {trace_path}")
    kv_read = (kv_read_metric.get() - kv_read0) if kv_read_metric else 0.0
    # no silent caps: the ring holds GLLM_OBS_TRACE_CAP events — report
    # how many measured-pass iterations rolled off before the dump
    lost = max(0, TRACE.mark() - TRACE.capacity - trace_mark)
    if lost:
        step_summary["trace_dropped"] = lost
        log(f"[bench] steptrace ring dropped {lost} measured-pass "
            f"events (raise GLLM_OBS_TRACE_CAP for full attribution)")
    lat = {}
    for name in hist_names:
        h = obs_metrics.REGISTRY.get(name)
        short = name[len("gllm_request_"):-len("_seconds")]
        pcts = {q: obs_metrics.percentile(h, q / 100.0,
                                          before=hist_before[name])
                for q in (50, 90, 99)}
        if any(v is not None for v in pcts.values()):
            lat[short] = {f"p{q}": (round(v, 4) if v is not None else None)
                          for q, v in pcts.items()}
    metrics_snapshot = {"steps": step_summary, "request_latency_s": lat}

    # Tiny-mode pipelined A/B control (ISSUE 11): re-run the same
    # measured workload on a flag-off engine in the same process so the
    # result JSON carries the bubble_frac DELTA directly — the on-chip
    # rungs A/B across runs via GLLM_BENCH_PIPELINED instead (engine
    # build + recompiles are too expensive to double there). Runs AFTER
    # the headline window's metric deltas (kv_read, latency histograms)
    # were snapshotted so the control never pollutes them.
    bubble_delta = None
    if args.tiny and engine_cfg.pipelined_loop:
        phase("pipelined_control_pass")
        import dataclasses as _dc
        ctrl_cfg = _dc.replace(engine_cfg, pipelined_loop=False)
        ctrl = LLM(config=ctrl_cfg, model_cfg=model_cfg)
        ctrl.generate(prompt_token_ids=prompts,
                      sampling_params=params)          # warm the buckets
        c_mark = TRACE.mark()
        ctrl.generate(prompt_token_ids=prompts, sampling_params=params)
        c_summary = summarize(TRACE.events(since=c_mark))
        b_on = step_summary.get("bubble_frac")
        b_off = c_summary.get("bubble_frac")
        if b_on is not None and b_off is not None:
            bubble_delta = {"bubble_frac_sync": b_off,
                            "bubble_frac_delta": round(b_on - b_off, 4)}
            log(f"pipelined A/B: bubble_frac {b_off} (sync) -> {b_on} "
                f"(pipelined)")
            # re-print the salvageable ATTRIBUTION line carrying BOTH
            # arms (salvage takes the most recent line; if the run dies
            # during the control, the first line already landed)
            print("ATTRIBUTION " + json.dumps({
                "host_ms_by_phase": step_summary.get("host_ms_by_phase"),
                "device_ms_by_kind":
                    step_summary.get("device_ms_by_kind"),
                "overlap_efficiency":
                    step_summary.get("overlap_efficiency"),
                "bubble_frac": b_on,
                "window_mfu": step_summary.get("mfu"),
                "mean_inflight_depth":
                    step_summary.get("mean_inflight_depth"),
                "loop_stalls": step_summary.get("loop_stalls_by_reason"),
                "pipelined_loop": True,
                **bubble_delta,
            }), flush=True)

    # Tiny-mode pp A/B (ISSUE 20, GLLM_BENCH_PP=2): the same measured
    # workload on a LEGACY pp engine (sync drain-per-pass loop: no
    # overlap, no pipelined re-forms, split dispatch families) in the
    # same process — the pipelined+unified pp arm must hold bubble_frac
    # no worse than the legacy pp pipeline (the no-inter-stage-bubble
    # claim, measured, not asserted from structure).
    pp_ab = None
    if args.tiny and bench_pp > 1 and engine_cfg.pipelined_loop:
        phase("pp_ab_pass")
        import dataclasses as _dc
        leg_cfg = _dc.replace(engine_cfg, overlap_scheduling=False,
                              pipelined_loop=False, unified_step=False)
        leg = LLM(config=leg_cfg, model_cfg=model_cfg)
        leg.generate(prompt_token_ids=prompts,
                     sampling_params=params)           # warm the buckets
        l_mark = TRACE.mark()
        leg.generate(prompt_token_ids=prompts, sampling_params=params)
        l_summary = summarize(TRACE.events(since=l_mark))
        b_on = step_summary.get("bubble_frac")
        b_off = l_summary.get("bubble_frac")
        pp_ab = {"pp": bench_pp, "bubble_frac": b_on,
                 "bubble_frac_legacy": b_off}
        log(f"pp A/B: bubble_frac {b_off} (legacy pp) -> {b_on} "
            f"(pipelined+unified pp)")
        if b_on is not None and b_off is not None:
            assert b_on <= b_off + 0.05, (
                f"pp fast path worsened bubble_frac vs legacy pp: "
                f"{b_on} vs {b_off}")

    # Tiny-mode unified-step A/B (ISSUE 12): the headline pass submits
    # every request up front, so the prefill/decode phase split barely
    # fires — run a STAGGERED-ARRIVAL churn micro-pass on two fresh
    # engines (flag on / flag off, same workload) and report the
    # dispatch-shape story directly: distinct warmed shape-bucket
    # signatures and the unfused decode share, both of which the
    # unified step must hold strictly lower. On-chip rungs A/B across
    # runs via GLLM_BENCH_UNIFIED instead.
    unified_ab = None
    if args.tiny and engine_cfg.unified_step:
        phase("unified_ab_pass")
        import dataclasses as _dc
        from gllm_tpu.sampling_params import SamplingParams

        def churn_arm(unified_on):
            cfg = _dc.replace(
                engine_cfg, unified_step=unified_on,
                # the flag-off arm runs the legacy ramp policy the
                # unified step retires — but only in the slots
                # configuration the headline gates it on (the SLOTS=0
                # arm must stay byte-identical legacy, not
                # legacy-with-ramp-policy)
                chain_under_prefill=(
                    0 if unified_on
                    else 8 if engine_cfg.decode_slot_batching else 0))
            arm = LLM(config=cfg, model_cfg=model_cfg)
            arng = np.random.default_rng(7)
            arrivals = {0: 4, 3: 3, 7: 3, 12: 2, 18: 2, 25: 2}
            mark, nseq, it = TRACE.mark(), 0, 0
            while nseq < 14 or arm.has_unfinished:
                for _ in range(arrivals.get(it, 0)):
                    if nseq >= 14:
                        break
                    ids = arng.integers(
                        1, model_cfg.vocab_size - 1,
                        size=int(arng.integers(8, 64))).tolist()
                    s = arm._allocate_seq(
                        ids, SamplingParams(
                            temperature=0.0, ignore_eos=True,
                            max_tokens=int(arng.integers(16, 48))))
                    arm.add_seq(s)
                    nseq += 1
                arm.step()
                it += 1
                assert it < 4000, "unified A/B churn arm wedged"
            summ = summarize(TRACE.events(since=mark))
            return {"warmed_buckets": arm.runner.num_shape_signatures,
                    "unfused_frac": summ.get("unfused_frac"),
                    "mixed_step_frac": summ.get("mixed_step_frac"),
                    "chain_breaks": summ.get("chain_breaks_by_reason")}

        on, off = churn_arm(True), churn_arm(False)
        assert not (on["chain_breaks"] or {}).get("waiting"), (
            "unified churn arm recorded retired 'waiting' breaks")
        unified_ab = {
            "warmed_buckets": on["warmed_buckets"],
            "warmed_buckets_split": off["warmed_buckets"],
            "unfused_frac": on["unfused_frac"],
            "unfused_frac_split": off["unfused_frac"],
            "mixed_step_frac": on["mixed_step_frac"],
        }
        log(f"unified A/B (churn): warmed_buckets "
            f"{off['warmed_buckets']} (split) -> {on['warmed_buckets']} "
            f"(unified); unfused_frac {off['unfused_frac']} -> "
            f"{on['unfused_frac']}")

    # Tiny-mode fused-speculation A/B (ISSUE 13): the headline random
    # workload is draft-hostile, so the dispatch-amortization win needs
    # a DRAFT-FRIENDLY (repetitive) micro-pass — two fresh engines run
    # the same workload (greedy byte-identity guarantees equal token
    # output) and the fused arm must take STRICTLY fewer device
    # dispatches. On-chip rungs A/B across runs via
    # GLLM_BENCH_SPEC_FUSED instead.
    spec_fused_ab = None
    if args.tiny and engine_cfg.spec_fused:
        phase("spec_fused_ab_pass")
        import dataclasses as _dc
        from gllm_tpu.sampling_params import SamplingParams

        # dedicated SMALL-VOCAB model for the A/B arms: greedy decode of
        # a random-weight model enters short cycles quickly at vocab 32
        # (measured periods 1-3) — the draft-friendly regime where
        # prompt-lookup actually accepts; the headline model's vocab
        # (2048) random-walks for hundreds of tokens and never drafts
        ab_model = ModelConfig(
            architecture="LlamaForCausalLM", vocab_size=32,
            hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=16, intermediate_size=128, max_position=512)

        def spec_arm(fused_on):
            cfg = _dc.replace(
                engine_cfg, spec_fused=fused_on,
                spec_decode="ngram" if fused_on else None)
            arm = LLM(config=cfg, model_cfg=ab_model)
            arng = np.random.default_rng(13)
            # repetitive prompts seed the n-gram window immediately
            s_prompts = [(arng.integers(
                1, ab_model.vocab_size - 1, size=4).tolist() * 8)[:24]
                for _ in range(6)]
            s_params = [SamplingParams(temperature=0.0, max_tokens=48,
                                       ignore_eos=True)
                        for _ in s_prompts]
            arm.generate(prompt_token_ids=s_prompts,
                         sampling_params=s_params)   # warm the buckets
            mark = TRACE.mark()
            d0 = arm.runner.num_dispatches
            outs = arm.generate(prompt_token_ids=s_prompts,
                                sampling_params=s_params)
            summ = summarize(TRACE.events(since=mark))
            toks = sum(o.num_output_tokens for o in outs)
            return {"dispatches": arm.runner.num_dispatches - d0,
                    "tokens": toks,
                    "out_ids": [o.output_token_ids for o in outs],
                    "spec_accept_rate": summ.get("spec_accept_rate"),
                    "tokens_per_dispatch":
                        summ.get("tokens_per_dispatch")}

        on, off = spec_arm(True), spec_arm(False)
        assert on["out_ids"] == off["out_ids"], (
            "fused speculation changed greedy token content")
        assert on["tokens"] == off["tokens"]
        assert on["dispatches"] < off["dispatches"], (
            "fused speculation must strictly reduce dispatches at equal "
            f"token output ({on['dispatches']} vs {off['dispatches']})")
        spec_fused_ab = {
            "dispatches": on["dispatches"],
            "dispatches_off": off["dispatches"],
            "tokens": on["tokens"],
            "spec_accept_rate": on["spec_accept_rate"],
            "tokens_per_dispatch": on["tokens_per_dispatch"],
            "tokens_per_dispatch_off": off["tokens_per_dispatch"],
        }
        log(f"spec_fused A/B (draft-friendly): dispatches "
            f"{off['dispatches']} -> {on['dispatches']} at "
            f"{on['tokens']} tokens; accept_rate "
            f"{on['spec_accept_rate']}")

    # Sampled-path pass (VERDICT r05: the sampled sampler program never
    # appeared in BENCH JSON, so its ~88 ms full-vocab sort regression was
    # invisible for two rounds): a smaller measured pass with temperature
    # > 0 / top_p < 1 so the sampled program variant gets a number of its
    # own. GLLM_BENCH_SAMPLED=0 skips it (budget-constrained reruns).
    sampled_result = None
    if os.environ.get("GLLM_BENCH_SAMPLED", "1") not in ("", "0"):
        from gllm_tpu.sampling_params import SamplingParams
        n_sampled = min(n_requests, 64)
        s_prompts = prompts[:n_sampled]
        s_params = [SamplingParams(temperature=0.8, top_p=0.95, top_k=64,
                                   max_tokens=p.max_tokens,
                                   ignore_eos=True)
                    for p in params[:n_sampled]]
        phase("sampled_warmup")
        llm.generate(prompt_token_ids=s_prompts, sampling_params=s_params)
        phase("sampled_pass")
        s_mark = TRACE.mark()
        s_kv0 = kv_read_metric.get() if kv_read_metric else 0.0
        t0 = time.monotonic()
        s_outs = llm.generate(prompt_token_ids=s_prompts,
                              sampling_params=s_params)
        s_dt = time.monotonic() - t0
        s_tokens = sum(o.num_output_tokens for o in s_outs)
        s_summary = summarize(TRACE.events(since=s_mark))
        s_kv = (kv_read_metric.get() - s_kv0) if kv_read_metric else 0.0
        s_flops = model_flops(model_cfg, s_prompts, s_params,
                              engine_cfg.scheduler.max_prefill_tokens)
        s_peak = chip_peak_flops()
        sampled_result = {
            "output_tok_s": round(s_tokens / s_dt, 2),
            "wall_s": round(s_dt, 2),
            "requests": n_sampled,
            # rung-comparable efficiency fields (same definitions as the
            # greedy headline): MFU + effective KV bytes per step
            "mfu": (round(s_flops / s_dt / s_peak, 4) if s_peak
                    else None),
            "kv_bytes_per_step": kv_bytes_per_step(s_kv, s_summary),
            "steps": s_summary,
        }
        log(f"sampled pass: {s_dt:.2f}s → {s_tokens / s_dt:.1f} "
            f"output tok/s ({n_sampled} reqs, temp=0.8 top_p=0.95)")

    # Repeated-system-prompt pass (ISSUE 9): the workload real multi-user
    # traffic is made of — N requests sharing one long system prefix with
    # unique tails. Three arms probe the tier stack: "populate" (cold
    # store; requests 2..N hit HBM), "disk" (HBM + host demoted to the
    # disk tier first, so every prefix page restores from disk), and
    # "no_tier" (tiers detached, full recompute — the without-disk
    # control). Hit rate + TTFT p50 per arm land first-class in the
    # result JSON.
    prefix_result = None
    if prefix_bench and getattr(llm, "prefix_tiers", None) is not None:
        from gllm_tpu.sampling_params import SamplingParams
        phase("prefix_pass")
        sys_len = 64 if args.tiny else 512
        n_pref = min(n_requests, 8 if args.tiny else 32)
        shared = rng.integers(1, 30000, size=sys_len).tolist()
        ttft_h = obs_metrics.REGISTRY.get("gllm_request_ttft_seconds")
        q_m = obs_metrics.REGISTRY.get(
            "gllm_prefix_cache_query_tokens_total")
        h_m = obs_metrics.REGISTRY.get(
            "gllm_prefix_cache_hit_tokens_total")
        disk_hits = obs_metrics.REGISTRY.get("gllm_kvstore_hits_total")

        def prefix_arm():
            before, q0, h0 = ttft_h.snapshot(), q_m.get(), h_m.get()
            p_prompts = [shared + rng.integers(
                1, 30000, size=16).tolist() for _ in range(n_pref)]
            p_params = [SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True)
                        for _ in range(n_pref)]
            t0 = time.monotonic()
            llm.generate(prompt_token_ids=p_prompts,
                         sampling_params=p_params)
            p50 = obs_metrics.percentile(ttft_h, 0.5, before=before)
            dq, dh = q_m.get() - q0, h_m.get() - h0
            return {"hit_rate": round(dh / dq, 4) if dq else 0.0,
                    "ttft_p50_s": (round(p50, 4) if p50 is not None
                                   else None),
                    "wall_s": round(time.monotonic() - t0, 2)}

        arms = {"populate": prefix_arm()}
        moved = llm.demote_prefix_cache()
        d0 = disk_hits.get(tier="disk")
        arms["disk"] = prefix_arm()
        disk_pages = disk_hits.get(tier="disk") - d0
        # control: detach the tiers AND the eviction demotion hook, and
        # forget every upper level (HBM maps + host-pool entries — the
        # disk arm re-staged pages there), so the same workload
        # recomputes every prefix token with true-legacy eviction costs
        pool = llm.swap_manager.pool
        llm.swap_manager.tiers, pool.on_evict = None, None
        mm = llm.memory_manager
        mm.hash_to_page.clear(); mm.page_meta.clear()
        mm._seq_chain.clear()
        for p in list(pool.page_meta):
            pool.drop_prefix(p)
        arms["no_tier"] = prefix_arm()
        llm.swap_manager.tiers = llm.prefix_tiers
        pool.on_evict = llm.prefix_tiers._on_host_evict
        prefix_result = {"system_prompt_tokens": sys_len,
                         "requests": n_pref,
                         "pages_demoted": moved,
                         "disk_hit_pages": int(disk_pages), **arms}
        log(f"prefix pass: hit_rate populate={arms['populate']['hit_rate']}"
            f" disk={arms['disk']['hit_rate']} "
            f"no_tier={arms['no_tier']['hit_rate']}; ttft_p50 "
            f"disk={arms['disk']['ttft_p50_s']} vs "
            f"no_tier={arms['no_tier']['ttft_p50_s']}")

    # Self-healing chaos lever (ISSUE 14, GLLM_BENCH_CHAOS=1): the
    # recovery acceptance run inside bench — a ServingEngine with
    # --engine-recovery serves the same greedy workload twice (a clean
    # arm, then an arm with an injected engine_hard_crash mid-pass), and
    # throughput degradation + recovery_s land FIRST-CLASS in the result
    # JSON. Greedy + ignore_eos makes every request replay-safe, so the
    # faulted arm must still emit every token (asserted) — the cost of
    # the crash shows up as wall clock, never as lost output.
    chaos_result = None
    if os.environ.get("GLLM_BENCH_CHAOS", "0") not in ("", "0"):
        phase("chaos_pass")
        import dataclasses as _dc
        import threading as _th
        from gllm_tpu.engine.serving_engine import ServingEngine
        from gllm_tpu.faults import FAULTS
        from gllm_tpu.sampling_params import SamplingParams
        ch_cfg = _dc.replace(engine_cfg, engine_recovery=True,
                             max_step_failures=1,
                             rebuild_backoff_s=0.05,
                             rebuild_backoff_max_s=1.0)
        n_chaos = min(n_requests, 8 if args.tiny else 32)
        ch_prompts = [list(p) for p in prompts[:n_chaos]]
        ch_tokens = [min(p.max_tokens, 64) for p in params[:n_chaos]]

        def chaos_arm(fault_delay_s=None):
            llm_c = LLM(config=ch_cfg, model_cfg=model_cfg)
            eng = ServingEngine(llm_c)
            counts = [0] * n_chaos
            timer = None
            try:
                if fault_delay_s is not None:
                    # time-based so the crash lands MID-pass on every
                    # profile (a fused engine drains the workload in
                    # too few loop passes for pass-counting to work)
                    timer = _th.Timer(
                        fault_delay_s,
                        lambda: FAULTS.arm("engine_hard_crash:0:1"))
                    timer.daemon = True
                    timer.start()
                t0 = time.monotonic()
                handles = [eng.submit(p, SamplingParams(
                    temperature=0.0, max_tokens=mt, ignore_eos=True))
                    for p, mt in zip(ch_prompts, ch_tokens)]

                def drain(i, h):
                    for c in h:
                        if c.token_id is not None:
                            counts[i] += 1

                ts = [_th.Thread(target=drain, args=(i, h), daemon=True)
                      for i, h in enumerate(handles)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=600)
                    assert not t.is_alive(), "chaos-arm stream hung"
                dt_arm = time.monotonic() - t0
            finally:
                if timer is not None:
                    timer.cancel()
                FAULTS.reset()
                eng.shutdown()
            sup = eng.supervisor
            return {"tok": sum(counts), "dt": dt_arm,
                    "recoveries": sup.recoveries if sup else 0,
                    "recovery_s": (sup.last_recovery_s
                                   if sup else None)}

        clean = chaos_arm(None)
        # the crash lands ~40% into the measured window (sized off the
        # clean arm), mid-stream on every profile
        faulted = chaos_arm(max(0.02, 0.4 * clean["dt"]))
        assert faulted["tok"] == clean["tok"], (
            "recovery dropped tokens: the greedy replay-safe workload "
            f"must re-emit every token ({faulted['tok']} vs "
            f"{clean['tok']})")
        tps_clean = clean["tok"] / clean["dt"]
        tps_fault = faulted["tok"] / faulted["dt"]
        chaos_result = {
            "requests": n_chaos,
            "output_tok_s": round(tps_fault, 2),
            "output_tok_s_clean": round(tps_clean, 2),
            "degradation_frac": round(1.0 - tps_fault / tps_clean, 4),
            "recoveries": faulted["recoveries"],
            "recovery_s": (round(faulted["recovery_s"], 3)
                           if faulted["recovery_s"] is not None
                           else None),
        }
        log(f"chaos pass: {tps_clean:.1f} tok/s clean -> "
            f"{tps_fault:.1f} tok/s under an injected hard crash "
            f"({faulted['recoveries']} recoveries, recovery_s="
            f"{chaos_result['recovery_s']})")

    # Fleet failover lever (ISSUE 15, GLLM_BENCH_FLEET=1): two
    # in-process replicas — real HTTP api_servers — behind the front
    # router core; a clean pass, then a pass with a time-based mid-pass
    # REPLICA KILL (engine + server torn down). Greedy ignore_eos makes
    # every stream replay-safe, so every stream on the dead replica
    # must MIGRATE and the client-side token count must not drop:
    # lost_tokens is asserted 0 — the cost of losing a replica shows up
    # as wall clock and failover_s, never as lost output.
    fleet_result = None
    if os.environ.get("GLLM_BENCH_FLEET", "0") not in ("", "0"):
        phase("fleet_pass")
        import threading as _th
        from gllm_tpu.entrypoints.api_server import serve as _serve
        from gllm_tpu.router import FrontRouter
        from gllm_tpu.router import core as _rcore
        n_fleet = min(n_requests, 8 if args.tiny else 16)
        fl_prompts = [list(p) for p in prompts[:n_fleet]]
        fl_tokens = [min(p.max_tokens, 64) for p in params[:n_fleet]]

        class _Sink:
            # FrontRouter.stream's downstream surface, minus the HTTP
            # hop — the router core + replica HTTP path is the measured
            # object; one SSE event per token makes counting exact
            def __init__(self):
                self.started = False
                self.tokens = 0
                self.finish = None
                self.error = None

            def start(self):
                self.started = True

            def send(self, ev):
                if "choices" in ev:
                    # one SSE event per generated token; the finish
                    # reason rides the LAST token's chunk, so events
                    # count tokens exactly
                    self.tokens += 1
                    fin = ev["choices"][0].get("finish_reason")
                    if fin:
                        self.finish = fin
                        if fin in ("error", "abort"):
                            self.error = f"finish={fin}"
                elif "error" in ev:
                    self.error = ev["error"].get("message")

            def done(self):
                pass

            def fail_json(self, status, obj, headers):
                self.error = f"{status}: {obj}"

        def fleet_arm(kill_delay_s=None):
            reps = []
            for _ in range(2):
                llm_r = LLM(config=engine_cfg, model_cfg=model_cfg)
                httpd = _serve(llm_r, "127.0.0.1", 0)
                _th.Thread(target=httpd.serve_forever,
                           daemon=True).start()
                reps.append(httpd)
            fr = FrontRouter(
                [f"127.0.0.1:{h.server_address[1]}" for h in reps],
                probe_interval_s=0.1, breaker_base_s=0.5,
                breaker_jitter=0.0, stream_idle_timeout_s=300.0)
            fo_before = _rcore._M_FAILOVERS.get(outcome="ok")
            _, fs_sum0, fs_n0 = _rcore._M_FAILOVER_S.snapshot()
            sinks = [_Sink() for _ in range(n_fleet)]
            timer = None
            try:
                t0 = time.monotonic()
                if kill_delay_s is not None:
                    def kill():
                        reps[0].state.engine.shutdown()
                        reps[0].shutdown()
                        reps[0].server_close()
                    timer = _th.Timer(kill_delay_s, kill)
                    timer.daemon = True
                    timer.start()
                threads = [_th.Thread(
                    target=fr.stream,
                    args=("completion",
                          {"prompt": p, "max_tokens": mt,
                           "temperature": 0, "ignore_eos": True,
                           "stream": True}, s),
                    daemon=True)
                    for p, mt, s in zip(fl_prompts, fl_tokens, sinks)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=600)
                    assert not t.is_alive(), "fleet-arm stream hung"
                dt_arm = time.monotonic() - t0
            finally:
                if timer is not None:
                    timer.cancel()
                fr.close()
                for h in reps:
                    try:
                        h.shutdown()
                        h.state.engine.shutdown()
                    except Exception:
                        pass        # the killed replica is already down
            _, fs_sum1, fs_n1 = _rcore._M_FAILOVER_S.snapshot()
            migrated = _rcore._M_FAILOVERS.get(outcome="ok") - fo_before
            errors = [s.error for s in sinks if s.error]
            assert not errors, f"fleet-arm stream errors: {errors[:3]}"
            return {"tok": sum(s.tokens for s in sinks), "dt": dt_arm,
                    "migrated": int(migrated),
                    "failover_s": (round((fs_sum1 - fs_sum0)
                                         / (fs_n1 - fs_n0), 3)
                                   if fs_n1 > fs_n0 else None)}

        clean = fleet_arm(None)
        assert clean["tok"] == sum(fl_tokens), (
            "clean fleet arm dropped tokens", clean["tok"],
            sum(fl_tokens))
        faulted = fleet_arm(max(0.05, 0.4 * clean["dt"]))
        lost = clean["tok"] - faulted["tok"]
        assert lost == 0, (
            "replica kill lost tokens despite journal-backed failover "
            f"({faulted['tok']} vs {clean['tok']})")
        assert faulted["migrated"] > 0, \
            "the mid-pass kill migrated no stream"
        tps_clean = clean["tok"] / clean["dt"]
        tps_fault = faulted["tok"] / faulted["dt"]
        fleet_result = {
            "requests": n_fleet,
            "replicas": 2,
            "output_tok_s": round(tps_fault, 2),
            "output_tok_s_clean": round(tps_clean, 2),
            "degradation_frac": round(1.0 - tps_fault / tps_clean, 4),
            "streams_migrated": faulted["migrated"],
            "failover_s": faulted["failover_s"],
            "lost_tokens": int(lost),
        }
        log(f"fleet pass: {tps_clean:.1f} tok/s clean -> "
            f"{tps_fault:.1f} tok/s across a mid-pass replica kill "
            f"({faulted['migrated']} streams migrated, failover_s="
            f"{faulted['failover_s']}, lost_tokens=0)")

    # Disaggregated prefill/decode lever (ISSUE 17, GLLM_BENCH_PD=1):
    # one prefill-role + one decode-role in-process replica behind the
    # front router core. Every stream prefills on the prefill pool, the
    # prefix KV chain is PUSHED to the decode replica at first token,
    # and the stream migrates there via the journaled continuation path.
    # Asserted invariants: reprefill_tokens == 0 (every pushed page is
    # claimed as cached tokens by the decode side — the decode pool
    # never recomputes the prompt) and lost_tokens == 0 — including
    # under a drain-triggered scale-down of the decode replica mid-pass.
    pd_result = None
    if os.environ.get("GLLM_BENCH_PD", "0") not in ("", "0"):
        phase("pd_pass")
        import copy as _copy
        import statistics as _stats
        import threading as _th
        from gllm_tpu.entrypoints.api_server import serve as _serve
        from gllm_tpu.kvstore import stats as _kvs
        from gllm_tpu.router import FrontRouter
        n_pd = min(n_requests, 4 if args.tiny else 8)
        pd_prompts = [list(p) for p in prompts[:n_pd]]
        pd_tokens = [min(p.max_tokens, 32) for p in params[:n_pd]]
        page = engine_cfg.cache.page_size
        # full prefix pages per prompt — the zero-re-prefill ledger
        pd_pages = [max(0, (len(p) - 1) // page) for p in pd_prompts]

        def _pd_cfg(role):
            cfg = _copy.deepcopy(engine_cfg)
            cfg.scheduler.pool_role = role
            cfg.cache.enable_prefix_caching = True
            cfg.cache.kv_host_pool_pages = max(
                256, 2 * sum(pd_pages) + 8)
            cfg.cache.prefix_serve_port = 0
            cfg.validate()
            return cfg

        class _PdSink:
            def __init__(self):
                self.started = False
                self.tokens = 0
                self.error = None
                self.t0 = None
                self.ttft = None

            def start(self):
                self.started = True

            def send(self, ev):
                if "choices" in ev:
                    if self.ttft is None and self.t0 is not None:
                        self.ttft = time.monotonic() - self.t0
                    self.tokens += 1
                    fin = ev["choices"][0].get("finish_reason")
                    if fin in ("error", "abort"):
                        self.error = f"finish={fin}"
                elif "error" in ev:
                    self.error = ev["error"].get("message")

            def done(self):
                pass

            def fail_json(self, status, obj, headers):
                self.error = f"{status}: {obj}"

        def pd_arm(drain_decode_frac=None, clean_dt=None):
            reps = []
            for role in ("prefill", "decode"):
                llm_r = LLM(config=_pd_cfg(role), model_cfg=model_cfg)
                httpd = _serve(llm_r, "127.0.0.1", 0)
                _th.Thread(target=httpd.serve_forever,
                           daemon=True).start()
                reps.append(httpd)
            addrs = [f"127.0.0.1:{h.server_address[1]}" for h in reps]
            fr = FrontRouter(addrs, probe_interval_s=0.1,
                             breaker_base_s=0.5, breaker_jitter=0.0,
                             stream_idle_timeout_s=300.0)
            push0 = _kvs.PUSH_PAGES.get()
            hit0 = obs_metrics.REGISTRY.get(
                "gllm_prefix_cache_hit_tokens_total").get()
            sinks = [_PdSink() for _ in range(n_pd)]
            timer = None
            try:
                t0 = time.monotonic()
                if drain_decode_frac is not None:
                    delay = max(0.05, drain_decode_frac * clean_dt)
                    timer = _th.Timer(
                        delay,
                        lambda: fr.drain_replica(addrs[1], migrate=True))
                    timer.daemon = True
                    timer.start()

                def run(p, mt, s):
                    s.t0 = time.monotonic()
                    fr.stream("completion",
                              {"prompt": p, "max_tokens": mt,
                               "temperature": 0, "ignore_eos": True,
                               "stream": True}, s)

                threads = [_th.Thread(target=run, args=(p, mt, s),
                                      daemon=True)
                           for p, mt, s in zip(pd_prompts, pd_tokens,
                                               sinks)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=600)
                    assert not t.is_alive(), "pd-arm stream hung"
                dt_arm = time.monotonic() - t0
            finally:
                if timer is not None:
                    timer.cancel()
                fr.close()
                for h in reps:
                    h.shutdown()
                    h.state.engine.shutdown()
            errors = [s.error for s in sinks if s.error]
            assert not errors, f"pd-arm stream errors: {errors[:3]}"
            pushed = int(_kvs.PUSH_PAGES.get() - push0)
            hit_tok = int(obs_metrics.REGISTRY.get(
                "gllm_prefix_cache_hit_tokens_total").get() - hit0)
            return {"tok": sum(s.tokens for s in sinks), "dt": dt_arm,
                    "pushed": pushed, "hit_tok": hit_tok,
                    "ttft_p50": round(_stats.median(
                        s.ttft for s in sinks if s.ttft is not None), 4)}

        clean = pd_arm()
        want_tok = sum(pd_tokens)
        want_pages = sum(pd_pages)
        assert clean["tok"] == want_tok, (
            "clean pd arm dropped tokens", clean["tok"], want_tok)
        # zero re-prefill: the push moved EVERY full prefix page, and
        # the decode side claimed every pushed token as cached
        assert clean["pushed"] == want_pages, (
            "push moved fewer pages than the prompts' prefix chains",
            clean["pushed"], want_pages)
        reprefill = max(0, want_pages * page - clean["hit_tok"])
        assert reprefill == 0, (
            f"decode pool re-prefilled {reprefill} pushed tokens")
        # drain-triggered scale-down mid-pass: the decode replica is
        # admin-drained with migrate=True while streams run on it —
        # journal-backed migration keeps every client stream whole
        drained = pd_arm(drain_decode_frac=0.4, clean_dt=clean["dt"])
        lost = want_tok - drained["tok"]
        assert lost == 0, (
            "drain-triggered scale-down lost tokens "
            f"({drained['tok']} vs {want_tok})")
        pd_result = {
            "requests": n_pd,
            "ttft_p50": clean["ttft_p50"],
            "pushed_pages": clean["pushed"],
            "reprefill_tokens": int(reprefill),
            "lost_tokens": int(lost),
            "drain_ttft_p50": drained["ttft_p50"],
        }
        log(f"pd pass: ttft_p50={clean['ttft_p50']}s, "
            f"{clean['pushed']} pages pushed, reprefill_tokens=0, "
            f"lost_tokens=0 across a mid-pass decode drain")

    phase("report")
    # MFU: every processed token (prompt + output) makes one forward pass.
    total_proc = total_in + total_out
    flops = model_flops(model_cfg, prompts, params,
                        engine_cfg.scheduler.max_prefill_tokens)
    peak = chip_peak_flops()
    mfu = round(flops / dt / peak, 4) if peak else None
    log(f"measured pass: {dt:.2f}s → {value:.1f} output tok/s "
        f"({n_requests / dt:.2f} req/s, "
        f"{total_proc / dt:.0f} processed tok/s, mfu={mfu})")
    result = {
        "metric": METRIC,
        "value": round(value, 2),
        "unit": "tok/s",
        "vs_baseline": round(value / BASELINE_TOK_S, 4),
        "mfu": mfu,
        # KV-cache efficiency (ISSUE 5): the active storage dtype and
        # the effective KV bytes streamed per step over the measured
        # pass — the int8 A/B (GLLM_BENCH_KV_DTYPE) halves the latter
        # against the decode HBM-bandwidth floor.
        "kv_cache_dtype": kv_dtype,
        "kv_bytes_per_step": kv_bytes_per_step(kv_read, step_summary),
        # First-class regression tracker (ISSUE 4): fraction of
        # measured-pass wall time spent in plain (UNfused) decode
        # iterations — the r5 "18/59 steps at 90.8 ms" class. The
        # trajectory watches this directly instead of digging through
        # metrics.steps.by_kind.
        "unfused_frac": step_summary.get("unfused_frac"),
        # On-device finish (ISSUE 6): wasted (dead-row) share of executed
        # fused-block sub-steps over the measured pass — the post-EOS
        # waste the in-loop alive mask + early exit remove. None when
        # ondevice_finish is off (GLLM_BENCH_ODF=0 A/B arm).
        "dead_substep_frac": step_summary.get("dead_substep_frac"),
        "chain_breaks": step_summary.get("chain_breaks_by_reason") or {},
        # Performance attribution (ISSUE 10): where the measured pass's
        # wall clock went (host phases vs device by kind), how much
        # device wall hid under host work, and the device-idle share —
        # every future BENCH_r*.json says WHY it got its number.
        "host_ms_by_phase": step_summary.get("host_ms_by_phase"),
        "device_ms_by_kind": step_summary.get("device_ms_by_kind"),
        "overlap_efficiency": step_summary.get("overlap_efficiency"),
        "bubble_frac": step_summary.get("bubble_frac"),
        # Pipelined loop (ISSUE 11, GLLM_BENCH_PIPELINED A/B): sustained
        # run-ahead depth + stall taxonomy — the bubble_frac's WHY; the
        # --tiny rung also carries the in-process sync-control delta.
        "pipelined_loop": bool(engine_cfg.pipelined_loop),
        "mean_inflight_depth": step_summary.get("mean_inflight_depth"),
        "loop_stalls": step_summary.get("loop_stalls_by_reason") or {},
        # Unified step (ISSUE 12, GLLM_BENCH_UNIFIED A/B): one dispatch
        # family — share of steps that were mixed unified batches
        # (chains absorbing arrivals) and the distinct shape-bucket
        # signatures the runner compiled/warmed over the whole run (the
        # two-population decode+mixed split this flag collapses).
        "unified_step": bool(engine_cfg.unified_step),
        "mixed_step_frac": step_summary.get("mixed_step_frac"),
        "warmed_buckets": getattr(llm.runner, "num_shape_signatures",
                                  None),
        # Fused speculation (ISSUE 13, GLLM_BENCH_SPEC_FUSED A/B): the
        # window draft-acceptance rate and committed tokens per device
        # dispatch — the per-dispatch multiplier the fused path buys
        # (None accept rate on draft-hostile windows that never drafted)
        "spec_fused": bool(engine_cfg.spec_fused),
        "spec_accept_rate": step_summary.get("spec_accept_rate"),
        "tokens_per_dispatch": step_summary.get("tokens_per_dispatch"),
        "metrics": metrics_snapshot,
    }
    if bench_pp > 1:
        # pp topology arm (ISSUE 20, GLLM_BENCH_PP): tag the JSON so pp
        # and single-runner rungs never get compared as like-for-like
        result["parallel_pp"] = bench_pp
    if pp_ab is not None:
        result["pp_ab"] = pp_ab
    if bubble_delta is not None:
        result.update(bubble_delta)
    if unified_ab is not None:
        result["unified_ab"] = unified_ab
    if spec_fused_ab is not None:
        result["spec_fused_ab"] = spec_fused_ab
    if trace_path is not None:
        result["trace_path"] = trace_path
    if sampled_result is not None:
        result["sampled"] = sampled_result
    if prefix_result is not None:
        # tiered prefix store A/B (ISSUE 9, GLLM_BENCH_PREFIX=1):
        # repeated-system-prompt hit rate + TTFT with the disk tier vs
        # full recompute — first-class so the trajectory tracks it
        result["prefix"] = prefix_result
        result["prefix_tiers"] = True
    if chaos_result is not None:
        # self-healing recovery (ISSUE 14, GLLM_BENCH_CHAOS=1): serving
        # throughput under an injected hard crash vs clean, and the
        # latch-to-ready recovery wall — first-class
        result["chaos"] = chaos_result
    if fleet_result is not None:
        # fleet failover (ISSUE 15, GLLM_BENCH_FLEET=1): two replicas
        # behind the front router, a mid-pass replica kill — throughput
        # degradation, streams migrated, failover wall, and the
        # zero-lost-tokens contract — first-class
        result["fleet"] = fleet_result
    if pd_result is not None:
        # disaggregated prefill/decode (ISSUE 17, GLLM_BENCH_PD=1): one
        # prefill + one decode replica behind the router — TTFT, pages
        # pushed, and the zero-re-prefill / zero-lost-tokens contracts
        # (the latter across a drain-triggered scale-down) — first-class
        result["pd"] = pd_result
    # Regression gate (ISSUE 20, GLLM_BENCH_BASELINE=<path>): compare
    # the measured pass against a committed BENCH JSON — the verdict
    # rides the result JSON either way; a regression exits nonzero AFTER
    # the JSON lands (the number is never lost to the gate).
    gate_rc = 0
    baseline_path = os.environ.get("GLLM_BENCH_BASELINE", "")
    if baseline_path and args.profile == "minimal":
        # the minimal rung's shorter-context workload is not comparable
        # to a committed full/conservative baseline (see PROFILES) — a
        # gate verdict here would be noise, and failing it would stop
        # the supervisor ladder before the rung that matters
        log("[bench] GLLM_BENCH_BASELINE set but profile=minimal is "
            "not comparable; gate deferred to the full rung")
        baseline_path = ""
    if baseline_path:
        gate_rc = run_bench_gate(result, baseline_path)
    print(json.dumps(result))
    if gate_rc:
        sys.exit(gate_rc)


if __name__ == "__main__":
    main()
