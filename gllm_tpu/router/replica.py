"""Replica registry: health poller, breaker ladder, restart detection.

Each serving replica is polled on its existing health surface (PR 7/14):
``/readyz`` drives rotation membership — ready / recovering(+Retry-
After) / draining / unhealthy with the latch reason class (crash_loop
etc.) all come back on the JSON body — and ``/server_info`` carries the
fleet identity (``replica.replica_id`` + ``start_time`` + supervised-
recovery ``engine_generation``) plus the prefix-store coordinates the
placement layer probes.

Probing is gated by a per-replica :class:`~gllm_tpu.utils.
CircuitBreaker` (the same ladder kvstore/peer.py runs per prefix peer):
a dead or crash-looping replica costs the poller at most ONE connection
attempt per backoff window — the fleet-level analogue of the
peer-breaker probe bound.

Restart detection is explicit, not inferred: a changed ``replica_id``
or ``start_time`` at the same address means the PROCESS restarted and
every stream it held is gone — the poller flags those streams so the
router fails them over immediately instead of waiting for the idle
timeout. A bumped ``engine_generation`` alone is a supervised
in-process recovery (PR 14): the replica replays its own streams and
the router must NOT interfere.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from gllm_tpu.kvstore.peer import parse_peer_addr
from gllm_tpu.obs import metrics as obs
from gllm_tpu.utils import CircuitBreaker

logger = logging.getLogger(__name__)

_M_PROBES = obs.counter(
    "gllm_router_probes_total",
    "replica health probes by outcome (ok = replica answered; fail = "
    "connection/transport error; skipped = breaker open)", ("outcome",))
_M_BREAKER_OPENS = obs.counter(
    "gllm_router_breaker_opens_total",
    "replica circuit-breaker open transitions, per replica", ("replica",))
_M_READY = obs.gauge(
    "gllm_router_replicas_ready",
    "replicas currently in rotation (ready and breaker closed)")
_M_RESTARTS = obs.counter(
    "gllm_router_restarts_detected_total",
    "silent replica process restarts detected via the /server_info "
    "identity (replica_id/start_time change)", ("replica",))


def http_get_json(host: str, port: int, path: str,
                  timeout: float = 2.0) -> tuple:
    """(status, parsed body or None, headers dict). Raises OSError on
    transport failure; a non-JSON body parses to None."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            body = json.loads(raw) if raw else None
        except (ValueError, UnicodeDecodeError):
            body = None
        return resp.status, body, dict(resp.getheaders())
    finally:
        conn.close()


class Replica:
    """One serving replica's router-side state. Mutated by the poller
    thread; read by placement/handler threads (GIL-atomic field reads;
    the poller is the single writer)."""

    def __init__(self, addr: str, breaker: Optional[CircuitBreaker] = None):
        self.addr = addr.strip()
        self.host, self.port = parse_peer_addr(self.addr)
        self.breaker = breaker or CircuitBreaker()
        self.state = "unknown"   # ready|recovering|draining|unhealthy|down
        self.reason = ""         # /readyz reason / unhealthy class detail
        self.retry_after_s = 0.0
        self.draining_admin = False   # router-side drain (leaves rotation)
        self.identity = None          # (replica_id, start_time)
        self.engine_generation = 0
        self.restarts = 0             # identity changes observed
        self.last_probe_t = 0.0
        self.last_ok_t = 0.0
        self.active_streams = 0       # maintained by FrontRouter
        self.info: dict = {}          # last /server_info body

    @property
    def in_rotation(self) -> bool:
        # breaker open ⇒ out, even when the last probe's state is a
        # stale "ready": a stream-level transport failure can open the
        # breaker between polls, and the poller SKIPS open-breaker
        # probes — without this gate the stale state would keep routing
        # streams at a dead replica for a whole backoff window
        return (self.state == "ready" and not self.draining_admin
                and self.breaker.state != "open")

    def health(self) -> dict:
        return {"addr": self.addr, "state": self.state,
                "reason": self.reason or None,
                "in_rotation": self.in_rotation,
                "draining_admin": self.draining_admin,
                "active_streams": self.active_streams,
                "replica_id": self.identity[0] if self.identity else None,
                "engine_generation": self.engine_generation,
                "restarts_detected": self.restarts,
                "breaker": self.breaker.health(),
                # when the breaker would admit a probe again (0 when
                # closed) — operators and the pool autoscaler both need
                # the recovery ETA, not just the state word
                "breaker_eta_s": round(self.breaker.down_for(), 3),
                "pool_role": (self.info or {}).get("pool_role")
                or "mixed",
                # engine-side queue depths from the last /server_info
                # (the router-side active_streams above counts proxied
                # streams, which misses direct-to-replica traffic)
                "load": {
                    "waiting": int((self.info or {}).get("waiting")
                                   or 0),
                    "running": int((self.info or {}).get("running")
                                   or 0)}}


class ReplicaSet:
    """Owns the replicas and the poller thread. ``on_restart(replica)``
    fires when a silent process restart is detected (the router flags
    that replica's journaled streams for immediate failover)."""

    def __init__(self, addrs: List[str], *,
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 2.0,
                 breaker_base_s: float = 1.0,
                 breaker_max_s: float = 30.0,
                 breaker_fails: int = 1,
                 breaker_jitter: float = 0.1,
                 on_restart: Optional[Callable] = None,
                 info_hook: Optional[Callable] = None,
                 start_poller: bool = True,
                 initial_probe: bool = True):
        if not addrs:
            raise ValueError("router needs at least one replica address")
        self.replicas: Dict[str, Replica] = {}
        for a in addrs:
            if not a.strip():
                continue
            rep = Replica(a, CircuitBreaker(
                breaker_base_s, breaker_max_s, breaker_fails,
                breaker_jitter))
            self.replicas[rep.addr] = rep
        if not self.replicas:
            raise ValueError("router needs at least one replica address")
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.on_restart = on_restart
        # called with each replica after a successful probe (the pool
        # autoscaler scrapes /metrics here, off the handler threads)
        self.info_hook = info_hook
        self._stop = False
        self._wake = threading.Event()
        self._thread = None
        if initial_probe:
            self.probe_all()
        if start_poller:
            self._thread = threading.Thread(target=self._poll_loop,
                                            daemon=True,
                                            name="gllm-router-poller")
            self._thread.start()

    # ---- probing (poller thread; also callable synchronously in tests) -----

    def probe_all(self) -> None:
        reps = list(self.replicas.values())
        if len(reps) == 1:
            self.probe_one(reps[0])
        else:
            # concurrent probes: one timeout-class (SYN-blackholed)
            # replica must not head-of-line-block every other
            # replica's health update for probe_timeout_s. Each
            # replica's breaker/state still has exactly one writer per
            # tick (its probe thread), and ticks serialize on the join.
            threads = [threading.Thread(target=self.probe_one,
                                        args=(r,), daemon=True)
                       for r in reps]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        self._set_ready_gauge()

    def probe_one(self, rep: Replica) -> None:
        if not rep.breaker.allow():
            # open breaker: the replica costs NOTHING this tick — at
            # most one probe per backoff window reaches the wire
            _M_PROBES.inc(outcome="skipped")
            return
        rep.last_probe_t = time.monotonic()
        try:
            status, body, headers = http_get_json(
                rep.host, rep.port, "/readyz",
                timeout=self.probe_timeout_s)
        except (OSError, http.client.HTTPException):
            was_open = rep.breaker.state == "open"
            rep.breaker.failure()
            if rep.breaker.state == "open" and not was_open:
                _M_BREAKER_OPENS.inc(replica=rep.addr)
                logger.warning(
                    "replica %s breaker OPEN for %.1fs (%d trips)",
                    rep.addr, rep.breaker.down_for(), rep.breaker.trips)
            rep.state = "down"
            rep.reason = "unreachable"
            _M_PROBES.inc(outcome="fail")
            return
        # ANY well-formed HTTP answer is a live process: close the
        # breaker; rotation membership is decided by the readiness body
        if rep.breaker.state != "closed":
            logger.info("replica %s recovered (probe succeeded)",
                        rep.addr)
        rep.breaker.success()
        rep.last_ok_t = time.monotonic()
        _M_PROBES.inc(outcome="ok")
        if status == 200:
            rep.state, rep.reason, rep.retry_after_s = "ready", "", 0.0
        else:
            body = body or {}
            rep.state = body.get("reason", "unhealthy")
            rep.reason = (body.get("unhealthy_reason")
                          or body.get("detail") or rep.state)
            try:
                rep.retry_after_s = float(headers.get("Retry-After", 0))
            except (TypeError, ValueError):
                rep.retry_after_s = 0.0
        self._probe_info(rep)
        if self.info_hook is not None:
            try:
                self.info_hook(rep)
            except Exception:   # pragma: no cover - hook guard
                logger.exception("info_hook failed for %s", rep.addr)

    def _probe_info(self, rep: Replica) -> None:
        """/server_info: fleet identity + prefix-store coordinates. A
        failure here never flips rotation (readiness already answered);
        the previous info is kept."""
        try:
            status, body, _ = http_get_json(
                rep.host, rep.port, "/server_info",
                timeout=self.probe_timeout_s)
        except (OSError, http.client.HTTPException):
            return
        if status != 200 or not isinstance(body, dict):
            return
        rep.info = body
        ident = body.get("replica") or {}
        new = (ident.get("replica_id"), ident.get("start_time"))
        rep.engine_generation = int(ident.get("engine_generation") or 0)
        if new[0] is None:
            return
        old = rep.identity
        rep.identity = new
        if old is not None and old != new:
            rep.restarts += 1
            _M_RESTARTS.inc(replica=rep.addr)
            logger.warning(
                "replica %s silently restarted (%s -> %s): its journaled "
                "streams fail over now", rep.addr, old[0], new[0])
            if self.on_restart is not None:
                try:
                    self.on_restart(rep)
                except Exception:   # pragma: no cover - callback guard
                    logger.exception("on_restart callback failed")

    def _set_ready_gauge(self) -> None:
        _M_READY.set(sum(1 for r in self.replicas.values()
                         if r.in_rotation))

    def _poll_loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=self.probe_interval_s)
            self._wake.clear()
            if self._stop:
                return
            self.probe_all()

    # ---- queries (any thread) ----------------------------------------------

    def request_probe(self) -> None:
        """Nudge the poller to re-probe NOW (a handler thread just saw
        a replica fail). The poller stays the breaker's single prober;
        handler threads never mutate breaker state directly."""
        self._wake.set()

    def get(self, addr: str) -> Optional[Replica]:
        return self.replicas.get(addr)

    def in_rotation(self) -> List[Replica]:
        return [r for r in self.replicas.values() if r.in_rotation]

    def min_retry_after(self, default: float = 5.0) -> float:
        """Retry-After hint when nothing is in rotation: the soonest a
        replica might return (breaker window expiry or its own
        Retry-After), floored at 1s."""
        etas = []
        for r in self.replicas.values():
            if r.breaker.state == "open":
                etas.append(r.breaker.down_for())
            elif r.retry_after_s > 0:
                etas.append(r.retry_after_s)
        return max(1.0, min(etas) if etas else default)

    def drain(self, addr: str, on: bool = True) -> bool:
        rep = self.replicas.get(addr)
        if rep is None:
            return False
        rep.draining_admin = on
        self._set_ready_gauge()
        return True

    def health(self) -> List[dict]:
        return [r.health() for r in self.replicas.values()]

    def close(self) -> None:
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
