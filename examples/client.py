"""Minimal OpenAI-API client against a running gllm-tpu server
(reference examples/client.py + chat_client.py). stdlib-only.

Usage:
  python examples/client.py --port 8000 --prompt "hello"
  python examples/client.py --port 8000 --chat "hi there" --stream
"""

import argparse
import http.client
import json


def request(host, port, path, body, stream=False):
    conn = http.client.HTTPConnection(host, port, timeout=600)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    if not stream:
        print(json.dumps(json.loads(resp.read()), indent=2))
        conn.close()
        return
    buf = b""
    while True:
        chunk = resp.read(1)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            event, buf = buf.split(b"\n\n", 1)
            if not event.startswith(b"data: "):
                continue
            payload = event[6:]
            if payload == b"[DONE]":
                print()
                conn.close()
                return
            d = json.loads(payload)
            choice = d["choices"][0]
            delta = (choice.get("delta", {}).get("content")
                     or choice.get("text") or "")
            print(delta, end="", flush=True)
    conn.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--prompt", default=None)
    ap.add_argument("--chat", default=None)
    ap.add_argument("--stream", action="store_true")
    ap.add_argument("--max-tokens", type=int, default=128)
    ap.add_argument("--temperature", type=float, default=0.7)
    args = ap.parse_args()

    if args.chat is not None:
        body = {"messages": [{"role": "user", "content": args.chat}],
                "max_tokens": args.max_tokens,
                "temperature": args.temperature, "stream": args.stream}
        request(args.host, args.port, "/v1/chat/completions", body,
                args.stream)
    else:
        body = {"prompt": args.prompt or "Hello",
                "max_tokens": args.max_tokens,
                "temperature": args.temperature, "stream": args.stream}
        request(args.host, args.port, "/v1/completions", body, args.stream)


if __name__ == "__main__":
    main()
