"""Buffer-donation audit (ISSUE 11 satellite).

The KV cache is by far the largest device buffer; every jitted dispatch
path must donate it (``donate_argnums``) so XLA updates it in place —
an undonated cache costs a whole-pool device copy per step, and a
"donated buffer not used" warning means the donation silently stopped
taking effect. These tests pin BOTH properties:

- behaviorally: the cache's device buffers are bit-for-bit REUSED
  across dispatches (``unsafe_buffer_pointer`` stability — true
  donation, not just a declared intent) on the single-step, fused
  multi-step, and dp-stacked paths;
- statically: all four dispatch-path jit sites (step / step_dp /
  step_multi / the pp stage fn) declare ``donate_argnums=(1,)``, via
  source scan so the pp path is covered without building a pipeline.

Deliberately NOT donated: the previous entry's sampled-token buffer at
the chained/re-form splice (runner._splice_prev) — its collect still
reads that array (the async host copy may be in flight), so donating it
into the next step would invalidate the handle. The audit documents the
boundary rather than chasing the (tiny, [S]-sized) buffer.
"""

import re
import warnings

import jax
import numpy as np
import pytest

from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                             SchedulerConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def model_cfg():
    return ModelConfig(
        architecture="LlamaForCausalLM", vocab_size=256, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, max_position=256)


def make_llm(model_cfg, **kw):
    cfg = EngineConfig(
        load_format="dummy", dtype="float32", max_model_len=64,
        max_num_seqs=4,
        scheduler=SchedulerConfig(max_prefill_tokens=32,
                                  max_decode_seqs=4),
        cache=CacheConfig(page_size=4, num_pages=128), **kw)
    return LLM(config=cfg, model_cfg=model_cfg)


def _kv_ptrs(runner):
    jax.block_until_ready(jax.tree.leaves(runner.kv))
    # per-shard pointers: works for both unsharded arrays and the
    # dp-stacked cache (sharded over the mesh)
    return sorted(sh.data.unsafe_buffer_pointer()
                  for leaf in jax.tree.leaves(runner.kv)
                  for sh in leaf.addressable_shards)


def _spy_reuse(runner, name):
    """Wrap a runner dispatch method; record whether the KV pool's
    device buffers survived the dispatch unchanged (donation aliasing
    reuses the input buffers for the output)."""
    reuse = []
    orig = getattr(runner, name)

    def spy(*a, **kw):
        before = _kv_ptrs(runner)
        out = orig(*a, **kw)
        reuse.append(_kv_ptrs(runner) == before)
        return out

    setattr(runner, name, spy)
    return reuse


def _workload(n=3):
    rng = np.random.default_rng(4)
    prompts = [[int(x) for x in rng.integers(2, 250, size=int(m))]
               for m in rng.integers(3, 10, size=n)]
    sps = [SamplingParams(temperature=0.0, max_tokens=10,
                          ignore_eos=True) for _ in range(n)]
    return prompts, sps


def test_kv_donated_on_single_step_path(model_cfg):
    llm = make_llm(model_cfg)
    reuse = _spy_reuse(llm.runner, "step_async")
    prompts, sps = _workload()
    llm.generate(prompt_token_ids=prompts, sampling_params=sps)
    assert reuse and all(reuse), \
        f"KV buffers copied on {reuse.count(False)} step dispatches"


def test_kv_donated_on_fused_and_chained_paths(model_cfg):
    llm = make_llm(model_cfg, overlap_scheduling=True,
                   multi_step_decode=4, pipelined_loop=True)
    r_multi = _spy_reuse(llm.runner, "step_multi")
    r_chain = _spy_reuse(llm.runner, "step_async_chained")
    prompts, sps = _workload()
    # staggered lengths force re-forms through the chained splice too
    for i, sp in enumerate(sps):
        sp.max_tokens = 6 + 5 * i
    llm.generate(prompt_token_ids=prompts, sampling_params=sps)
    assert r_multi and all(r_multi)
    assert all(r_chain)        # may be empty if every edge fused


def test_kv_donated_on_dp_path(model_cfg):
    llm = make_llm(model_cfg, parallel=ParallelConfig(dp=2),
                   attention_impl="xla")
    reuse = _spy_reuse(llm.runner, "step_async_dp")
    prompts, sps = _workload(4)
    llm.generate(prompt_token_ids=prompts, sampling_params=sps)
    assert reuse and all(reuse)


def test_no_donation_warnings_on_hot_path(model_cfg):
    """No 'donated buffer not used' (or any donation-related) warning
    may fire across the full overlap + fused + pipelined serving path —
    such a warning means a dispatch path stopped consuming its donated
    cache and every step silently pays a pool-sized copy."""
    llm = make_llm(model_cfg, overlap_scheduling=True,
                   multi_step_decode=4, decode_slot_batching=True,
                   ondevice_finish=True, pipelined_loop=True)
    prompts, sps = _workload(4)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        llm.generate(prompt_token_ids=prompts, sampling_params=sps)
    bad = [str(w.message) for w in caught
           if "donat" in str(w.message).lower()]
    assert not bad, bad


def test_all_dispatch_paths_declare_kv_donation():
    """Source guard: the five jitted dispatch paths — runner.py's step /
    step_dp / step_multi / step_spec (fused speculation) and
    pp_runner.py's stage fn — must declare ``donate_argnums=(1,)`` (kv
    is argument 1 on each). Source scan so the pp path is audited
    without building a pipeline on CPU."""
    import os
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "gllm_tpu", "runner")

    def jit_sites(path, fn_names):
        src = open(path).read()
        found = {}
        # each jitted dispatch body is an inner fn ``def <name>(params,
        # kv, ...)``; its multi-line @functools.partial(jax.jit, ...)
        # decorator sits in the preceding few hundred chars
        for name in fn_names:
            m = re.search(r"def " + name + r"\(params, kv", src)
            assert m, f"{path}: jit site for {name} not found"
            window = src[max(0, m.start() - 800):m.start()]
            assert "jax.jit" in window, \
                f"{path}: {name} is no longer jitted?"
            found[name] = "donate_argnums=(1,)" in window
        return found

    runner = jit_sites(os.path.join(root, "runner.py"),
                       ["step", "step_dp", "step_multi", "step_spec"])
    pp = jit_sites(os.path.join(root, "pp_runner.py"), ["stage"])
    missing = [n for n, ok in {**runner, **pp}.items() if not ok]
    assert not missing, f"dispatch paths without kv donation: {missing}"
