"""Online serving benchmark against a live server: TTFT / TPOT / throughput.

Counterpart of the reference's serving benchmark flow (backend_request_func
driven over a request list with bounded concurrency). stdlib threads.

Arrival model mirrors the reference's serving benchmark: with
``--request-rate R`` requests arrive as a Poisson process at R req/s
(exponential inter-arrivals, seeded); the default (inf) fires everything
at once, bounded only by ``--concurrency`` — the closed-loop saturation
measurement.

Usage:
  python benchmarks/serve_bench.py --port 8000 --num-prompts 64 \
      --concurrency 16 --prompt-len 256 --output-len 128 \
      [--request-rate 8]
"""

import argparse
import json
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from benchmarks.backend_request_func import (run_requests,  # noqa: E402
                                             summarize)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--num-prompts", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=256)
    ap.add_argument("--output-len", type=int, default=128)
    ap.add_argument("--request-rate", type=float, default=float("inf"),
                    help="poisson arrival rate (req/s); inf = all at once")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    rng = np.random.default_rng(args.seed)
    payloads = []
    for _ in range(args.num_prompts):
        p_len = max(4, int(rng.normal(args.prompt_len,
                                      args.prompt_len / 4)))
        payloads.append({
            "prompt": rng.integers(1, 30000, size=p_len).tolist(),
            "max_tokens": args.output_len,
            "temperature": 0.0,
            "ignore_eos": True,
        })

    results, wall = run_requests(args.host, args.port, payloads,
                                 args.concurrency, args.request_rate,
                                 seed=args.seed)

    summary = summarize(results, wall)
    errors = {r.error for r in results if r and not r.success and r.error}
    if errors:
        summary["errors"] = sorted(errors)[:3]
    print(json.dumps(summary, indent=2))


if __name__ == "__main__":
    main()
