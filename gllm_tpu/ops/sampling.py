"""On-device sampling.

Covers the reference Sampler (/root/reference/gllm/layers/sampler.py:22-106):
greedy fast path (argmax, temperature skipped), fused top-k/top-p sampling
(sgl_kernel top_k_top_p_sampling_from_probs → here a sorted-mask + Gumbel
argmax, one fused XLA program), scaling repetition penalty
(layers/repetition_penalty.py Triton kernel → a masked elementwise op over a
token-presence mask), and logprob computation.

Everything is batched over the padded seq axis with per-seq parameters so one
compiled program serves any mix of greedy/sampled requests.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SamplingMetadata(NamedTuple):
    temperature: jnp.ndarray       # [S] f32; 0.0 → greedy
    top_p: jnp.ndarray             # [S] f32 in (0, 1]
    top_k: jnp.ndarray             # [S] i32; >= vocab → disabled
    # Scaling repetition penalty (reference repetition_penalty.py:40-80):
    # penalty > 1 scales positive logits down / negative up for seen tokens.
    repetition_penalty: jnp.ndarray   # [S] f32
    step_key: jnp.ndarray          # PRNG key for this step
    # OpenAI additive penalties (reference protocol.py): logits -=
    # presence * (count > 0) + frequency * count.
    presence_penalty: Optional[jnp.ndarray] = None   # [S] f32
    frequency_penalty: Optional[jnp.ndarray] = None  # [S] f32
    # Per-seq seeded determinism (reference honors SamplingParams.seed):
    # seed >= 0 → that row's key is a pure function of (seed, out_step),
    # independent of batch composition; seed < 0 → engine step_key.
    seed: Optional[jnp.ndarray] = None       # [S] i32
    out_step: Optional[jnp.ndarray] = None   # [S] i32 output-token index
    # min_p nucleus floor (reference protocol.py min_p): after temperature,
    # drop tokens whose prob < min_p · max_prob. 0.0 → disabled.
    min_p: Optional[jnp.ndarray] = None      # [S] f32
    # OpenAI logit_bias (reference protocol.py logit_bias): per-seq sparse
    # (token id, bias) pairs scatter-added to the logits before greedy,
    # sampling, and logprobs. Padding rows carry bias 0 (a no-op add), so
    # no mask array is needed.
    bias_ids: Optional[jnp.ndarray] = None   # [S, B] i32
    bias_vals: Optional[jnp.ndarray] = None  # [S, B] f32
    # On-device finish detection (fused multi-step decode only): per-row
    # EOS + stop-token-id sets, padded to a fixed pow2 bucket with -1
    # (never equal to a sampled id ≥ 0), and the sub-step index from
    # which the check is armed (min_tokens gating — a stop hit before
    # this sub-step is ignored, matching Sequence.check_finish). None on
    # every other path — these fields never enter single-step programs.
    stop_ids: Optional[jnp.ndarray] = None   # [S, E] i32, -1 padding
    stop_from: Optional[jnp.ndarray] = None  # [S] i32


class PenaltyTokens(NamedTuple):
    """Padded per-seq token-id lists for penalty application.

    The reference keeps a persistent [seqs, vocab] mask pool on device
    (memory_manager.py:723-828) with slot lifecycle management; here the
    [S, V] count matrix is regenerated ON DEVICE each step from the padded
    id lists — a [S, L] int32 transfer (a few MB) and a fused scatter-add
    replace the pool, its alloc/free/preemption bookkeeping, and the
    hundred-MB host-built matrix the first version shipped per step."""
    ids: jnp.ndarray      # [S, L] int32 (padding clipped to id 0)
    mask: jnp.ndarray     # [S, L] bool — False on padding


def _counts_from_tokens(pt: PenaltyTokens, vocab: int) -> jnp.ndarray:
    S = pt.ids.shape[0]
    rows = jnp.arange(S, dtype=jnp.int32)[:, None]
    return jnp.zeros((S, vocab), jnp.int32).at[
        rows, pt.ids].add(pt.mask.astype(jnp.int32))


def apply_penalties(logits: jnp.ndarray,
                    token_counts,
                    md: "SamplingMetadata") -> jnp.ndarray:
    """token_counts: [S, V] occurrence counts, or a PenaltyTokens bundle
    expanded on device. Applies the scaling repetition penalty (reference
    repetition_penalty.py:40-80) and the OpenAI presence/frequency
    penalties in one pass."""
    if token_counts is None:
        return logits
    if isinstance(token_counts, PenaltyTokens):
        token_counts = _counts_from_tokens(token_counts, logits.shape[-1])
    counts = token_counts.astype(jnp.float32)
    seen = counts > 0
    p = md.repetition_penalty[:, None]
    penalized = jnp.where(logits > 0, logits / p, logits * p)
    logits = jnp.where(seen, penalized, logits)
    if md.presence_penalty is not None:
        logits = logits - md.presence_penalty[:, None] * seen
    if md.frequency_penalty is not None:
        logits = logits - md.frequency_penalty[:, None] * counts
    return logits


def apply_logit_bias(logits: jnp.ndarray,
                     md: "SamplingMetadata") -> jnp.ndarray:
    """Scatter-add the per-seq OpenAI logit_bias pairs (reference
    protocol.py logit_bias → sampler logits add). Padding entries carry
    value 0, so the add is a no-op there."""
    if md.bias_ids is None:
        return logits
    rows = jnp.arange(logits.shape[0], dtype=jnp.int32)[:, None]
    return logits.at[rows, md.bias_ids].add(md.bias_vals)


def adjust_logits(logits: jnp.ndarray, token_counts,
                  md: "SamplingMetadata") -> jnp.ndarray:
    """All pre-sampling logit adjustments in distribution order: logit_bias
    first (it defines the distribution), then repetition/presence/frequency
    penalties. Shared by the sample path and the logprob path so reported
    logprobs match what was sampled from."""
    logits = apply_logit_bias(logits.astype(jnp.float32), md)
    return apply_penalties(logits, token_counts, md)


# Truncation width of the sampled-path fast mask: a full-vocab jnp.sort
# lowers to an XLA sort+while pair (~88 ms/step at [256, 128256] on the
# r5 chip — VERDICT); jax.lax.top_k over the first 4096 candidates covers
# every practical top-k/top-p nucleus, with an exact full-sort fallback
# branch for the rows it can't prove (lax.cond, so only the taken branch
# executes). 0 disables the fast path (always sort).
_TOPK_FAST_BOUND = 4096
# Boundary margin of the fast path's equivalence certificate: the two
# paths accumulate probability mass with different float32 reduction
# shapes (cumsum over kb vs vocab entries), so a nucleus boundary
# sitting within the accumulated rounding error of top_p (or of the
# min_p floor) could classify differently. Such rows take the sort
# fallback; the bound covers the worst-case positive-summand prefix-sum
# error (~kb * eps_f32) with slack.
_TOPK_FAST_MARGIN = 5e-4


def _topk_topp_mask_sort(logits: jnp.ndarray, top_k: jnp.ndarray,
                         top_p: jnp.ndarray,
                         min_p: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mask logits outside the per-row top-k / top-p / min-p nucleus to
    -inf (full-vocab sort reference; the dispatch wrapper below routes
    through a bounded lax.top_k when it can prove equivalence)."""
    vocab = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]          # desc
    # top-k threshold value per row; top_k <= 0 is the "disabled" sentinel
    # (SamplingParams uses -1) → treat as full vocab.
    top_k = jnp.where(top_k <= 0, vocab, top_k)
    k_idx = jnp.clip(top_k - 1, 0, vocab - 1)
    kth = jnp.take_along_axis(sorted_logits, k_idx[:, None], axis=-1)
    keep_k = logits >= kth

    # top-p: keep the smallest prefix of sorted probs whose mass reaches
    # p. Probabilities via exp(x - logsumexp(UNSORTED logits)) — the
    # same formula (and normalizer input) the bounded fast path uses, so
    # the two paths' per-entry probs agree to the last ulp and only the
    # cumsum reduction shape can differ (covered by the fast path's
    # boundary-margin certificate).
    sorted_probs = jnp.exp(sorted_logits - jax.nn.logsumexp(
        logits, axis=-1, keepdims=True))
    cumsum = jnp.cumsum(sorted_probs, axis=-1)
    # entry i kept iff cumulative mass *before* it is < p
    keep_sorted = (cumsum - sorted_probs) < top_p[:, None]
    # threshold = smallest kept logit in sorted order; top_p >= 1 means
    # DISABLED and must keep the full support — without the explicit
    # -inf, float32 cumsum rounding can reach 1.0 before the tail and
    # silently drop the tiniest-probability tokens at p = 1.0
    thresh = jnp.where(
        (top_p >= 1.0)[:, None], -jnp.inf,
        jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                axis=-1, keepdims=True))
    keep_p = logits >= thresh

    keep = keep_k & keep_p
    if min_p is not None:
        # min_p floor: keep tokens with prob >= min_p · max_prob. The
        # condition is monotone along the sorted axis, so the smallest
        # kept sorted logit is a per-row threshold like top-p's.
        keep_sorted_mp = (sorted_probs
                          >= min_p[:, None] * sorted_probs[:, :1])
        mp_thresh = jnp.min(
            jnp.where(keep_sorted_mp, sorted_logits, jnp.inf),
            axis=-1, keepdims=True)
        keep = keep & (logits >= mp_thresh)
    return jnp.where(keep, logits, -jnp.inf)


def _topk_topp_mask(logits: jnp.ndarray, top_k: jnp.ndarray,
                    top_p: jnp.ndarray,
                    min_p: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Nucleus mask with a bounded fast path.

    ``jax.lax.top_k(k=min(vocab, _TOPK_FAST_BOUND))`` gives the same
    descending value prefix the full sort would, so all three per-row
    thresholds (kth logit, smallest kept top-p logit, smallest kept
    min-p logit) are computed from it EXACTLY whenever each row's kept
    set provably ends inside the truncation:

    - top-k: ``top_k <= bound`` (or disabled — threshold -inf);
    - top-p: the last truncated entry is already outside the nucleus
      (cumulative-mass-before >= top_p), so no entry beyond the bound
      can be kept (cumulative mass is monotone); or top_p >= 1;
    - min-p: the last truncated entry is already below the min_p floor
      (monotone along the sorted axis); or min_p <= 0.

    Both paths derive per-entry probabilities with the same
    exp(x - logsumexp) formula, but their cumsum reduction shapes
    differ, so the certificate is CONSERVATIVE: a row whose top-p (or
    min-p) decision boundary sits within _TOPK_FAST_MARGIN of the
    cutoff also fails it — float rounding could classify that boundary
    token differently between the two reductions, and such rows must
    take the reference instead of a near-miss "exact" mask.

    Any row that can't be proven routes the WHOLE batch through the
    full-sort reference via lax.cond — only the taken branch executes,
    so the common small-nucleus case never pays the sort. Disabled
    (threshold -inf) masks differ from the reference's global-min
    threshold only for -inf logits, which finite model logits never
    produce. Equivalence is pinned by tests/test_sampling_fastpath.py.
    """
    vocab = logits.shape[-1]
    kb = _TOPK_FAST_BOUND
    if not kb or vocab <= kb:
        return _topk_topp_mask_sort(logits, top_k, top_p, min_p)
    top_vals, _ = jax.lax.top_k(logits, kb)                  # [S, kb] desc
    lse = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    probs = jnp.exp(top_vals - lse)          # full-softmax probabilities
    cum = jnp.cumsum(probs, axis=-1)

    eff_k = jnp.where(top_k <= 0, vocab, top_k)
    ok_k = (eff_k <= kb) | (eff_k >= vocab)
    k_idx = jnp.clip(eff_k - 1, 0, kb - 1)
    kth = jnp.where(
        (eff_k >= vocab)[:, None], -jnp.inf,
        jnp.take_along_axis(top_vals, k_idx[:, None], axis=-1))

    cum_before = cum - probs
    keep_p = cum_before < top_p[:, None]
    # boundary-ambiguous rows (any entry's mass-before within the float
    # margin of top_p) fall back — see the docstring
    close_p = (jnp.abs(cum_before - top_p[:, None])
               < _TOPK_FAST_MARGIN).any(axis=-1)
    ok_p = (top_p >= 1.0) | (~keep_p[:, -1] & ~close_p)
    thresh_p = jnp.where(
        (top_p >= 1.0)[:, None], -jnp.inf,
        jnp.min(jnp.where(keep_p, top_vals, jnp.inf), axis=-1,
                keepdims=True))

    keep = (logits >= kth) & (logits >= thresh_p)
    ok = ok_k & ok_p
    if min_p is not None:
        floor = min_p[:, None] * probs[:, :1]
        keep_mp = probs >= floor
        close_mp = (jnp.abs(probs - floor)
                    < _TOPK_FAST_MARGIN).any(axis=-1)
        ok = ok & ((min_p <= 0.0) | (~keep_mp[:, -1] & ~close_mp))
        thresh_mp = jnp.where(
            (min_p <= 0.0)[:, None], -jnp.inf,
            jnp.min(jnp.where(keep_mp, top_vals, jnp.inf), axis=-1,
                    keepdims=True))
        keep = keep & (logits >= thresh_mp)
    return jax.lax.cond(
        jnp.all(ok),
        lambda: jnp.where(keep, logits, -jnp.inf),
        lambda: _topk_topp_mask_sort(logits, top_k, top_p, min_p))


def sample(logits: jnp.ndarray, md: SamplingMetadata,
           token_counts: Optional[jnp.ndarray] = None, *,
           all_greedy: bool = False) -> jnp.ndarray:
    """logits: [S, V] → sampled token ids [S] int32.

    ``all_greedy`` is a STATIC flag (part of the step program's jit key):
    when every live request in the batch has temperature 0, the whole
    sampled branch — the top-k/top-p/min-p mask plus per-row Gumbel
    draws — compiles away and the program ends at the argmax. On the r5
    chip that branch was ~88 ms of a ~96 ms decode step as a full-vocab
    jnp.sort; the mask now takes a bounded lax.top_k fast path with an
    exact sort fallback (_topk_topp_mask), so mixed/sampled batches pay
    far less too. Greedy rows of a MIXED batch take the same jnp.where
    below, so the two programs agree bit-for-bit on greedy rows."""
    logits = adjust_logits(logits, token_counts, md)
    greedy_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if all_greedy:
        return greedy_tokens

    temp = jnp.maximum(md.temperature, 1e-6)[:, None]
    scaled = _topk_topp_mask(logits / temp, md.top_k, md.top_p, md.min_p)
    # Gumbel-max == categorical sampling, stays fused on device.
    if md.seed is None:
        gumbel = jax.random.gumbel(md.step_key, scaled.shape,
                                   dtype=jnp.float32)
    else:
        S, V = scaled.shape
        keys = _row_base_keys(md, S)
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(k, (V,), dtype=jnp.float32))(keys)
    sampled = jnp.argmax(scaled + gumbel, axis=-1).astype(jnp.int32)

    return jnp.where(md.temperature == 0.0, greedy_tokens, sampled)


def _row_base_keys(md: "SamplingMetadata", S: int):
    """Per-seq verification keys, same derivation discipline as sample():
    seeded rows are a pure function of (seed, out_step) so a request is
    deterministic across batch compositions; unseeded rows fold the engine
    step key."""
    rows = jnp.arange(S, dtype=jnp.uint32)
    unseeded = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        md.step_key, rows)
    if md.seed is None:
        return unseeded
    seeded = jax.vmap(
        lambda s, t: jax.random.fold_in(
            jax.random.key(s.astype(jnp.uint32)), t))(
        md.seed, md.out_step.astype(jnp.uint32))
    key_data = jnp.where((md.seed >= 0)[:, None],
                         jax.random.key_data(seeded),
                         jax.random.key_data(unseeded))
    return jax.random.wrap_key_data(key_data)


def spec_adjust_logits(logits_mat: jnp.ndarray, drafts: jnp.ndarray,
                       md: "SamplingMetadata",
                       token_counts=None) -> jnp.ndarray:
    """Per-verify-row logit adjustments for speculative decoding.

    Verify row i of seq s scores the token that would follow
    ``committed_tokens + drafts[:i]`` — so penalties must see the base
    occurrence counts PLUS the draft prefix of that row (computed on
    device: the draft one-hots are exclusive-cumsummed along the row
    axis), while logit_bias is position-independent and simply repeats
    per row. With both applied here, spec_verify's accept/argmax math
    runs on exactly the distribution the non-speculative path samples
    from (reference applies the same sampler adjustments to its verify
    logits via its unified sampler; we share adjust_logits for the same
    reason). No-op when the batch carries neither penalties nor bias."""
    if token_counts is None and md.bias_ids is None:
        return logits_mat
    S, K1, V = logits_mat.shape
    K = K1 - 1
    rep = lambda a: (None if a is None                      # noqa: E731
                     else jnp.repeat(a, K1, axis=0))
    md_rep = md._replace(
        repetition_penalty=rep(md.repetition_penalty),
        presence_penalty=rep(md.presence_penalty),
        frequency_penalty=rep(md.frequency_penalty),
        bias_ids=rep(md.bias_ids), bias_vals=rep(md.bias_vals))
    counts_flat = None
    if token_counts is not None:
        base = (_counts_from_tokens(token_counts, V)
                if isinstance(token_counts, PenaltyTokens)
                else token_counts)                          # [S, V]
        # int8 keeps the [S, K, V] intermediates 4x smaller than the
        # verify logits they sit next to (counts per draft run <= K < 127)
        d_safe = jnp.maximum(drafts, 0)
        live = (drafts >= 0).astype(jnp.int8)
        dhot = jnp.zeros((S, K, V), jnp.int8).at[
            jnp.arange(S)[:, None], jnp.arange(K)[None, :],
            d_safe].add(live)
        # row i sees drafts[:i]: exclusive cumsum, then the bonus row
        # (i = K) sees all K drafts
        dcum = jnp.cumsum(dhot, axis=1)
        dpfx = jnp.concatenate(
            [jnp.zeros((S, 1, V), jnp.int8), dcum], axis=1)  # [S, K1, V]
        counts_flat = (base[:, None, :]
                       + dpfx.astype(jnp.int32)).reshape(S * K1, V)
    return adjust_logits(logits_mat.reshape(S * K1, V).astype(jnp.float32),
                         counts_flat, md_rep).reshape(S, K1, V)


def spec_verify(logits_mat: jnp.ndarray, drafts: jnp.ndarray,
                md: "SamplingMetadata", sampled: bool = True):
    """Verify speculative drafts against the target model's logits.

    ``sampled`` is a TRACE-TIME flag (the runner passes it as a jit
    static): False means every draft row in this batch is greedy, and the
    verify compiles to the single argmax of rounds past — no sort,
    softmax, or RNG on the hot path.

    logits_mat: [S, K+1, V] — row i is the target distribution for the
    token AFTER draft position i (row 0 follows the last committed token).
    drafts: [S, K] int32, -1 padding. Returns (tok_mat [S, K+1] int32,
    accept [S] int32) under the engine contract: the scheduler commits
    ``tok_mat[s, :accept+1]`` — accepted positions hold the draft itself,
    position ``accept`` holds the correction (or the bonus token when all
    K drafts were accepted).

    Greedy rows (temperature 0) accept by argmax equality — byte-identical
    to non-speculative greedy. Sampled rows use rejection sampling against
    the deterministic prompt-lookup proposal (q = δ at the draft): accept
    draft d_i with prob p_i(d_i); on rejection resample from the residual
    p_i with d_i excluded, which preserves the target distribution exactly
    (the standard speculative-sampling correction specialised to a
    one-hot q). Distribution-level equivalence, not realization-level: a
    seeded request's sampled tokens consume different draw indices than
    its non-speculative run."""
    S, K1, V = logits_mat.shape
    K = K1 - 1
    logits_f = logits_mat.astype(jnp.float32)
    greedy_mat = jnp.argmax(logits_f, axis=-1).astype(jnp.int32)
    ok_g = greedy_mat[:, :-1] == drafts                   # pad -1 never ==
    if not sampled:
        accept = jnp.cumprod(ok_g.astype(jnp.int32), axis=-1).sum(axis=-1)
        return greedy_mat, accept

    # target sampling distribution per verify row (temperature + top-k/p +
    # min-p masks, renormalized by the softmax)
    temp = jnp.maximum(md.temperature, 1e-6)[:, None, None]
    rep = lambda a: jnp.repeat(a, K1, axis=0)             # noqa: E731
    masked = _topk_topp_mask(
        (logits_f / temp).reshape(S * K1, V), rep(md.top_k), rep(md.top_p),
        None if md.min_p is None else rep(md.min_p))
    p = jax.nn.softmax(masked, axis=-1).reshape(S, K1, V)

    base = _row_base_keys(md, S)
    pos_keys = jax.vmap(
        lambda k: jax.vmap(lambda i: jax.random.fold_in(k, i))(
            jnp.arange(K1, dtype=jnp.uint32)))(base)      # [S, K1] keys
    u = jax.vmap(jax.vmap(
        lambda k: jax.random.uniform(jax.random.fold_in(k, 0), ())))(
        pos_keys)                                         # [S, K1]
    gumbel = jax.vmap(jax.vmap(
        lambda k: jax.random.gumbel(jax.random.fold_in(k, 1), (V,),
                                    dtype=jnp.float32)))(pos_keys)

    d_safe = jnp.maximum(drafts, 0)
    p_draft = jnp.take_along_axis(p[:, :K], d_safe[..., None],
                                  axis=-1)[..., 0]        # [S, K]
    ok_s = (u[:, :K] < p_draft) & (drafts >= 0)

    # corrections: position j < K samples the residual (draft banned);
    # position K samples the bonus token from its full distribution
    iota = jnp.arange(V, dtype=jnp.int32)
    ban = (iota[None, None, :] == d_safe[..., None]) & \
        (drafts >= 0)[..., None]
    p_corr = jnp.concatenate(
        [jnp.where(ban, 0.0, p[:, :K]), p[:, K:]], axis=1)
    logp = jnp.where(p_corr > 0, jnp.log(jnp.maximum(p_corr, 1e-30)),
                     -jnp.inf)
    corr = jnp.argmax(logp + gumbel, axis=-1).astype(jnp.int32)  # [S, K1]

    tok_sampled = jnp.concatenate(
        [jnp.where(ok_s, drafts, corr[:, :K]), corr[:, K:]], axis=1)

    greedy_rows = md.temperature == 0.0
    ok = jnp.where(greedy_rows[:, None], ok_g, ok_s)
    accept = jnp.cumprod(ok.astype(jnp.int32), axis=-1).sum(axis=-1)
    tok_mat = jnp.where(greedy_rows[:, None], greedy_mat, tok_sampled)
    return tok_mat, accept


def ngram_propose(ring: jnp.ndarray, ring_len: jnp.ndarray, *,
                  n: int, k: int) -> jnp.ndarray:
    """On-device prompt-lookup proposal (the fused-speculation half of
    ``propose_ngram_drafts``): for each row of a right-aligned recent-
    token ring (``ring[s, R-1]`` is the newest token, ``ring_len[s]``
    valid entries, -1 elsewhere), find the most recent EARLIER
    occurrence of the last-``n``-token pattern and return its
    continuation, up to ``k`` tokens — one vectorized sliding-window
    compare, no host readback. Returns [S, k] int32 drafts with -1
    padding (rows with no match, short rings, and continuation tails
    past the ring are all -1). ``n``/``k`` are trace-time constants (the
    compare unrolls over the n pattern positions)."""
    S, R = ring.shape
    if R <= n:
        return jnp.full((S, k), -1, jnp.int32)
    pattern = ring[:, R - n:]                          # [S, n]
    m = R - n                  # window starts 0..m-1 (start m IS the
    match = jnp.ones((S, m), bool)                     # pattern itself)
    for d in range(n):
        match = match & (ring[:, d:d + m] == pattern[:, d:d + 1])
    # a window is only real when it sits fully inside the valid region
    starts = jnp.arange(m, dtype=jnp.int32)[None, :]
    match = match & (starts >= (R - ring_len)[:, None])
    has = match.any(axis=1)
    # most recent match = highest start index
    j = (m - 1) - jnp.argmax(match[:, ::-1], axis=1)   # [S]
    idx = j[:, None] + n + jnp.arange(k, dtype=jnp.int32)[None, :]
    cont = jnp.take_along_axis(ring, jnp.minimum(idx, R - 1), axis=1)
    valid = (idx < R) & has[:, None] & (cont >= 0)
    return jnp.where(valid, cont, -1).astype(jnp.int32)


def ring_shift_in(ring: jnp.ndarray, ring_len: jnp.ndarray,
                  toks: jnp.ndarray, counts: jnp.ndarray):
    """Append ``counts[s]`` tokens of ``toks[s]`` (left-to-right) to each
    row of a right-aligned ring: the whole row shifts left by its count
    so ``ring[s, R-1]`` stays the newest token. ``counts`` may be 0
    (identity) up to toks.shape[1]; entries of ``toks`` past a row's
    count never enter the ring. Returns (ring, ring_len)."""
    S, R = ring.shape
    ext = jnp.concatenate([ring, toks.astype(ring.dtype)], axis=1)
    idx = jnp.arange(R, dtype=jnp.int32)[None, :] + counts[:, None]
    return (jnp.take_along_axis(ext, idx, axis=1),
            jnp.minimum(ring_len + counts, R))


def stop_token_hit(tokens: jnp.ndarray, md: "SamplingMetadata",
                   sub_step) -> jnp.ndarray:
    """[S] bool — did row s's sampled token land in its stop set?

    The on-device half of ``Sequence.check_finish``: ``tokens`` [S] are
    this sub-step's sampled ids, ``md.stop_ids`` [S, E] the padded
    per-row EOS/stop-token sets (-1 padding never matches an id >= 0),
    and ``md.stop_from`` the per-row arming sub-step (min_tokens gate).
    Rows with an empty set (all -1) never hit. Returns all-False when
    the batch carries no stop sets at all."""
    if md.stop_ids is None:
        return jnp.zeros(tokens.shape, bool)
    hit = (tokens[:, None] == md.stop_ids).any(axis=-1)
    if md.stop_from is not None:
        hit = hit & (sub_step >= md.stop_from)
    return hit


def compute_logprobs(logits: jnp.ndarray, token_ids: jnp.ndarray,
                     top_n: int):
    """Log-softmax based logprobs (reference sampler.py:71-91).

    Returns (chosen_logprob [S], top_ids [S, top_n], top_logprobs [S, top_n]).
    """
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    chosen = jnp.take_along_axis(logprobs, token_ids[:, None], axis=-1)[:, 0]
    top_vals, top_ids = jax.lax.top_k(logprobs, top_n)
    return chosen, top_ids.astype(jnp.int32), top_vals
