"""Logprobs end-to-end + OpenAI protocol completeness (n, best_of, stop
strings, presence/frequency penalties) — VERDICT r1 item 7."""

import http.client
import json
import math
import threading

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(3)
    d = tmp_path_factory.mktemp("lp_model")
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0, attention_bias=False))
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


def make_llm(model_dir, **kw):
    cfg = EngineConfig(model=model_dir, dtype="float32", max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=128), **kw)
    return LLM(config=cfg)


def test_output_logprobs_match_hf(ckpt):
    model_dir, hf = ckpt
    llm = make_llm(model_dir)
    prompt = [5, 17, 93, 41]
    out = llm.generate(
        prompt_token_ids=[prompt],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=4,
                                       logprobs=3, ignore_eos=True))[0]
    assert out.logprobs is not None and len(out.logprobs) == 4
    ids = list(prompt)
    with torch.no_grad():
        for (chosen, top_ids, top_lps), tok in zip(out.logprobs,
                                                   out.output_token_ids):
            logits = hf(torch.tensor([ids])).logits[0, -1]
            want = torch.log_softmax(logits.float(), -1)
            assert math.isclose(chosen, float(want[tok]), abs_tol=2e-3)
            want_top = torch.topk(want, 3)
            assert top_ids == want_top.indices.tolist()
            np.testing.assert_allclose(top_lps, want_top.values.numpy(),
                                       atol=2e-3)
            ids.append(tok)


def test_prompt_logprobs_match_hf(ckpt):
    model_dir, hf = ckpt
    llm = make_llm(model_dir)
    prompt = [5, 17, 93, 41, 7, 30]
    out = llm.generate(
        prompt_token_ids=[prompt],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=2,
                                       prompt_logprobs=2,
                                       ignore_eos=True))[0]
    assert out.prompt_logprobs is not None
    assert out.prompt_logprobs[0] is None
    with torch.no_grad():
        logits = hf(torch.tensor([prompt])).logits[0].float()
        want = torch.log_softmax(logits, -1)
    for p in range(1, len(prompt)):
        chosen, top_ids, top_lps = out.prompt_logprobs[p]
        assert math.isclose(chosen, float(want[p - 1, prompt[p]]),
                            abs_tol=2e-3), p
        assert len(top_ids) == 2


def test_prompt_logprobs_with_chunked_prefill(ckpt):
    model_dir, _ = ckpt
    from gllm_tpu.config import SchedulerConfig
    cfg = EngineConfig(model=model_dir, dtype="float32", max_model_len=128,
                       scheduler=SchedulerConfig(max_prefill_tokens=4,
                                                 min_prefill_tokens=2),
                       cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg)
    prompt = [5, 17, 93, 41, 7, 30, 2, 9, 77, 15]
    out = llm.generate(
        prompt_token_ids=[prompt],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=2,
                                       prompt_logprobs=1,
                                       ignore_eos=True))[0]
    big = make_llm(model_dir).generate(
        prompt_token_ids=[prompt],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=2,
                                       prompt_logprobs=1,
                                       ignore_eos=True))[0]
    assert out.prompt_logprobs[0] is None and big.prompt_logprobs[0] is None
    for a, b in zip(out.prompt_logprobs[1:], big.prompt_logprobs[1:]):
        assert math.isclose(a[0], b[0], abs_tol=2e-3)


def test_presence_frequency_penalties_change_output(ckpt):
    model_dir, _ = ckpt
    prompt = [[7, 8, 9, 10]]
    base = make_llm(model_dir).generate(
        prompt_token_ids=prompt,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=12,
                                       ignore_eos=True))[0]
    pen = make_llm(model_dir).generate(
        prompt_token_ids=prompt,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=12,
                                       ignore_eos=True,
                                       frequency_penalty=2.0))[0]
    # the tiny model repeats greedily; a strong frequency penalty breaks it
    assert base.output_token_ids != pen.output_token_ids
    assert len(set(pen.output_token_ids)) > len(set(base.output_token_ids))


# ---- API server ------------------------------------------------------------

class StubTokenizer:
    eos_token_id = 0

    def encode(self, text):
        return [min(ord(c), 120) for c in text][:64]

    def decode(self, ids, skip_special_tokens=False):
        return "".join(chr(max(32, i % 127)) for i in ids)

    def apply_chat_template(self, messages, add_generation_prompt=True,
                            **kw):
        return self.encode(" ".join(str(m.get("content", ""))
                                    for m in messages) or "hi")


@pytest.fixture(scope="module")
def server(ckpt):
    from gllm_tpu.entrypoints.api_server import serve
    model_dir, _ = ckpt
    llm = make_llm(model_dir)
    llm.tokenizer = StubTokenizer()
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield port
    httpd.shutdown()
    httpd.state.engine.shutdown()


def request(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = json.loads(resp.read())
    conn.close()
    return resp.status, data


def test_api_completion_logprobs(server):
    status, d = request(server, "/v1/completions", {
        "prompt": [5, 17, 93], "max_tokens": 4, "temperature": 0,
        "ignore_eos": True, "logprobs": 2})
    assert status == 200, d
    lp = d["choices"][0]["logprobs"]
    assert lp is not None
    assert len(lp["tokens"]) == 4
    assert all(isinstance(v, float) for v in lp["token_logprobs"])
    assert all(len(t) == 2 for t in lp["top_logprobs"])


def test_api_chat_logprobs(server):
    status, d = request(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hey"}],
        "max_tokens": 3, "temperature": 0, "ignore_eos": True,
        "logprobs": True, "top_logprobs": 2})
    assert status == 200, d
    content = d["choices"][0]["logprobs"]["content"]
    assert len(content) == 3
    assert all(len(c["top_logprobs"]) == 2 for c in content)


def test_api_n_choices(server):
    status, d = request(server, "/v1/completions", {
        "prompt": [5, 17, 93], "max_tokens": 4, "temperature": 0,
        "ignore_eos": True, "n": 3})
    assert status == 200, d
    assert len(d["choices"]) == 3
    assert [c["index"] for c in d["choices"]] == [0, 1, 2]
    # greedy → all choices identical
    assert len({c["text"] for c in d["choices"]}) == 1
    assert d["usage"]["completion_tokens"] == 12


def test_api_best_of(server):
    status, d = request(server, "/v1/completions", {
        "prompt": [5, 17, 93], "max_tokens": 4, "temperature": 1.0,
        "ignore_eos": True, "n": 1, "best_of": 3})
    assert status == 200, d
    assert len(d["choices"]) == 1


def test_api_echo_prompt_logprobs(server):
    status, d = request(server, "/v1/completions", {
        "prompt": [5, 17, 93, 41], "max_tokens": 2, "temperature": 0,
        "ignore_eos": True, "logprobs": 1, "prompt_logprobs": 1,
        "echo": True})
    assert status == 200, d
    lp = d["choices"][0]["logprobs"]
    assert len(lp["tokens"]) == 6            # 4 prompt + 2 output
    assert lp["token_logprobs"][0] is None   # first prompt position
    assert all(isinstance(v, float) for v in lp["token_logprobs"][1:])


def test_api_stop_string(server):
    # find what greedy produces, then stop on a substring of it
    _, base = request(server, "/v1/completions", {
        "prompt": [5, 17, 93], "max_tokens": 8, "temperature": 0,
        "ignore_eos": True})
    text = base["choices"][0]["text"]
    assert len(text) >= 3
    stop = text[1:3]
    _, d = request(server, "/v1/completions", {
        "prompt": [5, 17, 93], "max_tokens": 8, "temperature": 0,
        "ignore_eos": True, "stop": stop})
    got = d["choices"][0]["text"]
    assert stop not in got
    assert d["choices"][0]["finish_reason"] == "stop"
    assert got == text[:text.find(stop)]


def test_api_invalid_params(server):
    status, d = request(server, "/v1/completions", {
        "prompt": [1], "n": 2, "best_of": 1})
    assert status == 400
    status, d = request(server, "/v1/completions", {
        "prompt": [1], "presence_penalty": 5.0})
    assert status == 400
