"""DeepSeek V2/V3 (MLA + DeepSeekMoE): HF greedy-equivalence oracles."""

import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams

BASE = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=3,
    num_attention_heads=4, num_key_value_heads=4, intermediate_size=96,
    max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0,
    # MLA geometry
    kv_lora_rank=32, qk_nope_head_dim=16, qk_rope_head_dim=8,
    v_head_dim=16,
    # MoE: 1 dense layer then MoE layers
    n_routed_experts=8, num_experts_per_tok=2, moe_intermediate_size=32,
    first_k_dense_replace=1, n_shared_experts=1, moe_layer_freq=1,
    routed_scaling_factor=1.5,
)


def make_ckpt(arch, tmpdir, **over):
    torch.manual_seed(31)
    cfg_kw = {**BASE, **over}
    if arch == "DeepseekV2ForCausalLM":
        from transformers import DeepseekV2Config, DeepseekV2ForCausalLM
        cfg = DeepseekV2Config(**cfg_kw)
        model = DeepseekV2ForCausalLM(cfg)
    else:
        from transformers import DeepseekV3Config, DeepseekV3ForCausalLM
        cfg = DeepseekV3Config(**cfg_kw)
        model = DeepseekV3ForCausalLM(cfg)
    model.eval()
    model.save_pretrained(tmpdir, safe_serialization=True)
    return model


def hf_greedy(model, prompt_ids, n):
    ids = list(prompt_ids)
    with torch.no_grad():
        for _ in range(n):
            logits = model(torch.tensor([ids])).logits[0, -1]
            ids.append(int(logits.argmax()))
    return ids[len(prompt_ids):]


def ours(model_dir, prompts, n):
    cfg = EngineConfig(model=model_dir, dtype="float32", max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg)
    return [o.output_token_ids for o in llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=n,
                                       ignore_eos=True))]


@pytest.mark.parametrize("q_lora", [None, 48])
def test_deepseek_v2_greedy_equivalence(tmp_path, q_lora):
    hf = make_ckpt("DeepseekV2ForCausalLM", tmp_path, q_lora_rank=q_lora,
                   topk_method="greedy", n_group=None, topk_group=None,
                   scoring_func="softmax", norm_topk_prob=False)
    prompts = [[7, 3, 56, 21], [99, 14, 2]]
    got = ours(str(tmp_path), prompts, 8)
    for p, g in zip(prompts, got):
        assert g == hf_greedy(hf, p, 8), (p, g)


def test_deepseek_v3_greedy_equivalence(tmp_path):
    hf = make_ckpt("DeepseekV3ForCausalLM", tmp_path, q_lora_rank=48,
                   n_group=4, topk_group=2, topk_method="noaux_tc",
                   scoring_func="sigmoid", norm_topk_prob=True)
    # give the correction bias real values so the noaux_tc path is exercised
    with torch.no_grad():
        for layer in hf.model.layers[1:]:
            layer.mlp.gate.e_score_correction_bias.add_(
                torch.randn_like(layer.mlp.gate.e_score_correction_bias)
                * 0.1)
    hf.save_pretrained(tmp_path, safe_serialization=True)
    prompts = [[5, 9, 23, 41, 77], [100, 90]]
    got = ours(str(tmp_path), prompts, 8)
    for p, g in zip(prompts, got):
        assert g == hf_greedy(hf, p, 8), (p, g)


def test_deepseek_v2_yarn_rope(tmp_path):
    scaling = {"rope_type": "yarn", "factor": 2.0, "beta_fast": 32,
               "beta_slow": 1, "mscale": 0.707, "mscale_all_dim": 0.707,
               "original_max_position_embeddings": 64}
    hf = make_ckpt("DeepseekV2ForCausalLM", tmp_path, q_lora_rank=None,
                   topk_method="greedy", n_group=None, topk_group=None,
                   scoring_func="softmax", norm_topk_prob=False,
                   rope_scaling=scaling)
    prompts = [[9, 8, 7, 6, 5, 4, 3, 2]]
    got = ours(str(tmp_path), prompts, 6)
    assert got[0] == hf_greedy(hf, prompts[0], 6)


def test_mla_pallas_matches_xla(tmp_path):
    """MLA routed through the Pallas kernels (shared latent KV, v_dim <
    head_dim) must reproduce the xla-impl greedy output end-to-end."""
    make_ckpt("DeepseekV2ForCausalLM", tmp_path, q_lora_rank=None,
              topk_method="greedy", n_group=None, topk_group=None,
              scoring_func="softmax", norm_topk_prob=False)
    prompts = [[7, 3, 56, 21, 8, 4, 90], [99, 14, 2]]

    def run(impl):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=128, attention_impl=impl,
                           cache=CacheConfig(page_size=4, num_pages=128))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=prompts,
            sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                           ignore_eos=True))]

    assert run("pallas") == run("xla")
