"""Mixture-of-Experts decoder family (Mixtral / Qwen2-MoE / Qwen3-MoE).

TPU-native re-design of the reference's FusedMoE stack
(/root/reference/gllm/layers/moe/fused_moe_triton/layer.py:553-730 and the
986-LoC Triton grouped GEMM in fused_moe.py): instead of a hand-written
sorted-scatter GEMM with device-specific autotune tables, tokens are sorted
by expert and pushed through ``jax.lax.ragged_dot`` — XLA's native grouped
matmul, which tiles onto the MXU per expert group. Routing
(softmax → top-k → optional renorm) matches the reference's
``select_experts`` dispatch (layers/moe/topk.py).

Expert parallelism: expert-major weights [E, ...] shard over the ``tp`` mesh
axis (the reference's EP group equals the whole dp×tp stage,
dist_utils.py:81-86); GSPMD turns the ragged compute into
gather/psum collectives. Shared experts (Qwen2-MoE) run dense beside the
routed path with a sigmoid gate.

Layer structure reuses the dense attention block (gllm_tpu/models/dense.py);
only the MLP half differs.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from gllm_tpu.batching import StepBatch
from gllm_tpu.models import dense
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.models.dense import KVCache
from gllm_tpu.ops import silu_and_mul
from gllm_tpu.ops.quant import deq, qmm, qragged_dot

Params = dict


def moe_layer_mask(cfg: ModelConfig) -> Tuple[bool, ...]:
    """Per-stage-layer sparse/dense flag, HF Qwen2/Qwen3-MoE semantics:
    a layer runs the routed-expert MLP unless it is listed in
    ``mlp_only_layers`` or falls off the ``decoder_sparse_step`` stride
    ((layer_idx + 1) % step != 0)."""
    first, last = cfg.stage_layers
    step = cfg.decoder_sparse_step
    return tuple(
        i not in cfg.mlp_only_layers
        and (step <= 1 or (i + 1) % step == 0)
        for i in range(first, last))


def select_experts(router_logits: jnp.ndarray, top_k: int,
                   norm_topk_prob: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """softmax → top-k → optional renormalize (HF/reference semantics).

    Returns (weights [T, K] f32, ids [T, K] i32).
    """
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    weights, ids = jax.lax.top_k(probs, top_k)
    if norm_topk_prob:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, ids.astype(jnp.int32)


def moe_mlp(lp: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Routed-expert MLP over a flat token batch x: [T, H]."""
    T, H = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok

    router_logits = x.astype(jnp.float32) @ lp["router"].astype(jnp.float32)
    weights, ids = select_experts(router_logits, K, cfg.norm_topk_prob)

    if cfg.moe_force_dense:
        # Under vmap (DP replicas in one program) lax.ragged_dot's batch
        # rule can't handle the carried-weight layout — fall back to a
        # masked dense loop over experts. (The dp Pallas path runs under
        # shard_map manual over dp, where the ragged GEMM works natively.)
        w_gate = deq(lp["w_gate"], x.dtype)
        w_up = deq(lp["w_up"], x.dtype)
        w_down = deq(lp["w_down"], x.dtype)
        combined = jnp.zeros((T, H), jnp.float32)
        wf = weights.astype(jnp.float32)
        for e in range(E):
            ye = qmm(silu_and_mul(jnp.concatenate(
                [qmm(x, w_gate[e]), qmm(x, w_up[e])],
                axis=-1)), w_down[e]).astype(jnp.float32)
            w_e = jnp.sum(jnp.where(ids == e, wf, 0.0), axis=-1)
            combined = combined + ye * w_e[:, None]
        combined = combined.astype(x.dtype)
    else:
        # Sort token-replicas by expert id → contiguous per-expert groups.
        # Quantized stacks go through qragged_dot: W8A8 experts run the
        # int8 MXU grouped GEMM with epilogue scales (no dequantized
        # stack materialized); weight-only stacks cast in the transient.
        flat_ids = ids.reshape(-1)                      # [T*K]
        sort_idx = jnp.argsort(flat_ids)                # [T*K]
        token_of = sort_idx // K                        # source token rows
        xs = x[token_of]                                # [T*K, H]
        sorted_eids = flat_ids[sort_idx]                # [T*K]
        group_sizes = jnp.bincount(flat_ids, length=E).astype(jnp.int32)

        gate = qragged_dot(xs, lp["w_gate"], group_sizes, sorted_eids)
        up = qragged_dot(xs, lp["w_up"], group_sizes, sorted_eids)
        act = silu_and_mul(jnp.concatenate([gate, up], axis=-1))
        out = qragged_dot(act, lp["w_down"], group_sizes,
                          sorted_eids)                  # [T*K, H]

        # Weight by routing prob and scatter-add back to token rows.
        w_sorted = weights.reshape(-1)[sort_idx][:, None].astype(out.dtype)
        combined = jnp.zeros((T, H), out.dtype).at[token_of].add(
            out * w_sorted)

    if cfg.shared_expert_intermediate_size:
        sg = qmm(x, lp["shared_gate_proj"])
        su = qmm(x, lp["shared_up_proj"])
        shared = qmm(silu_and_mul(jnp.concatenate([sg, su], axis=-1)),
                     lp["shared_down_proj"])
        gate_logit = x @ lp["shared_expert_gate"]       # [T, 1]
        shared = shared * jax.nn.sigmoid(
            gate_logit.astype(jnp.float32)).astype(shared.dtype)
        combined = combined + shared
    return combined.astype(x.dtype)


# ---------------------------------------------------------------------------
# Params / forward (mirrors dense.py structure with MoE MLPs)
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> Params:
    params = dense.init_params(cfg, seed=seed, dtype=dtype)
    L = cfg.num_stage_layers
    H, E = cfg.hidden_size, cfg.num_experts
    I = cfg.moe_intermediate_size or cfg.intermediate_size
    key = jax.random.key(seed + 1)
    ks = iter(jax.random.split(key, 8))

    def w(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32)
                * scale).astype(dtype)

    lp = params["layers"]
    mask = moe_layer_mask(cfg)
    if all(mask):
        # pure-MoE stack: no dense MLP leaves at all (the common case —
        # don't carry dead [L, H, I] stacks)
        for name in ("gate_proj", "up_proj", "down_proj"):
            del lp[name]
    else:
        # Mixed dense/sparse stack (Qwen2/Qwen3-MoE mlp_only_layers /
        # decoder_sparse_step): the layer scan needs structurally uniform
        # per-layer params, so BOTH MLP variants are stacked for every
        # layer and a per-layer flag routes between them at run time
        # (lax.cond in forward — only the live branch executes). The
        # off-variant rows are dead weight; real mixed checkpoints keep
        # them rare (a handful of dense layers), so the overhead is
        # bounded and the alternative — heterogeneous scan segments —
        # would fork every KV-offset path in dense.forward.
        lp["moe_mask"] = jnp.asarray(mask, jnp.bool_)
    scale = H ** -0.5
    lp["router"] = w(next(ks), (L, H, E), scale)
    lp["w_gate"] = w(next(ks), (L, E, H, I), scale)
    lp["w_up"] = w(next(ks), (L, E, H, I), scale)
    lp["w_down"] = w(next(ks), (L, E, I, H), I ** -0.5)
    if cfg.shared_expert_intermediate_size:
        SI = cfg.shared_expert_intermediate_size
        lp["shared_gate_proj"] = w(next(ks), (L, H, SI), scale)
        lp["shared_up_proj"] = w(next(ks), (L, H, SI), scale)
        lp["shared_down_proj"] = w(next(ks), (L, SI, H), SI ** -0.5)
        lp["shared_expert_gate"] = w(next(ks), (L, H, 1), scale)
    return params


def forward(params, kv: KVCache, batch: StepBatch, cfg: ModelConfig, *,
            cos_sin, attn_impl: str = "xla", max_q_len: int,
            hidden_in=None, residual_in=None):
    if all(moe_layer_mask(cfg)):
        mlp_fn = lambda lp, x: moe_mlp(lp, x, cfg)   # noqa: E731
    else:
        # mixed stack: the scanned per-layer flag picks routed-expert vs
        # dense MLP; under scan only the selected branch runs (cond
        # lowers to a real branch — vmap'd DP replicas degrade to
        # select, which is still correct, just runs both)
        def mlp_fn(lp, x):
            return jax.lax.cond(
                lp["moe_mask"],
                lambda v: moe_mlp(lp, v, cfg),
                lambda v: dense._mlp(lp, v).astype(v.dtype), x)
    return dense.forward(
        params, kv, batch, cfg, cos_sin=cos_sin, attn_impl=attn_impl,
        max_q_len=max_q_len, hidden_in=hidden_in, residual_in=residual_in,
        mlp_fn=mlp_fn)


compute_logits = dense.compute_logits
make_rope_table = dense.make_rope_table
init_kv_cache = dense.init_kv_cache
