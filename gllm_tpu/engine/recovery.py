"""Self-healing engine: supervised in-process recovery + request replay.

The reference gLLM column drivers assume *process* supervision — a
crashed worker is killed and restarted from outside, and its peers
re-queue. PR 7 gave the single-controller engine the first half of that
model (quarantine → latched unhealthy, clean handoff), but no supervisor
exists in-tree, so a latched replica stays a brick until a human
restarts the process. This module is the missing supervisor, moved
in-process where it can exploit two things an external restart cannot:

- the warm lower tiers survive the rebuild for free — the disk prefix
  tier re-adopts its pages at construction (kvstore/disk.py) and the
  persistent XLA compilation cache replays every compiled program
  (engine/llm.py), so a rebuilt engine is seconds from serving, not
  minutes;
- the request streams survive too: every accepted request journals its
  immutable submission (prompt / sampling params / seed) plus the
  output tokens actually DELIVERED to its stream, so retry-safe
  requests (seeded or greedy) resubmit onto the rebuilt engine and
  continue from their committed prefix — the stream the client holds
  never drops a token and never hangs.

Three pieces:

``RequestJournal``
    Per-open-request log of the immutable submission + committed output
    token ids (appended as chunks are DELIVERED, i.e. at collect — a
    token computed but never collected is not committed). Bounded by
    the number of resident requests; entries drop at finish.

``JournalEntry.unsafe_reason``
    The replay-safety rule (docs/robustness.md#recovery-lifecycle):
    a request replays iff its continuation is deterministic from the
    committed prefix — greedy (argmax) or seeded (per-row sampling keys
    are a pure function of ``(seed, out_step)``, and replay preserves
    ``out_step`` by re-submitting ``prompt + committed`` with the
    ORIGINAL prompt_len). Unseeded sampled requests fold the engine
    step key (restarts with the runner) → unsafe. Multimodal / disagg
    state is not journaled → unsafe. Stop strings / prompt_logprobs
    carry detok-boundary state → unsafe (conservative). A partial
    tool-call delta already streamed vetoes replay via
    ``RequestHandle.replay_safe`` (the api_server clears it).

``EngineSupervisor``
    Owns the rebuild ladder on its own thread: trigger → tear down the
    old engine (quarantine + tier close; a WEDGED engine thread is
    abandoned behind a generation bump) → factory() a replacement with
    bounded exponential backoff (``rebuild_fail`` injectable) → replay
    the journal → flip /readyz back to ready. K failed rebuilds within
    ``rebuild_window_s`` latch the CRASH-LOOP state — today's permanent
    unhealthy is the bounded fallback, never an infinite rebuild loop.

No jax imports: host bookkeeping only.
"""

from __future__ import annotations

import copy
import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from gllm_tpu import faults
from gllm_tpu.obs import metrics as obs
from gllm_tpu.obs.steptrace import TRACE

logger = logging.getLogger(__name__)

_M_REBUILDS = obs.counter(
    "gllm_engine_rebuilds_total",
    "supervised in-process engine rebuild attempts by outcome "
    "(ok|fail)", ("outcome",))
_M_RECOVERY_S = obs.histogram(
    "gllm_engine_recovery_seconds",
    "latch-to-ready wall time of a supervised in-process recovery")
_M_REPLAYED = obs.counter(
    "gllm_requests_replayed_total",
    "journaled requests at recovery by outcome (replayed = resubmitted "
    "onto the rebuilt engine; unsafe = terminal error chunk with "
    "Retry-After; expired = deadline passed during the rebuild; "
    "aborted = client went away mid-recovery)", ("outcome",))
_M_RECOVERING = obs.gauge(
    "gllm_engine_recovering",
    "1 while a supervised rebuild is in progress (/readyz 503 "
    "'recovering'); 0 otherwise")


@dataclasses.dataclass
class JournalEntry:
    """Immutable submission + committed-delivery state of one open
    request. ``committed`` holds the output token ids whose chunks were
    DELIVERED to the stream; replay resubmits ``prompt + committed``
    with the original prompt_len so max_tokens / min_tokens / penalties
    / seeded out_step all continue exactly where the stream stopped."""

    seq_id: int
    prompt: Tuple[int, ...]
    sampling: object                       # SamplingParams deep copy
    mm: bool = False
    disagg: bool = False
    target_dp: Optional[int] = None
    committed: List[int] = dataclasses.field(default_factory=list)
    # filled at recovery-partition time
    handle: object = None
    deadline: Optional[float] = None       # absolute monotonic
    aborted: bool = False                  # client left mid-recovery

    def unsafe_reason(self) -> Optional[str]:
        """None = retry-safe; otherwise why the request cannot replay
        with a byte-identical continuation."""
        sp = self.sampling
        if self.mm:
            return "multimodal state is not journaled"
        if self.disagg:
            return "disagg requests are not journaled"
        if not (sp.temperature == 0.0 or sp.seed is not None):
            return ("unseeded sampling folds the engine step key — the "
                    "continuation is not deterministic across a rebuild")
        if sp.stop:
            return "stop strings may span the crash boundary"
        if sp.prompt_logprobs is not None:
            return "prompt logprobs are not journaled"
        h = self.handle
        if h is not None and not getattr(h, "replay_safe", True):
            return "a partial tool-call stream was already delivered"
        return None


class RequestJournal:
    """Thread-safe seq_id → JournalEntry map. Writes come from the
    submit path (record) and the engine thread's delivery loop
    (commit); the supervisor snapshots + rebinds at recovery."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[int, JournalEntry] = {}

    def record(self, seq_id: int, token_ids, sampling_params, *,
               mm: bool = False, disagg: bool = False,
               target_dp: Optional[int] = None) -> None:
        entry = JournalEntry(
            seq_id=seq_id, prompt=tuple(int(t) for t in token_ids),
            sampling=copy.deepcopy(sampling_params), mm=mm,
            disagg=disagg, target_dp=target_dp)
        with self._lock:
            self._entries[seq_id] = entry

    def commit(self, seq_id: int, token_id: int) -> None:
        with self._lock:
            e = self._entries.get(seq_id)
            if e is not None:
                e.committed.append(int(token_id))

    def pop(self, seq_id: int) -> Optional[JournalEntry]:
        with self._lock:
            return self._entries.pop(seq_id, None)

    def adopt(self, new_seq_id: int, entry: JournalEntry) -> None:
        """Re-key a replayed entry under its rebuilt-engine seq id so a
        SECOND crash replays the same request again (committed tokens
        accumulated so far included)."""
        entry.seq_id = new_seq_id
        with self._lock:
            self._entries[new_seq_id] = entry

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class EngineSupervisor:
    """In-process analogue of the external process supervisor the
    reference design assumes. One per ServingEngine; owns the rebuild
    thread and the crash-loop accounting."""

    def __init__(self, serving, factory: Callable[[], object], *,
                 max_rebuilds: int = 3, rebuild_window_s: float = 300.0,
                 backoff_s: float = 0.25, backoff_max_s: float = 30.0):
        self.serving = serving
        self.factory = factory
        self.max_rebuilds = max(1, int(max_rebuilds))
        self.rebuild_window_s = float(rebuild_window_s)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.rebuilds_ok = 0
        self.rebuilds_failed = 0
        self.recoveries = 0
        self.last_recovery_s: Optional[float] = None
        self._fail_times: deque = deque()     # monotonic failed-rebuild
        self._recovery_times: deque = deque()  # monotonic completed
        self._consecutive_fails = 0
        self._trigger = threading.Event()
        self._why = ("", "")
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="gllm-supervisor")
        self._thread.start()

    # ---- crash-loop accounting (any thread) -------------------------------

    def _recent(self, dq: deque) -> int:
        now = time.monotonic()
        while dq and now - dq[0] > self.rebuild_window_s:
            dq.popleft()
        return len(dq)

    def _recent_failures(self) -> int:
        return self._recent(self._fail_times)

    def may_recover(self) -> bool:
        """False once the crash-loop budget is spent — the caller falls
        through to the permanent latch. BOTH failed rebuilds and
        COMPLETED recoveries count against the window budget: a replica
        that keeps latching right after every successful rebuild (e.g.
        a hard-stall threshold below the post-rebuild compile time) is
        crash-looping just as surely as one whose factory raises, and
        an unbounded recover-latch-recover storm would otherwise never
        terminate."""
        return (not self._stop
                and self._recent(self._fail_times) < self.max_rebuilds
                and self._recent(self._recovery_times)
                < self.max_rebuilds)

    def eta_s(self) -> float:
        """Retry-After estimate for /readyz while recovering: the next
        rebuild attempt's backoff (plus one attempt's worth of build)."""
        n = max(0, self._consecutive_fails)
        if n == 0:
            return max(1.0, self.backoff_s)
        return max(1.0, min(self.backoff_max_s,
                            self.backoff_s * (2 ** (n - 1))))

    # ---- trigger / shutdown ------------------------------------------------

    def trigger(self, cls: str, why: str) -> None:
        self._why = (cls, why)
        self._trigger.set()

    def close(self) -> None:
        self._stop = True
        self._trigger.set()
        self._thread.join(timeout=5)

    # ---- the rebuild ladder (supervisor thread) ---------------------------

    def _loop(self) -> None:
        while not self._stop:
            self._trigger.wait(timeout=0.2)
            if self._stop:
                return
            if not self._trigger.is_set():
                continue
            self._trigger.clear()
            try:
                self._recover(*self._why)
            except Exception:  # pragma: no cover - last-resort contain
                logger.exception("supervisor recovery pass died")
                self.serving._crash_loop_latch(
                    "supervisor recovery pass raised")

    def _recover(self, cls: str, why: str) -> None:
        s = self.serving
        t_begin = time.monotonic()
        logger.warning("engine recovery begins (%s): %s", cls, why)

        # 1. Tear down / abandon the old engine. The generation bump in
        # _maybe_recover already superseded the loop; a cooperative
        # thread exits within one pass, a WEDGED one (hard stall) is
        # abandoned — its gen checks keep it from ever touching shared
        # state again, and the old LLM goes to GC with it.
        # A cooperative thread exits within one loop pass; only a
        # wedged one needs the timeout — and a hard-stall trigger has
        # ALREADY watched the heartbeat go stale past the hard
        # threshold, so waiting longer just delays recovery.
        old_thread, old_llm = s._thread, s.llm
        old_thread.join(timeout=1.0 if cls == "stall" else 5.0)
        wedged = old_thread.is_alive()
        if wedged:
            logger.error("old engine thread still wedged after 5s — "
                         "abandoning it (generation %d)", s._gen)
        else:
            try:
                old_llm.quarantine_step_failure(everything=True)
            except Exception:
                logger.exception("old-engine quarantine failed (state "
                                 "is discarded anyway)")
        try:
            # releases the prefix-peer serve port + drains disk writes
            # so the successor can re-adopt the tier; touches only the
            # kvstore plane, safe even behind a wedged dispatch
            old_llm.close()
        except Exception:
            logger.exception("old-engine close failed")

        # 2. Partition the open streams: retry-safe entries wait for the
        # rebuilt engine, everything else ends NOW with a terminal error
        # chunk carrying Retry-After.
        entries = s._partition_for_replay()

        # 3. Rebuild with bounded exponential backoff; K failures within
        # the window latch the crash loop.
        while not self._stop:
            if not self.may_recover():
                TRACE.record("recovery", phase="crash_loop",
                             failed_rebuilds=self._recent_failures())
                s._crash_loop_latch(
                    f"{self._recent_failures()} failed rebuilds within "
                    f"{self.rebuild_window_s:.0f}s (last trigger: {why})")
                return
            if self._consecutive_fails:
                delay = min(self.backoff_max_s, self.backoff_s *
                            (2 ** (self._consecutive_fails - 1)))
                logger.warning("rebuild backoff %.2fs (attempt %d)",
                               delay, self._consecutive_fails + 1)
                deadline = time.monotonic() + delay
                while not self._stop and time.monotonic() < deadline:
                    time.sleep(min(0.05, delay))
            if self._stop:
                return
            try:
                faults.FAULTS.maybe_raise("rebuild_fail")
                t0 = time.monotonic()
                new_llm = self.factory()
            except Exception as e:
                self.rebuilds_failed += 1
                self._consecutive_fails += 1
                self._fail_times.append(time.monotonic())
                _M_REBUILDS.inc(outcome="fail")
                TRACE.record("recovery", phase="rebuild_fail",
                             error=f"{type(e).__name__}: {e}"[:200])
                logger.exception("engine rebuild failed")
                continue
            self.rebuilds_ok += 1
            self._consecutive_fails = 0
            _M_REBUILDS.inc(outcome="ok")
            logger.warning("engine rebuilt in %.2fs",
                           time.monotonic() - t0)
            if self._stop:
                # shutdown raced the rebuild: it already closed the
                # parked handles — never adopt/replay after stop
                return
            replayed, dropped = s._adopt_llm(new_llm, entries)
            self.recoveries += 1
            self._recovery_times.append(time.monotonic())
            self.last_recovery_s = time.monotonic() - t_begin
            _M_RECOVERY_S.observe(self.last_recovery_s)
            TRACE.record("recovery", phase="ready",
                         recovery_s=round(self.last_recovery_s, 3),
                         replayed=replayed, dropped=dropped)
            logger.warning(
                "engine recovered in %.2fs (%d requests replayed, %d "
                "dropped)", self.last_recovery_s, replayed, dropped)
            return
