"""Device-side ops.

The reference funnels every kernel call through one dispatch point
(/root/reference/gllm/_custom_ops.py:1-10) so backends can be swapped. Here the
same role is played by this package: elementwise/norm/rope/sampling ops are
plain jnp (XLA fuses them into neighboring matmuls); paged attention has an
XLA reference implementation (runs everywhere, used as the test oracle) and a
Pallas TPU kernel, selected via :func:`gllm_tpu.ops.attention.paged_attention`.
"""

from gllm_tpu.ops.layers import (fused_add_rms_norm, rms_norm, silu_and_mul,
                                 gelu_and_mul)
from gllm_tpu.ops.rope import apply_rope, compute_rope_cos_sin
from gllm_tpu.ops.kv_cache import write_kv, write_kv_quant
from gllm_tpu.ops.attention import paged_attention

__all__ = [
    "apply_rope",
    "compute_rope_cos_sin",
    "fused_add_rms_norm",
    "gelu_and_mul",
    "paged_attention",
    "rms_norm",
    "silu_and_mul",
    "write_kv",
    "write_kv_quant",
]
