"""Engine frontends: offline LLM and (async) serving engine."""
