"""Weight-only quantization: numerics, memory, and engine integration."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.ops.quant import (Quantized, param_bytes, qmm,
                                quantize_params, quantize_weight)
from gllm_tpu.sampling_params import SamplingParams


def test_quantize_roundtrip_error_small():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 96)).astype(np.float32))
    qw = quantize_weight(w)
    deq = qw.q.astype(jnp.float32) * qw.scale
    err = np.abs(np.asarray(deq - w)).max()
    scale_max = float(np.asarray(qw.scale).max())
    assert err <= scale_max  # within one quantization step
    assert qw.q.dtype == jnp.int8


def test_qmm_matches_dense_within_tolerance():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 64)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((64, 32)).astype(np.float32))
    exact = x @ w
    approx = qmm(x, quantize_weight(w))
    rel = np.abs(np.asarray(approx - exact)).max() / \
        np.abs(np.asarray(exact)).max()
    assert rel < 0.02


def test_quantize_params_halves_matmul_bytes():
    from gllm_tpu.models import dense
    from gllm_tpu.models.config import ModelConfig
    cfg = ModelConfig(architecture="LlamaForCausalLM", vocab_size=256,
                      hidden_size=64, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, intermediate_size=128,
                      max_position=128)
    params = dense.init_params(cfg, dtype=jnp.bfloat16)
    qparams = quantize_params(params)
    assert param_bytes(qparams) < param_bytes(params)
    assert isinstance(qparams["layers"]["q_proj"], Quantized)
    assert not isinstance(qparams["layers"]["input_norm"], Quantized)
    assert not isinstance(qparams["embed"], Quantized)


@pytest.mark.parametrize("quant", ["int8", "fp8"])
def test_engine_int8_outputs_close_to_full_precision(tmp_path, quant):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(3)
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=128, eos_token_id=0,
        attention_bias=False)).save_pretrained(tmp_path,
                                               safe_serialization=True)

    def run(q):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=64, quantization=q,
                           cache=CacheConfig(page_size=4, num_pages=64))
        llm = LLM(config=cfg)
        return llm.generate(
            prompt_token_ids=[[5, 9, 23, 41]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                           ignore_eos=True))[0]

    full = run(None)
    quantized = run(quant)
    # greedy argmax is robust to small perturbations on a tiny random
    # model for at least the first tokens
    assert quantized.output_token_ids[:2] == full.output_token_ids[:2]
    assert len(quantized.output_token_ids) == 8


def test_deepseek_int8_quantized_runs(tmp_path):
    """DeepSeek leaves are in QUANT_LEAVES — the model must route them
    through qmm (regression for the trace-time crash)."""
    from transformers import DeepseekV2Config, DeepseekV2ForCausalLM
    torch.manual_seed(5)
    DeepseekV2ForCausalLM(DeepseekV2Config(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=96,
        max_position_embeddings=128, eos_token_id=0,
        kv_lora_rank=32, q_lora_rank=48, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16,
        n_routed_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        first_k_dense_replace=1, n_shared_experts=1,
        topk_method="greedy", n_group=None, topk_group=None,
        norm_topk_prob=False)).save_pretrained(tmp_path,
                                               safe_serialization=True)
    cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                       max_model_len=64, quantization="int8",
                       cache=CacheConfig(page_size=4, num_pages=64))
    out = LLM(config=cfg).generate(
        prompt_token_ids=[[5, 9, 23]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=4,
                                       ignore_eos=True))[0]
    assert len(out.output_token_ids) == 4


def test_bad_quantization_value_rejected():
    with pytest.raises(ValueError, match="quantization"):
        EngineConfig(quantization="int3").validate()


def test_int4_pack_roundtrip():
    import numpy as np

    from gllm_tpu.ops.quant import deq, quantize_weight_int4
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((2, 16, 8)).astype(np.float32))
    q4 = quantize_weight_int4(w)
    assert q4.q.shape == (2, 8, 8)               # packed in-axis
    back = np.asarray(deq(q4, jnp.float32))
    # int4 per-output-channel: max error bounded by scale/2
    scale = np.asarray(q4.scale)
    assert np.all(np.abs(back - np.asarray(w)) <= scale * 0.51 + 1e-6)


@pytest.mark.parametrize("quant", ["int4", "w8a8"])
def test_engine_int4_w8a8_close_to_full_precision(tmp_path, quant):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(3)
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=128, eos_token_id=0,
        attention_bias=False)).save_pretrained(tmp_path,
                                               safe_serialization=True)

    def run(q):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=64, quantization=q,
                           cache=CacheConfig(page_size=4, num_pages=64))
        return LLM(config=cfg).generate(
            prompt_token_ids=[[5, 9, 23, 41]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                           ignore_eos=True))[0]

    full = run(None)
    quantized = run(quant)
    assert quantized.output_token_ids[:2] == full.output_token_ids[:2]
    assert len(quantized.output_token_ids) == 8


def test_moe_experts_are_quantized_and_close(tmp_path):
    """Routed expert stacks quantize too (the reference's weight-only path
    skipped them — VERDICT r1 item 10)."""
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    from gllm_tpu.ops.quant import Quantized, param_bytes
    torch.manual_seed(9)
    Qwen2MoeForCausalLM(Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        moe_intermediate_size=32, shared_expert_intermediate_size=48,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=128, eos_token_id=0)).save_pretrained(
        tmp_path, safe_serialization=True)

    def make(q):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=64, quantization=q,
                           cache=CacheConfig(page_size=4, num_pages=64))
        return LLM(config=cfg)

    llm_q = make("int8")
    assert isinstance(llm_q.runner.params["layers"]["w_gate"], Quantized)
    llm_f = make(None)
    assert param_bytes(llm_q.runner.params) < \
        0.5 * param_bytes(llm_f.runner.params)
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    a = llm_q.generate(prompt_token_ids=[[5, 9, 23]],
                       sampling_params=sp)[0]
    b = llm_f.generate(prompt_token_ids=[[5, 9, 23]],
                       sampling_params=sp)[0]
    assert a.output_token_ids[:2] == b.output_token_ids[:2]


def test_qragged_dot_w8a8_matches_per_expert_qmm():
    """The int8 MXU grouped GEMM (epilogue scales, no dequantized stack)
    must agree with running each expert's QuantizedW8A8 qmm separately —
    same activation-quant semantics, grouped in one ragged call
    (reference fused quantized MoE GEMM, fused_moe_triton/layer.py)."""
    import jax.numpy as jnp

    from gllm_tpu.ops.quant import (QuantizedW8A8, qmm, qragged_dot,
                                    quantize_weight)
    rng = np.random.default_rng(0)
    E, K, N, R = 3, 32, 16, 10
    w = jnp.asarray(rng.normal(size=(E, K, N)), jnp.float32)
    qz = quantize_weight(w, jnp.int8)
    wq = QuantizedW8A8(qz.q, qz.scale)
    xs = jnp.asarray(rng.normal(size=(R, K)), jnp.float32)
    sizes = [4, 0, 6]
    group_sizes = jnp.asarray(sizes, jnp.int32)
    eids = jnp.asarray(sum(([e] * n for e, n in enumerate(sizes)), []),
                       jnp.int32)

    out = qragged_dot(xs, wq, group_sizes, eids)
    start = 0
    for e, n in enumerate(sizes):
        if n == 0:
            continue
        ref = qmm(xs[start:start + n],
                  QuantizedW8A8(wq.q[e], wq.scale[e]))
        np.testing.assert_allclose(np.asarray(out[start:start + n]),
                                   np.asarray(ref), rtol=1e-5, atol=1e-5)
        start += n


def test_moe_w8a8_no_dequantized_stack_and_close(tmp_path):
    """W8A8 MoE: the expert hot path runs the int8 grouped GEMM (no deq)
    and engine outputs stay close to full precision."""
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    from gllm_tpu.ops.quant import QuantizedW8A8
    torch.manual_seed(11)
    Qwen2MoeForCausalLM(Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        moe_intermediate_size=32, shared_expert_intermediate_size=48,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=128, eos_token_id=0)).save_pretrained(
        tmp_path, safe_serialization=True)

    def make(q):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=64, quantization=q,
                           cache=CacheConfig(page_size=4, num_pages=64))
        return LLM(config=cfg)

    llm_q = make("w8a8")
    assert isinstance(llm_q.runner.params["layers"]["w_gate"],
                      QuantizedW8A8)
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    a = llm_q.generate(prompt_token_ids=[[5, 9, 23], [7, 12, 2, 44]],
                       sampling_params=sp)
    b = make(None).generate(prompt_token_ids=[[5, 9, 23], [7, 12, 2, 44]],
                            sampling_params=sp)
    for qa, qb in zip(a, b):
        assert qa.output_token_ids[:2] == qb.output_token_ids[:2]


def test_hybrid_gdn_int8_quantized_runs(tmp_path):
    """Hybrid GDN projections (in_qkvz/out_proj) route through qmm."""
    from tests.test_hybrid_qwen3next import make_ckpt
    make_ckpt(tmp_path)
    cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                       max_model_len=64, quantization="int8",
                       cache=CacheConfig(page_size=4, num_pages=64))
    out = LLM(config=cfg).generate(
        prompt_token_ids=[[5, 9, 23]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=4,
                                       ignore_eos=True))[0]
    assert len(out.output_token_ids) == 4


def test_mla_fp8_kv_cache_close(tmp_path):
    """fp8 latent-KV storage (reference concat_and_cache_mla_fp8): runs
    and stays close to the full-precision cache on short greedy runs."""
    from tests.test_deepseek import make_ckpt
    make_ckpt("DeepseekV2ForCausalLM", tmp_path, q_lora_rank=None,
              topk_method="greedy", n_group=None, topk_group=None,
              scoring_func="softmax", norm_topk_prob=False)

    def run(kv_dtype):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=64,
                           cache=CacheConfig(page_size=4, num_pages=64,
                                             kv_cache_dtype=kv_dtype))
        return LLM(config=cfg).generate(
            prompt_token_ids=[[7, 3, 56, 21]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))[0]

    full = run("auto")
    fp8 = run("fp8")
    assert fp8.output_token_ids[:2] == full.output_token_ids[:2]
    assert len(fp8.output_token_ids) == 6


def test_fp8_block_roundtrip_close():
    """Block-wise fp8 (128×128 tile scales, reference fp8.py:370-453):
    dequantized weight is close; ragged tails handled."""
    import numpy as np
    from gllm_tpu.ops.quant import deq, quantize_weight_block

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((200, 300)).astype(np.float32))
    qb = quantize_weight_block(w)
    assert qb.q.shape == (200, 300)
    assert qb.scale.shape == (2, 3)
    back = np.asarray(deq(qb, jnp.float32))
    err = np.abs(back - np.asarray(w)).max()
    assert err < 0.3               # e4m3: ~6% relative on |w|max ≈ 4.4
    # per-tile scaling isolates a hot tile: a 100× tile would cost ~30 abs
    # error under one global scale; untouched tiles keep fp8 resolution
    w2 = w.at[:128, :128].multiply(100.0)
    qb2 = quantize_weight_block(w2)
    back2 = np.asarray(deq(qb2, jnp.float32))
    tail_err = np.abs(back2[128:, 128:]
                      - np.asarray(w2)[128:, 128:]).max()
    assert tail_err < 0.3


def test_engine_fp8_block_close_to_full_precision(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(3)
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=128, eos_token_id=0,
        attention_bias=False)).save_pretrained(tmp_path,
                                               safe_serialization=True)

    def run(q):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=64, quantization=q,
                           cache=CacheConfig(page_size=4, num_pages=64))
        return LLM(config=cfg).generate(
            prompt_token_ids=[[5, 9, 23, 41]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                           ignore_eos=True))[0]

    full = run(None)
    quantized = run("fp8_block")
    assert quantized.output_token_ids[:2] == full.output_token_ids[:2]
    assert len(quantized.output_token_ids) == 8


def test_moe_w8a8_under_ep_matches_ep1(tmp_path):
    """Quantized (W8A8) experts under expert-parallel sharding: the int8
    grouped GEMM partitions over the EP axis (GSPMD shards w.q/w.scale on
    the expert dim) and outputs match the unsharded quantized run."""
    from gllm_tpu.config import ParallelConfig
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    from gllm_tpu.ops.quant import QuantizedW8A8
    torch.manual_seed(13)
    Qwen2MoeForCausalLM(Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=96,
        moe_intermediate_size=32, shared_expert_intermediate_size=64,
        num_experts=8, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=128, eos_token_id=0)).save_pretrained(
        tmp_path, safe_serialization=True)

    def run(tp):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=64, quantization="w8a8",
                           cache=CacheConfig(page_size=4, num_pages=64),
                           parallel=ParallelConfig(tp=tp,
                                                   enable_ep=tp > 1))
        llm = LLM(config=cfg)
        assert isinstance(llm.runner.params["layers"]["w_gate"],
                          QuantizedW8A8)
        return [o.output_token_ids for o in llm.generate(
            prompt_token_ids=[[5, 9, 23], [7, 12, 2, 44]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))]

    assert run(4) == run(1)
