"""Pretty-print a steptrace JSONL for bench post-mortems.

Usage:
  python -m gllm_tpu.obs.dump trace.jsonl            # event table + summary
  python -m gllm_tpu.obs.dump trace.jsonl --summary  # summary only
  curl -s host:8000/steptrace | python -m gllm_tpu.obs.dump -  # live dump

The input is one JSON event per line (``StepTrace.to_jsonl``) or a single
JSON object with an ``events`` list (the ``GET /steptrace`` payload).
"""

from __future__ import annotations

import argparse
import json
import sys

from gllm_tpu.obs.steptrace import summarize

# ``reason`` is carried by chain_break events (waiting/pages/shape/
# spec/finish — docs/overlap_scheduling.md); blank for step events
_COLS = ("seq", "t", "kind", "reason", "num_seqs", "tokens", "k",
         "wall_ms")


def load_events(stream) -> list:
    text = stream.read()
    text = text.strip()
    if not text:
        return []
    if text.startswith("{") and "\n" not in text.split("}", 1)[0]:
        try:
            obj = json.loads(text)
            if isinstance(obj, dict) and "events" in obj:
                return obj["events"]
        except json.JSONDecodeError:
            pass
    events = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            events.append(json.loads(line))
    return events


def format_table(events: list) -> str:
    rows = [[str(e.get(c, "")) for c in _COLS] for e in events]
    widths = [max([len(c)] + [len(r[i]) for r in rows])
              for i, c in enumerate(_COLS)]
    lines = ["  ".join(c.ljust(w) for c, w in zip(_COLS, widths))]
    for r in rows:
        lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gllm_tpu.obs.dump",
        description="pretty-print a steptrace JSONL")
    ap.add_argument("path", help="JSONL file, or - for stdin")
    ap.add_argument("--summary", action="store_true",
                    help="print only the by-kind wall-time summary")
    args = ap.parse_args(argv)
    if args.path == "-":
        events = load_events(sys.stdin)
    else:
        with open(args.path) as f:
            events = load_events(f)
    if not args.summary:
        print(format_table(events))
        print()
    print(json.dumps(summarize(events), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
