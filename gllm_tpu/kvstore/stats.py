"""Metric family of the tiered prefix store (docs/observability.md).

One module owns the registrations so the disk tier, the peer tiers, and
the manager share the exact same metric objects — the registry would
reject a drifted re-registration, but sharing them makes drift
impossible by construction.
"""

from __future__ import annotations

from gllm_tpu.obs import metrics as obs

# tier ∈ {disk, peer} — the two tiers this subsystem adds below the
# existing HBM / host levels (whose hit accounting lives in
# memory_manager / kvswap; the steptrace `prefix` events unify all four).
HITS = obs.counter(
    "gllm_kvstore_hits_total",
    "prefix-page probes served by a kvstore tier", ("tier",))
MISSES = obs.counter(
    "gllm_kvstore_misses_total",
    "prefix-page probes a kvstore tier could not serve", ("tier",))
POISON = obs.counter(
    "gllm_kvstore_poison_drops_total",
    "kvstore entries dropped on canary/geometry verification failure "
    "(corruption or hash collision — treated as a miss, never served)",
    ("tier",))
EVICTIONS = obs.counter(
    "gllm_kvstore_evictions_total",
    "kvstore entries evicted by the tier's byte-budgeted LRU", ("tier",))
BYTES = obs.counter(
    "gllm_kvstore_bytes_total",
    "payload bytes moved through a kvstore tier (dir=read|write; int8 "
    "KV pages move roughly half the bf16 bytes)", ("tier", "dir"))
DISK_USED = obs.gauge(
    "gllm_kvstore_disk_used_bytes",
    "bytes currently stored by the disk prefix tier")
DISK_ENTRIES = obs.gauge(
    "gllm_kvstore_disk_entries",
    "page files currently stored by the disk prefix tier")
PEER_TIMEOUTS = obs.counter(
    "gllm_kvstore_peer_timeouts_total",
    "peer prefix fetches abandoned at the deadline (the probe degrades "
    "to the next tier; it never stalls the scheduler)")
PEER_SERVED = obs.counter(
    "gllm_kvstore_peer_served_total",
    "prefix pages this replica served to peers")
PEER_BREAKER_OPENS = obs.counter(
    "gllm_kvstore_peer_breaker_opens_total",
    "per-peer circuit-breaker trips (closed/half-open → open): the "
    "peer's probes are skipped for an exponentially-backed-off window "
    "with jitter, then ONE half-open probe decides recovery", ("peer",))
PEER_BREAKER_OPEN = obs.gauge(
    "gllm_kvstore_peer_breaker_open",
    "peers currently held open (skipped) by their circuit breaker")
PUSH_PAGES = obs.counter(
    "gllm_kvstore_peer_push_pages_total",
    "prefix pages accepted into the host pool via the peer push op "
    "(pd-pool KV handoff: each accepted page is one page of decode-side "
    "re-prefill avoided)")
PUSH_BYTES = obs.counter(
    "gllm_kvstore_peer_push_bytes_total",
    "payload bytes accepted via the peer push op (int8 pages are about "
    "half the bf16 bytes)")
PUSH_REJECTS = obs.counter(
    "gllm_kvstore_peer_push_rejects_total",
    "pushed pages refused by the receiving replica (verification "
    "failure, malformed frame, or host pool full — the decode side "
    "falls back to pull-then-recompute, never a stall)")
