"""Offline throughput benchmark (reference examples/batch_inference.py).

Drives ``LLM.generate`` over a ShareGPT-style JSON dataset (or a synthetic
workload when no dataset is given — this environment has no egress) and
prints reqs/s + input/output tok/s like the reference (:56-74).

Usage:
  python examples/batch_inference.py --model <dir> [--dataset sharegpt.json]
  python examples/batch_inference.py --model-size tiny --dummy   # smoke
"""

import argparse
import json
import sys
import time

import numpy as np


def load_sharegpt(path, tokenizer, n, max_len):
    with open(path) as f:
        data = json.load(f)
    out = []
    for conv in data:
        turns = conv.get("conversations", [])
        if len(turns) < 2:
            continue
        prompt = tokenizer.encode(turns[0]["value"])[:max_len // 2]
        completion = tokenizer.encode(turns[1]["value"])
        if len(prompt) < 4:
            continue
        out.append((prompt, max(1, len(completion))))
        if len(out) >= n:
            break
    return out


def synthetic(rng, n, max_len):
    out = []
    for _ in range(n):
        p = int(min(max(rng.lognormal(5.0, 0.8), 16), max_len // 2))
        o = int(min(max(rng.lognormal(4.5, 0.7), 16), max_len // 2))
        out.append((rng.integers(1, 30000, size=p).tolist(), o))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="")
    ap.add_argument("--dataset", default=None, help="ShareGPT json")
    ap.add_argument("--num-prompts", type=int, default=64)
    ap.add_argument("--max-model-len", type=int, default=2048)
    ap.add_argument("--maxp", type=int, default=1024)
    ap.add_argument("--maxd", type=int, default=128)
    ap.add_argument("--dummy", action="store_true",
                    help="random weights (no checkpoint)")
    ap.add_argument("--enable-prefix-caching", action="store_true")
    args = ap.parse_args()

    from gllm_tpu.config import (CacheConfig, EngineConfig, SchedulerConfig)
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams

    cfg = EngineConfig(
        model=args.model, max_model_len=args.max_model_len,
        load_format="dummy" if args.dummy else "auto",
        scheduler=SchedulerConfig(max_prefill_tokens=args.maxp,
                                  max_decode_seqs=args.maxd),
        cache=CacheConfig(enable_prefix_caching=args.enable_prefix_caching))
    model_cfg = None
    if args.dummy and not args.model:
        from gllm_tpu.models.config import ModelConfig
        model_cfg = ModelConfig(
            architecture="LlamaForCausalLM", vocab_size=32000,
            hidden_size=512, num_layers=4, num_heads=8, num_kv_heads=4,
            head_dim=64, intermediate_size=1024,
            max_position=args.max_model_len)
    llm = LLM(config=cfg, model_cfg=model_cfg)

    rng = np.random.default_rng(0)
    if args.dataset:
        workload = load_sharegpt(args.dataset, llm.tokenizer,
                                 args.num_prompts, args.max_model_len)
    else:
        workload = synthetic(rng, args.num_prompts, args.max_model_len)
    prompts = [p for p, _ in workload]
    params = [SamplingParams(temperature=0.0, max_tokens=o, ignore_eos=True)
              for _, o in workload]

    t0 = time.monotonic()
    outs = llm.generate(prompt_token_ids=prompts, sampling_params=params)
    dt = time.monotonic() - t0

    n_in = sum(len(p) for p in prompts)
    n_out = sum(o.num_output_tokens for o in outs)
    print(f"requests:      {len(prompts)} in {dt:.2f}s "
          f"({len(prompts) / dt:.2f} req/s)")
    print(f"input tokens:  {n_in} ({n_in / dt:.1f} tok/s)")
    print(f"output tokens: {n_out} ({n_out / dt:.1f} tok/s)")
    print(f"total:         {(n_in + n_out) / dt:.1f} tok/s")


if __name__ == "__main__":
    sys.exit(main())
