"""MoE family: HF equivalence oracles + EP sharding on the CPU mesh."""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from gllm_tpu.config import CacheConfig, EngineConfig, ParallelConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models.config import from_hf_config
from gllm_tpu.models.moe import select_experts
from gllm_tpu.sampling_params import SamplingParams

MOE_TINY = dict(
    vocab_size=128, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    max_position_embeddings=256, rms_norm_eps=1e-6, rope_theta=10000.0,
    tie_word_embeddings=False, eos_token_id=0,
)


def make_ckpt(arch, tmpdir):
    torch.manual_seed(13)
    if arch == "MixtralForCausalLM":
        from transformers import MixtralConfig, MixtralForCausalLM
        cfg = MixtralConfig(**MOE_TINY, num_local_experts=4,
                            num_experts_per_tok=2)
        model = MixtralForCausalLM(cfg)
    elif arch == "Qwen3MoeForCausalLM":
        from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM
        cfg = Qwen3MoeConfig(**MOE_TINY, num_experts=8,
                             num_experts_per_tok=2, moe_intermediate_size=32,
                             norm_topk_prob=True, head_dim=16,
                             decoder_sparse_step=1, mlp_only_layers=[])
        model = Qwen3MoeForCausalLM(cfg)
    elif arch == "Qwen2MoeForCausalLM":
        from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM
        cfg = Qwen2MoeConfig(**MOE_TINY, num_experts=4,
                             num_experts_per_tok=2, moe_intermediate_size=32,
                             shared_expert_intermediate_size=48,
                             norm_topk_prob=False, decoder_sparse_step=1,
                             mlp_only_layers=[])
        model = Qwen2MoeForCausalLM(cfg)
    else:
        raise ValueError(arch)
    model.eval()
    model.save_pretrained(tmpdir, safe_serialization=True)
    return model


def hf_greedy(model, prompt_ids, n):
    ids = list(prompt_ids)
    with torch.no_grad():
        for _ in range(n):
            logits = model(torch.tensor([ids])).logits[0, -1]
            ids.append(int(logits.argmax()))
    return ids[len(prompt_ids):]


@pytest.mark.parametrize("arch", ["MixtralForCausalLM",
                                  "Qwen3MoeForCausalLM",
                                  "Qwen2MoeForCausalLM"])
def test_moe_checkpoint_greedy_equivalence(arch, tmp_path):
    hf = make_ckpt(arch, tmp_path)
    cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                       max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg)
    prompts = [[7, 3, 56, 21], [99, 14]]
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
    for p, out in zip(prompts, outs):
        want = hf_greedy(hf, p, 8)
        assert out.output_token_ids == want, (arch, out.output_token_ids,
                                              want)


def make_mixed_ckpt(tmp_path, mlp_only_layers, decoder_sparse_step):
    """Qwen2-MoE with a mixed dense/sparse layer stack (4 layers so the
    stride patterns are non-trivial)."""
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM
    torch.manual_seed(17)
    tiny = dict(MOE_TINY, num_hidden_layers=4)
    cfg = Qwen2MoeConfig(**tiny, num_experts=4, num_experts_per_tok=2,
                         moe_intermediate_size=32,
                         shared_expert_intermediate_size=48,
                         norm_topk_prob=False,
                         decoder_sparse_step=decoder_sparse_step,
                         mlp_only_layers=list(mlp_only_layers))
    model = Qwen2MoeForCausalLM(cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


@pytest.mark.parametrize("mlp_only,stride", [((0,), 1), ((), 2),
                                             ((1, 3), 1)])
def test_moe_mixed_dense_sparse_stack(mlp_only, stride, tmp_path):
    """mlp_only_layers / decoder_sparse_step route those layers through a
    dense MLP (HF semantics: sparse iff not mlp_only and (i+1) % step ==
    0) — greedy tokens must match HF exactly."""
    from gllm_tpu.models.config import from_hf_config
    from gllm_tpu.models.loader import load_hf_config
    from gllm_tpu.models.moe import moe_layer_mask

    hf = make_mixed_ckpt(tmp_path, mlp_only, stride)
    mc = from_hf_config(load_hf_config(str(tmp_path)))
    mask = moe_layer_mask(mc)
    assert len(mask) == 4 and not all(mask), mask   # genuinely mixed
    for i, sparse in enumerate(mask):
        want = (i not in mlp_only) and ((i + 1) % stride == 0)
        assert sparse == want, (i, mask)

    cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                       max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=128))
    llm = LLM(config=cfg)
    prompts = [[7, 3, 56, 21], [99, 14, 5]]
    outs = llm.generate(
        prompt_token_ids=prompts,
        sampling_params=SamplingParams(temperature=0.0, max_tokens=8,
                                       ignore_eos=True))
    for p, out in zip(prompts, outs):
        want = hf_greedy(hf, p, 8)
        assert out.output_token_ids == want, (mlp_only, stride,
                                              out.output_token_ids, want)


def test_moe_ep_sharded_matches_single(tmp_path):
    make_ckpt("Qwen3MoeForCausalLM", tmp_path)

    def run(tp):
        cfg = EngineConfig(model=str(tmp_path), dtype="float32",
                           max_model_len=128,
                           cache=CacheConfig(page_size=4, num_pages=64),
                           parallel=ParallelConfig(tp=tp))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=[[5, 9, 23, 41], [7, 7, 7]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))]

    assert run(4) == run(1)


def test_select_experts_matches_torch_topk():
    rng = np.random.default_rng(3)
    logits = rng.standard_normal((5, 8)).astype(np.float32)
    w, ids = select_experts(jnp.asarray(logits), 2, True)
    tw = torch.softmax(torch.tensor(logits), dim=-1)
    tw, tids = torch.topk(tw, 2, dim=-1)
    tw = tw / tw.sum(-1, keepdim=True)
    np.testing.assert_array_equal(np.asarray(ids), tids.numpy())
    np.testing.assert_allclose(np.asarray(w), tw.numpy(), rtol=1e-5)
