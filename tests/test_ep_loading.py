"""EP-pruned / sharding-aware MoE weight loading (VERDICT r1 item 10).

Expert stacks are assembled per device shard via make_array_from_callback:
peak host buffer is bounded by one shard (not the full expert stack), and
the engine output is byte-identical to the full-host-then-shard path.
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.models import loader
from gllm_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def moe_ckpt(tmp_path_factory):
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM
    torch.manual_seed(12)
    d = tmp_path_factory.mktemp("ep_moe")
    Qwen2MoeForCausalLM(Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        moe_intermediate_size=32, shared_expert_intermediate_size=48,
        num_experts=8, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=256, eos_token_id=0)).save_pretrained(
        d, safe_serialization=True)
    return str(d)


def run(ckpt, tp):
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128,
        cache=CacheConfig(page_size=4, num_pages=64),
        parallel=ParallelConfig(tp=tp, enable_ep=True))
    llm = LLM(config=cfg)
    return [o.output_token_ids for o in llm.generate(
        prompt_token_ids=[[7, 3, 56], [99, 14, 2, 8]],
        sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True))]


def test_ep_sharded_load_matches_full_load(moe_ckpt):
    loader.ep_load_stats["max_chunk_bytes"] = 0
    sharded = run(moe_ckpt, tp=4)
    assert loader.ep_load_stats["max_chunk_bytes"] > 0  # EP path taken
    full = run(moe_ckpt, tp=1)                          # single-device path
    assert sharded == full


def test_ep_load_peak_host_buffer_bounded(moe_ckpt):
    """The biggest host buffer materialized for expert weights must be one
    tp shard, not the full [L, E, ...] stack."""
    loader.ep_load_stats["max_chunk_bytes"] = 0
    run(moe_ckpt, tp=4)
    # full stack for the largest expert leaf: L*E*H*I*4 bytes
    full_stack = 2 * 8 * 64 * 32 * 4
    assert 0 < loader.ep_load_stats["max_chunk_bytes"] <= full_stack // 4


def test_ep_load_deepseek(moe_ckpt, tmp_path):
    """Same discipline for the DeepSeek family (dense+MoE layer groups)."""
    from tests.test_deepseek import make_ckpt
    make_ckpt("DeepseekV2ForCausalLM", tmp_path, q_lora_rank=None,
              topk_method="greedy", n_group=None, topk_group=None,
              scoring_func="softmax", norm_topk_prob=False)

    def run_ds(tp):
        cfg = EngineConfig(
            model=str(tmp_path), dtype="float32", max_model_len=128,
            cache=CacheConfig(page_size=4, num_pages=128),
            parallel=ParallelConfig(tp=tp, enable_ep=True))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=[[7, 3, 56, 21]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))]

    loader.ep_load_stats["max_chunk_bytes"] = 0
    sharded = run_ds(2)
    assert loader.ep_load_stats["max_chunk_bytes"] > 0
    assert sharded == run_ds(1)
