#!/bin/bash
# One-shot on-chip sequence for a freshly recovered axon tunnel
# (single-tenant: NOTHING else may touch the chip while this runs).
#
#   bash benchmarks/onchip_round.sh [outdir]
#
# Order is deliberate (VERDICT r03 next #1/#2):
#  1. chip_probes  — each Pallas kernel + engine path, per-probe subprocess
#                    timeouts; a stall names its kernel instead of wedging
#                    the session.
#  2. kernel_tune  — block-size sweep; winners land in
#                    gllm_tpu/ops/pallas/tables.json (--write).
#  3. vmem probe   — oversized tiles until Mosaic refuses: validates the
#                    6 MB heuristic in ragged_attention.py.
#  4. bench.py     — the headline number (supervised, degrade ladder,
#                    persistent compile cache shared with steps 1-3).
# Every step appends to $OUT; steps are individually timeout-bounded and
# the script continues past failures so one bad step can't eat the rest.

set -u
cd /root/repo
OUT=$(readlink -f "${1:-/root/repo/.tunnel/onchip}")
mkdir -p "$OUT"

run() {
  name=$1; tmo=$2; shift 2
  echo "=== $name ($(date -u +%FT%TZ)) ===" | tee -a "$OUT/sequence.log"
  timeout -k 30 "$tmo" "$@" >"$OUT/$name.out" 2>&1
  rc=$?
  echo "$name rc=$rc" | tee -a "$OUT/sequence.log"
  tail -5 "$OUT/$name.out" | sed 's/^/    /' >> "$OUT/sequence.log"
}

run chip_probes 950 python benchmarks/chip_probes.py
run kernel_tune 2800 python benchmarks/kernel_tune.py --write
run vmem_probe 900 python benchmarks/kernel_tune.py --vmem-probe
run bench 1200 python bench.py
#  5. latency    — TTFT/TPOT/ITL percentiles against the BASELINE <500 ms
#                  p50-TTFT serving target (in-process server: still ONE
#                  TPU holder).
run latency 1200 python benchmarks/latency_bench.py
echo "=== done ($(date -u +%FT%TZ)) ===" | tee -a "$OUT/sequence.log"
grep -h "sharegpt_output" "$OUT/bench.out" | tail -1
grep -h "ttft_p50_ms" "$OUT/latency.out" | tail -1
