"""Pallas block-size tuning table (VERDICT r03 missing #4).

The attention dispatch reads block sizes from
``gllm_tpu/ops/pallas/tuning.py`` (analogue of the reference's
``fused_moe_triton/configs/`` autotune tables); the table is layered:
BUILTIN defaults < committed tables.json < GLLM_TPU_TUNE_TABLE override.
"""

import importlib.util
import json
import os

from gllm_tpu.ops.pallas import tuning


def _load_kernel_tune():
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "benchmarks", "kernel_tune.py")
    spec = importlib.util.spec_from_file_location("_kernel_tune", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _reset_caches():
    tuning._table.cache_clear()
    tuning.device_tag.cache_clear()


def test_builtin_defaults():
    _reset_caches()
    assert tuning.get("ragged") == {"q_block": 128, "kv_block": 256}
    assert tuning.get("decode") == {"kv_block": 256}
    # the unified mixed-batch kernel (--unified-step) resolves its own
    # geometry: block sizes + the decode-class DMA interleave depth
    assert tuning.get("unified") == {"q_block": 128, "kv_block": 256,
                                     "group": 4}


def test_env_override_layering(tmp_path, monkeypatch):
    _reset_caches()
    # device-specific beats default; partial override keeps other params
    table = {"default": {"ragged": {"kv_block": 512}},
             tuning.device_tag(): {"decode": {"kv_block": 128}}}
    p = tmp_path / "tune.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv("GLLM_TPU_TUNE_TABLE", str(p))
    tuning._table.cache_clear()
    assert tuning.get("ragged") == {"q_block": 128, "kv_block": 512}
    assert tuning.get("decode") == {"kv_block": 128}
    monkeypatch.delenv("GLLM_TPU_TUNE_TABLE")
    tuning._table.cache_clear()


def test_malformed_table_ignored(tmp_path, monkeypatch):
    _reset_caches()
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    monkeypatch.setenv("GLLM_TPU_TUNE_TABLE", str(p))
    tuning._table.cache_clear()
    assert tuning.get("ragged") == {"q_block": 128, "kv_block": 256}
    monkeypatch.delenv("GLLM_TPU_TUNE_TABLE")
    tuning._table.cache_clear()


def test_device_tag_cpu():
    _reset_caches()
    # on the CPU test backend this resolves to some non-empty tag and the
    # lookup falls back to default cleanly
    assert tuning.device_tag()
    assert tuning.get("nonexistent_kernel") == {}


def test_committed_table_entries_carry_provenance():
    """Every committed tables.json entry must say which sweep artifact
    produced it (guards against a repeat of the round-5 silent
    tuning-table regression, where a hand-edited value shipped with no
    trail back to a measurement)."""
    with open(tuning._TABLES_PATH) as f:
        table = json.load(f)
    assert table, "committed tables.json is empty"
    for dev, kernels in table.items():
        for kern, params in kernels.items():
            comment = params.get("comment")
            assert isinstance(comment, str) and comment.strip(), (
                f"tables.json entry {dev}/{kern} lacks a provenance "
                f"'comment' naming the sweep artifact behind it")
            # provenance must point somewhere checkable, not just vibes
            assert any(tok in comment for tok in ("docs/", "r0", "sweep",
                                                  "kernel_tune")), (
                f"{dev}/{kern} comment names no artifact: {comment!r}")
            # ... and a named docs/ artifact must actually be committed
            repo = os.path.join(os.path.dirname(__file__), os.pardir)
            for tok in comment.split():
                if tok.startswith("docs/"):
                    path = tok.rstrip(".,;:)")
                    assert os.path.exists(os.path.join(repo, path)), (
                        f"{dev}/{kern} cites missing artifact {path!r}")
            # a kept-from-a-manual-A/B placeholder is not provenance —
            # the r05/r06 decode regression class (sweep broken, value
            # hand-carried with no measured artifact behind it)
            assert "manual" not in comment.lower(), (
                f"{dev}/{kern} provenance is a manual A/B placeholder: "
                f"{comment!r}")
            # and the entry must carry actual kernel params besides it
            assert any(k != "comment" for k in params), (dev, kern)


def test_get_strips_provenance_from_kwargs(monkeypatch, tmp_path):
    """tuning.get() must never leak the provenance annotation into
    kernel kwargs — on any layer, device-specific or default."""
    _reset_caches()
    table = {"default": {"ragged": {"kv_block": 512,
                                    "comment": "sweep artifact X"}},
             tuning.device_tag(): {"ragged": {"q_block": 64,
                                              "comment": "sweep Y"}}}
    p = tmp_path / "tune.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv("GLLM_TPU_TUNE_TABLE", str(p))
    tuning._table.cache_clear()
    got = tuning.get("ragged")
    assert "comment" not in got
    assert got == {"q_block": 64, "kv_block": 512}
    monkeypatch.delenv("GLLM_TPU_TUNE_TABLE")
    tuning._table.cache_clear()
    # the COMMITTED table must also come out comment-free
    for kern in ("ragged", "decode"):
        assert "comment" not in tuning.get(kern)


# ---------------------------------------------------------------------------
# sweep-body closure hygiene (the r5 HTTP-413 regression class)
# ---------------------------------------------------------------------------

_CONST_CAP_BYTES = 128 * 1024


def _jaxpr_consts(fn, *args):
    """Every constant the traced computation closes over, including
    constants of nested sub-jaxprs (jit bodies land inside a pjit eqn's
    ClosedJaxpr param, not the outer jaxpr's consts)."""
    import jax
    closed = jax.make_jaxpr(fn)(*args)
    consts = list(closed.consts)

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            for p in eqn.params.values():
                stack = [p]
                while stack:
                    x = stack.pop()
                    if isinstance(x, jax.core.ClosedJaxpr):
                        consts.extend(x.consts)
                        walk(x.jaxpr)
                    elif isinstance(x, (list, tuple)):
                        stack.extend(x)

    walk(closed.jaxpr)
    return consts


def _big_consts(fn, *args):
    import numpy as np
    out = []
    for c in _jaxpr_consts(fn, *args):
        arr = np.asarray(c)
        if arr.nbytes > _CONST_CAP_BYTES:
            out.append((arr.shape, arr.dtype, arr.nbytes))
    return out


def test_const_detector_flags_closure_capture():
    """Self-check: a body that DOES capture a buffer must be flagged,
    so a jax upgrade that moves constants somewhere the walker misses
    fails loudly instead of hollowing out the guard below."""
    import jax
    import jax.numpy as jnp
    big = jnp.ones((512, 512), jnp.float32)          # 1 MiB

    @jax.jit
    def bad(q):
        return q @ big

    assert _big_consts(bad, jnp.ones((4, 512), jnp.float32))


def test_sweep_bodies_close_over_no_buffers():
    """The compiled sweep bodies must take the KV caches as ARGUMENTS,
    never closure constants: axon's remote_compile ships captured
    constants in the request body, and a GB-scale cache gets HTTP 413 /
    an upload that outlives the config timeout (the diagnosed r5
    decode-sweep "hang"). Traced on a shrunken workload — capture is a
    structural property, not a size one."""
    kt = _load_kernel_tune()
    run_r, args_r = kt.build_ragged(64, 64, T=128, S=4, ctx=256)
    run_d, args_d = kt.build_decode(64, gsz=2, S=8, ctx=256)
    run_u, args_u = kt.build_unified(64, 64, gsz=2, mix="balanced",
                                     shrink=True)
    for name, run, args in (("ragged", run_r, args_r),
                            ("decode", run_d, args_d),
                            ("unified", run_u, args_u)):
        # the unified workload's token axis must include the verify
        # class (fused-speculation q_len=spec_k+1 rows priced by the
        # sweep — ISSUE 13); structural check rides the closure trace
        if name == "unified":
            nd, nv, chunks = 8, 4, (32, 32)   # shrink "balanced"
            assert args[0].shape[0] == (nd + nv * kt.VERIFY_Q
                                        + sum(chunks)), args[0].shape
        # the caches must be in the argument list...
        assert len(args) == 3, name
        # ...and nothing buffer-sized may ride the jaxpr as a constant
        big = _big_consts(run, *args)
        assert not big, (
            f"{name} sweep body closes over buffer-sized constants "
            f"{big}; pass them as arguments (HTTP-413 guard)")
