"""Weight quantization (int8 weight-only, per-output-channel).

TPU-native counterpart of the reference's quantization stack
(/root/reference/gllm/layers/quantization/fp8.py + int4 Marlin MoE): the
reference consumes prebuilt CUDA block-quant GEMMs; on TPU the idiomatic
form is narrow storage + XLA-fused dequantation — int8 weights halve HBM
footprint and weight bandwidth (the decode bottleneck), and XLA fuses the
``int8→bf16 cast × scale`` into the matmul epilogue.

``Quantized`` is a pytree node, so quantized params flow through jit,
donation, and NamedSharding exactly like plain arrays; ``qmm`` dispatches on
leaf type so model code is written once (`qmm(x, lp["q_proj"])`).

FP8 (float8_e4m3) storage is supported with the same machinery where the
backend provides it; int4 packing and quantized MoE experts are follow-ups.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp


class Quantized(NamedTuple):
    """Per-output-channel symmetric quantization: w ≈ q * scale."""
    q: jnp.ndarray        # [..., in, out] int8 (or float8)
    scale: jnp.ndarray    # [..., 1, out] f32


def quantize_weight(w: jnp.ndarray, dtype=jnp.int8) -> Quantized:
    """Quantize a [..., in, out] matmul weight per output channel."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    if dtype == jnp.int8:
        scale = absmax / 127.0
        q = jnp.clip(jnp.round(wf / jnp.maximum(scale, 1e-9)),
                     -127, 127).astype(jnp.int8)
    else:  # float8 family
        fmax = float(jnp.finfo(dtype).max)
        scale = absmax / fmax
        q = (wf / jnp.maximum(scale, 1e-9)).astype(dtype)
    return Quantized(q, scale)


def qmm(x: jnp.ndarray, w: Union[jnp.ndarray, Quantized]) -> jnp.ndarray:
    """Matmul against a plain or quantized weight."""
    if isinstance(w, Quantized):
        deq = w.q.astype(x.dtype) * w.scale.astype(x.dtype)
        return x @ deq
    return x @ w


# Matmul leaves of the dense/moe layer groups that get quantized (norms,
# biases, rope tables, routers, and embeddings stay high-precision — same
# policy as the reference's ignored-layers audit, model_loader.py:122-174).
QUANT_LEAVES = frozenset({
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
    "q_b_proj", "shared_gate_proj", "shared_up_proj", "shared_down_proj",
})


def quantize_params(params: dict, dtype=jnp.int8) -> dict:
    """Quantize the eligible matmul leaves of a model param tree."""
    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            elif k in QUANT_LEAVES:
                out[k] = quantize_weight(v, dtype)
            else:
                out[k] = v
        return out

    return walk(params)


def param_bytes(params) -> int:
    return sum(x.nbytes for x in jax.tree.leaves(params))
