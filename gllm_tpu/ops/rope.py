"""Rotary position embeddings.

Covers the reference's RotaryEmbedding family
(/root/reference/gllm/layers/rotary_embedding.py): base NeoX-style rotation
plus linear / llama3 frequency scaling. YaRN (DeepSeek MLA) and mrope
(vision models) extend these tables in later modules.

Design: the cos/sin table is precomputed once per model ([max_pos, rot_dim/2],
float32) and gathered by token position inside the jit'd step — a cheap
[T, rot_dim/2] gather that XLA fuses; no per-layer recompute.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp


def _base_inv_freq(rot_dim: int, theta: float) -> jnp.ndarray:
    return 1.0 / (theta ** (jnp.arange(0, rot_dim, 2, dtype=jnp.float32)
                            / rot_dim))


def _llama3_scale_inv_freq(inv_freq: jnp.ndarray,
                           scaling: Dict[str, Any]) -> jnp.ndarray:
    """Llama-3.x rope scaling (reference rotary_embedding.py Llama3 variant)."""
    factor = scaling.get("factor", 8.0)
    low_factor = scaling.get("low_freq_factor", 1.0)
    high_factor = scaling.get("high_freq_factor", 4.0)
    orig_max = scaling.get("original_max_position_embeddings", 8192)

    low_wavelen = orig_max / low_factor
    high_wavelen = orig_max / high_factor
    wavelen = 2 * math.pi / inv_freq
    # three bands: scale fully / don't scale / smooth interpolation
    smooth = ((orig_max / wavelen - low_factor)
              / (high_factor - low_factor))
    scaled = jnp.where(
        wavelen > low_wavelen, inv_freq / factor,
        jnp.where(wavelen < high_wavelen, inv_freq,
                  (1 - smooth) * inv_freq / factor + smooth * inv_freq))
    return scaled


def _yarn_get_mscale(scale: float, mscale: float) -> float:
    if scale <= 1.0:
        return 1.0
    return 0.1 * mscale * math.log(scale) + 1.0


def _yarn_inv_freq(rot_dim: int, theta: float,
                   s: Dict[str, Any]) -> Tuple[jnp.ndarray, float]:
    """YaRN NTK-by-parts frequency blend (reference rotary_embedding.py YaRN
    variant; used by DeepSeek V2/V3). Returns (inv_freq, cos_sin_mscale)."""
    factor = s.get("factor", 1.0)
    orig_max = s.get("original_max_position_embeddings", 4096)
    beta_fast = s.get("beta_fast", 32)
    beta_slow = s.get("beta_slow", 1)
    mscale = s.get("mscale", 1.0)
    mscale_all_dim = s.get("mscale_all_dim", 0.0)

    def correction_dim(num_rot):
        return (rot_dim * math.log(orig_max / (num_rot * 2 * math.pi))
                / (2 * math.log(theta)))

    low = math.floor(correction_dim(beta_fast))
    high = math.ceil(correction_dim(beta_slow))
    low, high = max(low, 0), min(high, rot_dim - 1)

    pos_freq = theta ** (jnp.arange(0, rot_dim, 2, dtype=jnp.float32)
                         / rot_dim)
    inv_extra = 1.0 / pos_freq
    inv_interp = 1.0 / (factor * pos_freq)
    # linear ramp over dims: 0 below low (extrapolate), 1 above high
    idx = jnp.arange(rot_dim // 2, dtype=jnp.float32)
    ramp = jnp.clip((idx - low) / max(high - low, 0.001), 0, 1)
    inv_freq_mask = 1.0 - ramp
    inv_freq = inv_interp * (1 - inv_freq_mask) + inv_extra * inv_freq_mask
    cs_mscale = float(_yarn_get_mscale(factor, mscale)
                      / _yarn_get_mscale(factor, mscale_all_dim))
    return inv_freq, cs_mscale


def yarn_softmax_scale_mult(rope_scaling: Optional[Dict[str, Any]]) -> float:
    """Extra attention-scale factor under YaRN with mscale_all_dim
    (HF DeepSeek: softmax_scale *= mscale**2)."""
    if not rope_scaling:
        return 1.0
    rtype = rope_scaling.get("rope_type", rope_scaling.get("type"))
    if rtype != "yarn":
        return 1.0
    m = _yarn_get_mscale(rope_scaling.get("factor", 1.0),
                         rope_scaling.get("mscale_all_dim", 0.0))
    return m * m


def compute_rope_cos_sin(
    rot_dim: int,
    max_position: int,
    theta: float = 10000.0,
    rope_scaling: Optional[Dict[str, Any]] = None,
) -> jnp.ndarray:
    """Returns [max_position, rot_dim] table: concat(cos, sin) halves."""
    inv_freq = _base_inv_freq(rot_dim, theta)
    positions = jnp.arange(max_position, dtype=jnp.float32)
    mscale = 1.0
    if rope_scaling:
        rtype = rope_scaling.get("rope_type",
                                 rope_scaling.get("type", "default"))
        if rtype in ("linear",):
            positions = positions / rope_scaling.get("factor", 1.0)
        elif rtype in ("llama3",):
            inv_freq = _llama3_scale_inv_freq(inv_freq, rope_scaling)
        elif rtype in ("yarn",):
            inv_freq, mscale = _yarn_inv_freq(rot_dim, theta, rope_scaling)
        elif rtype in ("default", "mrope", None):
            pass
        else:
            raise NotImplementedError(f"rope scaling type {rtype!r}")
    freqs = jnp.outer(positions, inv_freq)          # [max_pos, rot_dim/2]
    return jnp.concatenate([jnp.cos(freqs) * mscale,
                            jnp.sin(freqs) * mscale], axis=-1)


def apply_rope(q: jnp.ndarray, k: jnp.ndarray, positions: jnp.ndarray,
               cos_sin: jnp.ndarray):
    """NeoX-style (rotate-half) rotary embedding.

    q: [T, Hq, D], k: [T, Hkv, D], positions: [T] int32,
    cos_sin: [max_pos, rot_dim] precomputed table. rot_dim may be < D
    (partial rotary, e.g. ChatGLM); the tail passes through.
    """
    rot_dim = cos_sin.shape[-1]
    half = rot_dim // 2
    cs = cos_sin[positions]                          # [T, rot_dim]
    cos = cs[:, :half][:, None, :]                   # [T, 1, half]
    sin = cs[:, half:][:, None, :]

    def rotate(x):
        x_rot, x_pass = x[..., :rot_dim], x[..., rot_dim:]
        x1, x2 = x_rot[..., :half], x_rot[..., half:]
        x1f = x1.astype(jnp.float32)
        x2f = x2.astype(jnp.float32)
        o1 = x1f * cos - x2f * sin
        o2 = x2f * cos + x1f * sin
        out = jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)
        if x_pass.shape[-1]:
            out = jnp.concatenate([out, x_pass], axis=-1)
        return out

    return rotate(q), rotate(k)


def apply_rope_interleaved(q: jnp.ndarray, k: jnp.ndarray,
                           positions: jnp.ndarray, cos_sin: jnp.ndarray):
    """Pair-interleaved rotary (DeepSeek, GLM): channel pairs (2i, 2i+1)
    rotate with frequency i. Implemented by de-interleaving the rotated
    prefix into half layout and applying the standard rotation — a fixed
    permutation applied identically to q and k, so attention scores are
    unchanged vs the interleaved-output formulation (HF's rotate_half on
    strided halves). Supports partial rotary: only the first
    ``cos_sin.shape[-1]`` channels rotate; the tail passes through.
    """
    rot_dim = cos_sin.shape[-1]

    def deinterleave(x):
        head, tail = x[..., :rot_dim], x[..., rot_dim:]
        *lead, d = head.shape
        head = head.reshape(*lead, d // 2, 2).swapaxes(-1, -2).reshape(
            *lead, d)
        return (jnp.concatenate([head, tail], axis=-1)
                if tail.shape[-1] else head)

    return apply_rope(deinterleave(q), deinterleave(k), positions, cos_sin)
