"""Fleet front-router suite (docs/robustness.md#fleet-topology--failover).

The router unit ladder (breaker / placement / affinity / journal) plus
the multi-replica failover chaos harness: 2 in-process ServingEngines
behind real HTTP api_servers behind an in-process FrontRouter, driven
deterministically through the ``replica_kill`` / ``replica_hang`` fault
points:

- mid-stream replica kill → the stream fails over to the surviving
  replica and the CLIENT observes one uninterrupted stream,
  byte-identical to a clean run, greedy AND seeded, zero lost or
  duplicated tokens (the acceptance headline);
- a wedged replica (hang) is caught by the stream idle timeout and the
  stream migrates the same way;
- non-retry-safe streams (unseeded sampling) terminate with an error
  chunk carrying retry_after instead of failing over;
- a dead replica costs the router at most ONE probe per breaker window;
- an admin-drained replica leaves rotation without dropping in-flight
  streams; a silent replica restart is detected via the /server_info
  identity;
- the api_server satellites: /server_info replica identity,
  POST /fault_inject (env-gated), SSE error events carrying retry_after,
  and the ServingEngine continuation path's byte-identity.
"""

import http.client
import json
import threading
import time

import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.engine.serving_engine import ServingEngine
from gllm_tpu.entrypoints.api_server import serve
from gllm_tpu.entrypoints.router_server import serve_router
from gllm_tpu.faults import FAULTS
from gllm_tpu.router import FrontRouter
from gllm_tpu.router.journal import (StreamEntry, StreamJournal,
                                     router_unsafe_reason)
from gllm_tpu.router.placement import Placement, PrefixAffinity
from gllm_tpu.router.replica import Replica, ReplicaSet
from gllm_tpu.sampling_params import SamplingParams
from gllm_tpu.utils import CircuitBreaker

PROMPT = [5, 17, 93, 41]
GREEDY = {"temperature": 0, "max_tokens": 24, "ignore_eos": True}
SEEDED = {"temperature": 0.8, "top_p": 0.9, "seed": 1234,
          "max_tokens": 24, "ignore_eos": True}


class StubTokenizer:
    """One char per token id: text equality ⇔ token-stream equality."""
    eos_token_id = 0

    def encode(self, text):
        return [min(ord(c), 120) for c in text][:64]

    def decode(self, ids, skip_special_tokens=False):
        return "".join(chr(max(32, i % 127)) for i in ids)

    def apply_chat_template(self, messages, add_generation_prompt=True,
                            **kw):
        text = " ".join(str(m.get("content", "")) for m in messages)
        return self.encode(text or "hi")


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(7)
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=96, max_position_embeddings=256,
        eos_token_id=0, attention_bias=False))
    d = tmp_path_factory.mktemp("router_model")
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


def make_llm(ckpt, **over):
    cfg = EngineConfig(model=ckpt, dtype="float32", max_model_len=128,
                       cache=CacheConfig(page_size=4, num_pages=128))
    for k, v in over.items():
        setattr(cfg, k, v)
    cfg.validate()
    return LLM(config=cfg, tokenizer=StubTokenizer())


def start_replica(ckpt, replica_id=None, **over):
    llm = make_llm(ckpt, **over)
    httpd = serve(llm, "127.0.0.1", 0, replica_id=replica_id)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    # warm the prefill buckets a failover continuation will need (4-,
    # 8-, 16-token prompts) so the compile pause can't trip the
    # router's idle timeout in the hang test
    for p in (PROMPT, list(range(2, 10)), list(range(2, 14))):
        for c in httpd.state.engine.submit(
                list(p), SamplingParams(temperature=0.0, max_tokens=2,
                                        ignore_eos=True)):
            pass
    return {"httpd": httpd, "port": port, "llm": llm,
            "addr": f"127.0.0.1:{port}"}


@pytest.fixture(scope="module")
def fleet(tiny_ckpt):
    reps = [start_replica(tiny_ckpt), start_replica(tiny_ckpt)]
    yield reps
    for r in reps:
        r["httpd"].shutdown()
        r["httpd"].state.engine.shutdown()


@pytest.fixture
def router(fleet):
    made = []

    def make(**kw):
        kw.setdefault("probe_interval_s", 0.1)
        kw.setdefault("breaker_base_s", 0.2)
        kw.setdefault("breaker_max_s", 2.0)
        kw.setdefault("breaker_jitter", 0.0)
        fr = FrontRouter([r["addr"] for r in fleet], **kw)
        httpd = serve_router(fr, "127.0.0.1", 0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        made.append((fr, httpd))
        return fr, httpd.server_address[1]

    yield make
    for fr, httpd in made:
        httpd.shutdown()
        fr.close()


# ---- HTTP helpers ----------------------------------------------------------

def post_json(port, path, body, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, raw, headers


def get_json(port, path, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("GET", path)
    resp = conn.getresponse()
    raw = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, (json.loads(raw) if raw else None), headers


def sse_stream(port, path, body, timeout=120, headers=None):
    """POST a streaming request, return (status, [parsed events]) —
    events end at [DONE] or EOF."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", path, body=json.dumps(body), headers=hdrs)
    resp = conn.getresponse()
    if resp.status != 200:
        raw = resp.read()
        conn.close()
        return resp.status, [json.loads(raw)] if raw else []
    events = []
    while True:
        line = resp.readline()
        if not line:
            break
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        payload = line[5:].strip()
        if payload == b"[DONE]":
            break
        events.append(json.loads(payload))
    conn.close()
    return 200, events


def completion_text(events):
    return "".join((e.get("choices") or [{}])[0].get("text") or ""
                   for e in events if "choices" in e)


def chat_text(events):
    out = []
    for e in events:
        if "choices" not in e:
            continue
        delta = e["choices"][0].get("delta") or {}
        out.append(delta.get("content") or "")
    return "".join(out)


def finish_of(events):
    for e in events:
        if "choices" in e and e["choices"][0].get("finish_reason"):
            return e["choices"][0]["finish_reason"]
    return None


def error_events(events):
    return [e for e in events if "error" in e and "choices" not in e]


# ---- unit ladder: breaker / journal / placement / affinity -----------------

def test_shared_breaker_class():
    """The kvstore peer breaker and the router breaker are ONE class in
    gllm_tpu.utils (the PR 15 generalization)."""
    from gllm_tpu.kvstore.peer import PeerBreaker
    assert PeerBreaker is CircuitBreaker
    b = CircuitBreaker(base_s=1.0, max_s=8.0, threshold=2, jitter=0.0)
    assert b.allow()
    b.failure(now=0.0)
    assert b.state == "closed"          # threshold 2
    b.failure(now=0.0)
    assert b.state == "open" and not b.allow(now=0.5)
    assert b.allow(now=1.5) and b.state == "half_open"
    assert not b.allow(now=1.5)         # single half-open probe
    b.failure(now=1.5)
    assert b.state == "open" and b.down_for(now=1.5) > 1.5  # doubled
    assert b.allow(now=4.0)
    b.success()
    assert b.state == "closed" and b.trips == 0


def test_router_unsafe_reason_vetoes():
    assert router_unsafe_reason({}, "completion") is None
    assert router_unsafe_reason({"n": 1, "best_of": 1}, "chat") is None
    assert "multi-choice" in router_unsafe_reason({"n": 2}, "completion")
    assert "multi-choice" in router_unsafe_reason(
        {"best_of": 3}, "completion")
    assert "tool-call" in router_unsafe_reason(
        {"tools": [{}], "tool_choice": "auto"}, "chat")
    assert router_unsafe_reason(
        {"tools": [{}], "tool_choice": "none"}, "chat") is None


def test_stream_journal_semantics():
    j = StreamJournal()
    e = j.open(StreamEntry(rid="r1", kind="completion",
                           body={"prompt": [1]}, replica="a:1"))
    j.open(StreamEntry(rid="r2", kind="chat", body={}, replica="b:2"))
    assert len(j) == 2
    assert [x.rid for x in j.by_replica("a:1")] == ["r1"]
    # nothing delivered → restartable, but no continuation payload
    assert e.can_restart and e.continuation_payload() is None
    e.prompt_token_ids = [1, 2]
    e.delivered_events = 3
    e.committed.extend([7, 8])
    cp = e.continuation_payload()
    assert cp == {"prompt_token_ids": [1, 2],
                  "committed_token_ids": [7, 8]}
    assert not e.can_restart
    assert j.close("r1") is e and len(j) == 1 and j.close("rX") is None


def _fake_set(states):
    rs = ReplicaSet([f"127.0.0.1:{10000 + i}"
                     for i in range(len(states))],
                    start_poller=False, initial_probe=False)
    for rep, st in zip(rs.replicas.values(), states):
        rep.state = st
    return rs


def test_placement_rotation_and_load():
    rs = _fake_set(["ready", "recovering", "ready", "down"])
    reps = list(rs.replicas.values())
    reps[0].active_streams = 3
    reps[2].active_streams = 1
    p = Placement(rs)
    # only ready replicas are candidates; least-loaded wins
    assert p.pick() is reps[2]
    # exclusion (failover must not bounce back)
    assert p.pick(exclude={reps[2].addr}) is reps[0]
    # draining leaves rotation
    rs.drain(reps[2].addr)
    assert p.pick() is reps[0]
    rs.drain(reps[2].addr, on=False)
    assert p.pick() is reps[2]
    # nothing ready → None
    for rep in reps:
        rep.state = "down"
    assert p.pick() is None


def test_placement_session_affinity_sticky():
    rs = _fake_set(["ready", "ready"])
    reps = list(rs.replicas.values())
    p = Placement(rs)
    first = p.pick(session="alice")
    # load now favors the other replica, but the session sticks
    first.active_streams = 5
    assert p.pick(session="alice") is first
    assert p.pick(session="bob") is not first
    # stickiness breaks when the replica leaves rotation
    first.state = "down"
    assert p.pick(session="alice") is not first


def test_prefix_affinity_digest_probe():
    """The item-4 placement skeleton: chained page digests probed over
    the peer protocol's ``has`` op pick the replica holding the deepest
    prefix."""
    from gllm_tpu.kvstore.peer import PeerPrefixServer
    from gllm_tpu.memory_manager import prefix_digests
    page = 4
    tokens = list(range(1, 13))          # 12 tokens → 2 whole pages
    digests = prefix_digests(tokens, len(tokens), page)
    assert len(digests) == 2
    held = {digests[0][0]}               # replica holds page 1 only
    srv = PeerPrefixServer(
        lambda d: b"x" if d in held else None, {"page_size": page},
        host="127.0.0.1", port=0)
    try:
        rep = Replica("127.0.0.1:9")     # port unused by the probe
        rep.info = {"page_size": page,
                    "prefix_store": {"serve_port": srv.port}}
        aff = PrefixAffinity(timeout_s=1.0)
        assert aff.score(rep, tokens) == 1     # depth of deepest hit
        held.add(digests[1][0])
        assert aff.score(rep, tokens) == 2
        bare = Replica("127.0.0.1:9")          # no store advertised
        bare.info = {"page_size": page, "prefix_store": {}}
        assert aff.score(bare, tokens) == 0
    finally:
        srv.close()


# ---- api_server satellites --------------------------------------------------

def test_server_info_replica_identity(fleet):
    status, info, _ = get_json(fleet[0]["port"], "/server_info")
    assert status == 200
    rep = info["replica"]
    assert rep["replica_id"] == fleet[0]["httpd"].state.replica_id
    assert rep["start_time"] > 0
    assert rep["engine_generation"] == 0
    assert rep["recoveries"] == 0


def test_fault_inject_endpoint_gated(fleet, monkeypatch):
    port = fleet[0]["port"]
    # off by default: the endpoint does not exist
    status, _, _ = post_json(port, "/fault_inject", {"spec": ""})
    assert status == 404
    monkeypatch.setenv("GLLM_FAULT_INJECT_HTTP", "1")
    status, raw, _ = post_json(port, "/fault_inject",
                               {"spec": "intake_burst:0:1"})
    assert status == 200
    assert json.loads(raw)["armed"] == {"intake_burst": [0, 1]}
    # the armed point really fires on the live server
    status, _, _ = post_json(port, "/v1/completions", {
        "prompt": PROMPT, "max_tokens": 2, "temperature": 0})
    assert status == 429
    status, raw, _ = post_json(port, "/fault_inject", {"reset": True})
    assert status == 200 and json.loads(raw)["armed"] == {}
    status, raw, _ = post_json(port, "/fault_inject", {"spec": "bogus"})
    assert status == 400


def test_engine_continuation_byte_identity(fleet):
    """ServingEngine.submit_continuation resumes prompt+committed with
    the original prompt_len — the engine-level contract the router's
    failover rides (greedy and seeded)."""
    eng = fleet[0]["httpd"].state.engine
    for params in (GREEDY, SEEDED):
        sp = SamplingParams(**params)
        want_ids, want_text = [], []
        for c in eng.submit(list(PROMPT), SamplingParams(**params)):
            if c.token_id is not None:
                want_ids.append(c.token_id)
            want_text.append(c.text)
        k = 5
        got_ids, got_text = [], []
        h = eng.submit_continuation(list(PROMPT), want_ids[:k], sp)
        assert h.prompt_len == len(PROMPT)
        for c in h:
            if c.token_id is not None:
                got_ids.append(c.token_id)
            got_text.append(c.text)
        assert got_ids == want_ids[k:], params
        assert "".join(got_text) == "".join(want_text)[k:], params


# ---- the acceptance headline: mid-stream kill → byte-identical failover ----

def _clean_completion(fleet, params):
    body = {"prompt": PROMPT, "stream": True, **params}
    status, events = sse_stream(fleet[0]["port"], "/v1/completions", body)
    assert status == 200 and finish_of(events) == "length"
    return events


@pytest.mark.chaos
@pytest.mark.parametrize("params", [GREEDY, SEEDED],
                         ids=["greedy", "seeded"])
def test_failover_mid_stream_kill_byte_identical(fleet, router, params):
    """replica_kill hard-closes the serving connection mid-stream (the
    process-death shape); the router resumes the stream on the
    surviving replica via the continuation path and the client observes
    ONE stream, byte-identical to a clean run — zero lost, zero
    duplicated tokens."""
    want = _clean_completion(fleet, params)
    want_text = completion_text(want)
    assert len(want_text) == params["max_tokens"]   # stub: 1 char/token
    fr, port = router()
    FAULTS.arm("replica_kill:3:1")
    body = {"prompt": PROMPT, "stream": True, **params}
    status, events = sse_stream(port, "/v1/completions", body)
    assert status == 200
    assert FAULTS.hits.get("replica_kill") == 1, "kill never fired"
    assert finish_of(events) == "length"
    assert not error_events(events)
    got_text = completion_text(events)
    assert got_text == want_text, (
        f"stream diverged across failover: {got_text!r} vs "
        f"{want_text!r}")
    # one event per token: count equality = zero lost/duplicated
    assert len([e for e in events if "choices" in e]) == \
        len([e for e in want if "choices" in e])


@pytest.mark.chaos
def test_failover_survives_kills_on_every_replica(fleet, router):
    """A fault that follows the stream around (replica_kill fires once
    on EACH replica) must not exhaust the fleet: after every replica
    failed once, the router re-admits all but the most recent failure
    (the attempt budget still bounds the loop) — the stream completes
    byte-identically with TWO migrations."""
    want_text = completion_text(_clean_completion(fleet, GREEDY))
    fr, port = router()
    FAULTS.arm("replica_kill:3:2")     # fires on A, then again on B
    body = {"prompt": PROMPT, "stream": True, **GREEDY}
    status, events = sse_stream(port, "/v1/completions", body)
    assert status == 200
    assert FAULTS.hits.get("replica_kill") == 2
    assert finish_of(events) == "length"
    assert completion_text(events) == want_text
    assert not error_events(events)


@pytest.mark.chaos
def test_failover_chat_stream_role_not_duplicated(fleet, router):
    """Chat failover: the continuation must not re-emit the role
    preamble chunk; the merged stream carries exactly one."""
    body = {"messages": [{"role": "user", "content": "hello fleet"}],
            "stream": True, **GREEDY}
    status, want = sse_stream(fleet[0]["port"], "/v1/chat/completions",
                              body)
    assert status == 200
    fr, port = router()
    FAULTS.arm("replica_kill:4:1")
    status, events = sse_stream(port, "/v1/chat/completions", body)
    assert status == 200
    assert FAULTS.hits.get("replica_kill") == 1
    assert chat_text(events) == chat_text(want)
    roles = [e for e in events if "choices" in e
             and (e["choices"][0].get("delta") or {}).get("role")]
    assert len(roles) == 1
    assert finish_of(events) == "length"


@pytest.mark.chaos
def test_failover_on_replica_hang_idle_timeout(fleet, router):
    """replica_hang stalls the upstream mid-stream; the router's idle
    timeout declares the replica wedged and migrates the stream —
    byte-identical, no client-visible stall beyond the timeout."""
    want_text = completion_text(_clean_completion(fleet, GREEDY))
    fr, port = router(stream_idle_timeout_s=1.5)
    FAULTS.stall_s = 8.0
    try:
        FAULTS.arm("replica_hang:3:1")
        body = {"prompt": PROMPT, "stream": True, **GREEDY}
        t0 = time.monotonic()
        status, events = sse_stream(port, "/v1/completions", body)
        dt = time.monotonic() - t0
        assert status == 200
        assert FAULTS.hits.get("replica_hang") == 1
        assert completion_text(events) == want_text
        assert finish_of(events) == "length"
        # the client never waited out the full 8s stall
        assert dt < 7.0, f"hang failover took {dt:.1f}s"
    finally:
        FAULTS.stall_s = 2.0


@pytest.mark.chaos
def test_unsafe_stream_gets_terminal_error_with_retry_after(fleet,
                                                            router):
    """An unseeded sampled stream (replica preamble vetoes replay)
    killed mid-stream must NOT fail over: the client gets a terminal
    error chunk + an error event carrying retry_after."""
    fr, port = router()
    FAULTS.arm("replica_kill:2:1")
    body = {"prompt": PROMPT, "stream": True, "temperature": 0.9,
            "max_tokens": 24, "ignore_eos": True}
    status, events = sse_stream(port, "/v1/completions", body)
    assert status == 200
    assert FAULTS.hits.get("replica_kill") == 1
    assert finish_of(events) == "error"
    errs = error_events(events)
    assert errs, "terminal error event missing"
    err = errs[-1]["error"]
    assert "not replay-safe" in err["message"]
    assert err.get("retry_after", 0) >= 1.0


@pytest.mark.chaos
def test_fresh_request_restarts_even_when_unsafe(fleet, router):
    """An unsafe request that delivered NOTHING yet may still move to
    another replica (nothing to contradict): kill the connection before
    the first chunk is forwarded and the stream completes elsewhere."""
    fr, port = router()
    FAULTS.arm("replica_kill:0:1")     # fires before the first chunk
    body = {"prompt": PROMPT, "stream": True, "temperature": 0.9,
            "max_tokens": 8, "ignore_eos": True}
    status, events = sse_stream(port, "/v1/completions", body)
    assert status == 200
    assert finish_of(events) == "length"
    assert len(completion_text(events)) == 8


# ---- breaker-bounded probe cost / drain / restart detection ----------------

@pytest.mark.chaos
def test_dead_replica_costs_one_probe_per_window():
    """A crash-looping/dead replica costs the router at most ONE
    connection attempt per breaker window (the peer-breaker bound,
    fleet edition)."""
    import socket as _socket
    lst = _socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(8)
    port = lst.getsockname()[1]
    conns = []

    def accept_and_slam():
        while True:
            try:
                s, _ = lst.accept()
            except OSError:
                return
            conns.append(1)
            s.close()                  # RemoteDisconnected for the probe

    t = threading.Thread(target=accept_and_slam, daemon=True)
    t.start()
    rs = ReplicaSet([f"127.0.0.1:{port}"], probe_interval_s=0.02,
                    probe_timeout_s=0.5, breaker_base_s=10.0,
                    breaker_jitter=0.0)
    try:
        time.sleep(0.5)                # ~25 poll ticks
        rep = next(iter(rs.replicas.values()))
        assert rep.breaker.state == "open"
        assert rep.state == "down"
        assert len(conns) == 1, (
            f"{len(conns)} probes hit a dead replica inside one "
            "breaker window")
        assert not rep.in_rotation
    finally:
        rs.close()
        lst.close()


def test_drain_leaves_rotation_without_dropping_streams(fleet, router):
    """Admin drain takes a replica out of rotation; its in-flight
    stream finishes untouched and new requests land elsewhere."""
    want_text = completion_text(_clean_completion(fleet, GREEDY))
    fr, port = router()
    target = fleet[0]["addr"]
    box = {}

    def run_stream():
        box["resp"] = sse_stream(port, "/v1/completions",
                                 {"prompt": PROMPT, "stream": True,
                                  **GREEDY})

    t = threading.Thread(target=run_stream, daemon=True)
    t.start()
    status, raw, _ = post_json(port, "/admin/drain", {"replica": target})
    assert status == 200 and json.loads(raw)["draining"]
    t.join(timeout=60)
    assert not t.is_alive()
    status, events = box["resp"]
    assert status == 200 and finish_of(events) == "length"
    assert completion_text(events) == want_text
    # drained replica is out of rotation; requests still served
    rep = fr.replicas.get(target)
    assert not rep.in_rotation and rep.state == "ready"
    status, events = sse_stream(port, "/v1/completions",
                                {"prompt": PROMPT, "stream": True,
                                 **GREEDY})
    assert status == 200 and completion_text(events) == want_text
    status, raw, _ = post_json(port, "/admin/undrain",
                               {"replica": target})
    assert status == 200 and not json.loads(raw)["draining"]
    assert fr.replicas.get(target).in_rotation
    status, raw, _ = post_json(port, "/admin/drain",
                               {"replica": "nonsense:1"})
    assert status == 404


def test_silent_restart_detected_via_identity(fleet, router):
    """A changed replica_id at the same address (process restart) is
    detected explicitly and counted; a mere engine-generation bump (a
    supervised in-process recovery) is not a restart."""
    fr, port = router(probe_interval_s=0.05)
    rep = fr.replicas.get(fleet[1]["addr"])
    deadline = time.monotonic() + 5
    while rep.identity is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert rep.identity is not None
    old_id = fleet[1]["httpd"].state.replica_id
    try:
        fleet[1]["httpd"].state.replica_id = "restarted-process"
        deadline = time.monotonic() + 5
        while rep.restarts == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert rep.restarts == 1
        status, info, _ = get_json(port, "/router_info")
        rh = [r for r in info["replicas"]
              if r["addr"] == fleet[1]["addr"]][0]
        assert rh["restarts_detected"] == 1
        assert rh["replica_id"] == "restarted-process"
    finally:
        fleet[1]["httpd"].state.replica_id = old_id


# ---- router health surface / proxying --------------------------------------

def test_router_readyz_and_info(fleet, router):
    fr, port = router()
    status, body, _ = get_json(port, "/healthz")
    assert status == 200
    status, body, _ = get_json(port, "/readyz")
    assert status == 200 and body["replicas_in_rotation"] == 2
    status, info, _ = get_json(port, "/router_info")
    assert info["ready"] and len(info["replicas"]) == 2
    for r in info["replicas"]:
        assert r["breaker"]["state"] == "closed"
    # all drained → not ready, Retry-After present
    for r in fleet:
        fr.replicas.drain(r["addr"])
    status, body, headers = get_json(port, "/readyz")
    assert status == 503 and "Retry-After" in headers
    for r in fleet:
        fr.replicas.drain(r["addr"], on=False)


def test_router_nonstream_proxy_and_failover(fleet, router):
    """Non-streaming requests proxy through; a dead first-choice
    replica is skipped (nothing was delivered, any request may
    retry)."""
    fr, port = router()
    body = {"prompt": PROMPT, **GREEDY}
    status, raw, _ = post_json(port, "/v1/completions", body)
    assert status == 200
    d = json.loads(raw)
    assert d["choices"][0]["finish_reason"] == "length"
    want = d["choices"][0]["text"]
    # models proxy
    status, raw, _ = post_json(port, "/v1/completions", body)
    status, mraw, _ = get_json(port, "/v1/models")
    assert status == 200 and mraw["data"][0]["object"] == "model"
    # force first-choice replica down: mark state down router-side and
    # verify the OTHER replica answers identically
    first = fr.placement.pick()
    first.state = "down"
    try:
        status, raw, _ = post_json(port, "/v1/completions", body)
        assert status == 200
        assert json.loads(raw)["choices"][0]["text"] == want
    finally:
        first.state = "ready"


def test_sse_error_event_carries_retry_after_over_http(tiny_ckpt):
    """Satellite: the api_server SSE error path surfaces
    StreamChunk.retry_after — an unsafe stream dropped during a
    supervised recovery ends with an error event carrying the hint."""
    llm = make_llm(tiny_ckpt, engine_recovery=True, max_step_failures=1,
                   rebuild_backoff_s=0.02, rebuild_backoff_max_s=0.2)
    httpd = serve(llm, "127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        # warm, then stream an UNSEEDED sampled request (not
        # replay-safe) and crash the engine under it
        for c in httpd.state.engine.submit(
                list(PROMPT), SamplingParams(temperature=0.0,
                                             max_tokens=2,
                                             ignore_eos=True)):
            pass
        box = {}

        def run():
            box["resp"] = sse_stream(
                port, "/v1/completions",
                {"prompt": PROMPT, "stream": True, "temperature": 0.9,
                 "max_tokens": 64, "ignore_eos": True})

        th = threading.Thread(target=run, daemon=True)
        th.start()
        time.sleep(0.2)               # let a few tokens stream
        FAULTS.arm("step_exception:0:1")
        th.join(timeout=60)
        assert not th.is_alive()
        status, events = box["resp"]
        assert status == 200
        assert finish_of(events) == "error"
        errs = error_events(events)
        assert errs and errs[-1]["error"].get("retry_after", 0) > 0
        assert "not replay-safe" in errs[-1]["error"]["message"]
    finally:
        httpd.shutdown()
        httpd.state.engine.shutdown()
