"""Fused multi-step blocks RUNNING THROUGH mid-block length finishes.

A seq that reaches max_tokens inside a fused block goes inactive
(`ScheduledBatch.active_until`): the device freezes its position and
redirects its KV writes to the dummy page; the host discards its later
sampled tokens. The other rows keep the fused block. Oracle: outputs are
byte-identical to the non-overlapped engine on the same saved checkpoint
(the reference's overlap machinery — gllm scheduler.py:702-783 deferred
finalize — has no fused multi-step blocks at all; this is TPU-side
dispatch amortization for the remote-attached chip)."""

import numpy as np
import pytest
import torch

from gllm_tpu.config import CacheConfig, EngineConfig, SchedulerConfig
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(47)
    d = tmp_path_factory.mktemp("rt_llama")
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    return str(d)


def _cfg(model, overlap: bool, msd: int = 8,
         prefix_cache: bool = False) -> EngineConfig:
    return EngineConfig(
        model=model, dtype="float32", max_model_len=128,
        max_num_seqs=8, overlap_scheduling=overlap, multi_step_decode=msd,
        scheduler=SchedulerConfig(max_prefill_tokens=64, max_decode_seqs=8),
        cache=CacheConfig(page_size=4, num_pages=256,
                          enable_prefix_caching=prefix_cache))


def _workload():
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 120, size=int(n)).tolist()
               for n in (12, 33, 7, 21, 5, 17)]
    # staggered limits: deaths land INSIDE 8-step blocks at different
    # offsets (3 dies first, then 9, 14, ... while 40 keeps running)
    params = [SamplingParams(temperature=0.0, max_tokens=m, ignore_eos=True)
              for m in (23, 40, 9, 31, 3, 14)]
    return prompts, params


def _run(llm):
    prompts, params = _workload()
    outs = llm.generate(prompt_token_ids=prompts, sampling_params=params)
    mm = llm.memory_manager
    assert mm.num_free_pages == mm.allocator.num_total, \
        (mm.num_free_pages, mm.allocator.num_total)
    return [o.output_token_ids for o in outs]


def test_run_through_byte_identity(ckpt):
    base = _run(LLM(config=_cfg(ckpt, overlap=False)))
    fused = _run(LLM(config=_cfg(ckpt, overlap=True)))
    assert [len(t) for t in base] == [23, 40, 9, 31, 3, 14]
    assert base == fused


def test_run_through_blocks_form(ckpt, monkeypatch):
    """The staggered-finish workload must actually produce blocks that
    carry dead rows (active_until set), not collapse to singles."""
    seen = []
    from gllm_tpu import scheduler as sched_mod
    orig = sched_mod.Scheduler.schedule_chain

    def spy(self, prev, k_max, *a, **kw):
        chain = orig(self, prev, k_max, *a, **kw)
        if chain and chain[0].active_until is not None:
            seen.append(list(chain[0].active_until))
        return chain

    monkeypatch.setattr(sched_mod.Scheduler, "schedule_chain", spy)
    fused = _run(LLM(config=_cfg(ckpt, overlap=True)))
    assert [len(t) for t in fused] == [23, 40, 9, 31, 3, 14]
    assert seen, "no block ever carried a dead row"
    assert any(min(au) < max(au) for au in seen)


def test_no_zombie_chains_after_eos(ckpt, monkeypatch):
    """A seq finished by EOS (not length) while later links were in
    flight must never appear in a NEW chain: schedule_chain's status
    gate forces the sync re-form (zombie rows would allocate pages
    toward max_tokens and burn a batch slot on discarded tokens)."""
    from gllm_tpu.scheduler import SequenceStatus
    from gllm_tpu import scheduler as sched_mod
    orig = sched_mod.Scheduler.schedule_chain

    def spy(self, prev, k_max, *a, **kw):
        chain = orig(self, prev, k_max, *a, **kw)
        for b in chain:
            assert all(it.seq.status is SequenceStatus.RUNNING
                       for it in b.items)
        return chain

    monkeypatch.setattr(sched_mod.Scheduler, "schedule_chain", spy)
    llm = LLM(config=_cfg(ckpt, overlap=True))
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 120, size=int(n)).tolist()
               for n in (9, 14, 6, 11)]
    # eos_token_id=0 and a 128-vocab random model: greedy hits EOS well
    # before the 96-token cap for at least some seqs
    params = [SamplingParams(temperature=0.0, max_tokens=96)
              for _ in prompts]
    outs = llm.generate(prompt_token_ids=prompts, sampling_params=params)
    assert any(o.finish_reason == "stop" for o in outs), \
        [(o.finish_reason, o.num_output_tokens) for o in outs]
    mm = llm.memory_manager
    assert mm.num_free_pages == mm.allocator.num_total


def test_run_through_prefix_cache_intact(ckpt):
    """Dead-row dummy-page writes must not clobber cached pages: a warm
    rerun of the same prompts after fused blocks with mid-block deaths
    must reproduce the cold outputs from the re-used cached prefixes."""
    llm = LLM(config=_cfg(ckpt, overlap=True, prefix_cache=True))
    cold = _run(llm)
    warm = _run(llm)
    assert warm == cold
    assert llm.memory_manager.cache_hit_rate > 0.0
