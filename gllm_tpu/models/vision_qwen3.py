"""Qwen3-VL vision tower (ViT + interpolated pos-embed + deepstack mergers).

TPU-native re-design of the reference Qwen3 vision transformer
(/root/reference/gllm/models/qwen3_vl.py:193-434). Differences from the
Qwen2.5 tower (gllm_tpu/models/vision.py):

- **No window attention**: every block attends globally within each
  temporal frame (HF splits by cu_seqlens per frame); we mask by frame
  segment id, q-chunked above a size threshold like the 2.5 full layers.
- **LayerNorm (with bias) norms**, biased patch embed, non-gated MLP
  (linear_fc1 → act → linear_fc2) with ``gelu_pytorch_tanh``.
- **Learned position embeddings** bilinearly interpolated from a
  ``num_position_embeddings`` grid to the image grid (HF
  fast_pos_embed_interpolate); interpolation indices/weights are pure
  functions of (h, w) — precomputed per grid in numpy and lru-cached.
- **Deepstack**: after blocks listed in ``deepstack_visual_indexes`` a
  dedicated patch merger (post-shuffle LayerNorm) produces one extra
  feature level per merged token; the tower returns
  ``[L/mu, out*(1+n_levels)]`` = [main ‖ level0 ‖ level1 ‖ ...], which the
  LM splits into the embedding splice + per-layer residuals.

Weight layout is [in, out] (x @ W) like the LM modules; token order is the
HF processor's merge-grouped raster order throughout (no permutes needed).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]

# Frame-masked global attention materializes dense scores below this many
# tokens; above it the q axis is chunked (exact, O(L·chunk) memory).
_FULL_DENSE_MAX = 2048
_FULL_CHUNK = 128


@dataclasses.dataclass(frozen=True)
class VisionConfig3:
    depth: int
    hidden_size: int
    intermediate_size: int
    num_heads: int
    patch_size: int
    temporal_patch_size: int
    in_channels: int
    spatial_merge_size: int
    out_hidden_size: int
    num_position_embeddings: int
    deepstack_visual_indexes: Tuple[int, ...]
    hidden_act: str = "gelu_pytorch_tanh"
    norm_eps: float = 1e-6

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def merge_unit(self) -> int:
        return self.spatial_merge_size ** 2

    @property
    def patch_input_dim(self) -> int:
        return (self.in_channels * self.temporal_patch_size
                * self.patch_size ** 2)

    @property
    def num_grid_per_side(self) -> int:
        return int(self.num_position_embeddings ** 0.5)


def from_hf_vision_config(d: Dict[str, Any]) -> VisionConfig3:
    return VisionConfig3(
        depth=d.get("depth", 27),
        hidden_size=d.get("hidden_size", 1152),
        intermediate_size=d.get("intermediate_size", 4304),
        num_heads=d.get("num_heads", 16),
        patch_size=d.get("patch_size", 16),
        temporal_patch_size=d.get("temporal_patch_size", 2),
        in_channels=d.get("in_channels", 3),
        spatial_merge_size=d.get("spatial_merge_size", 2),
        out_hidden_size=d.get("out_hidden_size", 3584),
        num_position_embeddings=d.get("num_position_embeddings", 2304),
        deepstack_visual_indexes=tuple(
            d.get("deepstack_visual_indexes", (8, 16, 24))),
        hidden_act=d.get("hidden_act", "gelu_pytorch_tanh"),
    )


def _merger_params(key, cfg: VisionConfig3, dtype) -> Params:
    muH, out = cfg.merge_unit * cfg.hidden_size, cfg.out_hidden_size
    k1, k2 = jax.random.split(key)
    s = muH ** -0.5

    def w(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dtype)

    return {
        "norm_w": jnp.ones((muH,), dtype), "norm_b": jnp.zeros((muH,), dtype),
        "fc1_w": w(k1, (muH, muH)), "fc1_b": jnp.zeros((muH,), dtype),
        "fc2_w": w(k2, (muH, out)), "fc2_b": jnp.zeros((out,), dtype),
    }


def init_vision_params(cfg: VisionConfig3, seed: int = 0,
                       dtype=jnp.float32) -> Params:
    L, H, I = cfg.depth, cfg.hidden_size, cfg.intermediate_size
    key = jax.random.key(seed + 13)
    ks = iter(jax.random.split(key, 8 + len(cfg.deepstack_visual_indexes)))

    def w(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32)
                * scale).astype(dtype)

    s = H ** -0.5
    p: Params = {
        "patch_embed": w(next(ks), (cfg.patch_input_dim, H),
                         cfg.patch_input_dim ** -0.5),
        "patch_bias": jnp.zeros((H,), dtype),
        "pos_embed": w(next(ks), (cfg.num_position_embeddings, H), 0.02),
        "blocks": {
            "norm1_w": jnp.ones((L, H), dtype),
            "norm1_b": jnp.zeros((L, H), dtype),
            "norm2_w": jnp.ones((L, H), dtype),
            "norm2_b": jnp.zeros((L, H), dtype),
            "qkv_w": w(next(ks), (L, H, 3 * H), s),
            "qkv_b": jnp.zeros((L, 3 * H), dtype),
            "proj_w": w(next(ks), (L, H, H), s),
            "proj_b": jnp.zeros((L, H), dtype),
            "fc1_w": w(next(ks), (L, H, I), s),
            "fc1_b": jnp.zeros((L, I), dtype),
            "fc2_w": w(next(ks), (L, I, H), I ** -0.5),
            "fc2_b": jnp.zeros((L, H), dtype),
        },
        # main merger norms pre-shuffle over H (rows broadcast to mu*H so
        # one merger code path serves both)
        "merger": _merger_params(next(ks), cfg, dtype),
        "deepstack": [
            _merger_params(next(ks), cfg, dtype)
            for _ in cfg.deepstack_visual_indexes
        ],
    }
    # the MAIN merger's LayerNorm is over H (pre-shuffle); overwrite shape
    p["merger"]["norm_w"] = jnp.ones((cfg.hidden_size,), dtype)
    p["merger"]["norm_b"] = jnp.zeros((cfg.hidden_size,), dtype)
    return p


# ---------------------------------------------------------------------------
# Host precompute per (t, h, w) grid
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=512)
def _grid_precompute(t: int, h: int, w: int, merge: int, head_dim: int,
                     num_grid_per_side: int):
    """Static per-grid data in merge-grouped processor order:

    (pos_idx [4, L], pos_w [4, L] f32, seg [L] frame ids,
     cos/sin [L, head_dim] f32)

    Port of HF fast_pos_embed_interpolate + rot_pos_ids (qwen3_vl.py:
    289-389); everything here is a pure function of the grid.
    """
    L = t * h * w

    def merge_order(p2d):
        return p2d.reshape(h // merge, merge, w // merge, merge) \
                  .transpose(0, 2, 1, 3).reshape(-1)

    # --- bilinear pos-embed interpolation (per frame, tiled over t) ---
    side = num_grid_per_side
    h_idx = np.linspace(0, side - 1, h, dtype=np.float32)
    w_idx = np.linspace(0, side - 1, w, dtype=np.float32)
    h_floor = h_idx.astype(np.int64)
    w_floor = w_idx.astype(np.int64)
    h_ceil = np.minimum(h_floor + 1, side - 1)
    w_ceil = np.minimum(w_floor + 1, side - 1)
    dh = (h_idx - h_floor)[:, None]
    dw = (w_idx - w_floor)[None, :]
    w11 = dh * dw
    w10 = dh - w11
    w01 = dw - w11
    w00 = 1 - dh - w01
    hg = [h_floor, h_floor, h_ceil, h_ceil]
    wg = [w_floor, w_ceil, w_floor, w_ceil]
    idx = np.stack([(hg[i][:, None] * side + wg[i][None, :]).reshape(-1)
                    for i in range(4)])                     # [4, h*w]
    wts = np.stack([np.broadcast_to(x, (h, w)).reshape(-1)
                    for x in (w00, w01, w10, w11)])         # [4, h*w]
    # merge-grouped order, tiled over frames
    idx = np.stack([np.tile(merge_order(r), t) for r in idx])
    wts = np.stack([np.tile(merge_order(r), t) for r in wts])

    # --- frame segments ---
    seg = np.repeat(np.arange(t), h * w)

    # --- 2-D rotary ---
    hpos = np.broadcast_to(np.arange(h)[:, None], (h, w))
    wpos = np.broadcast_to(np.arange(w)[None, :], (h, w))
    hpos = np.tile(merge_order(hpos), t)
    wpos = np.tile(merge_order(wpos), t)
    inv_freq = 1.0 / (10000.0 ** (np.arange(0, head_dim // 2, 2,
                                            dtype=np.float64)
                                  / (head_dim // 2)))
    freqs = np.concatenate([hpos[:, None] * inv_freq[None, :],
                            wpos[:, None] * inv_freq[None, :]],
                           axis=-1)                         # [L, head_dim/2]
    emb = np.concatenate([freqs, freqs], axis=-1)           # [L, head_dim]
    return (idx.astype(np.int32), wts.astype(np.float32),
            seg.astype(np.int32), np.cos(emb).astype(np.float32),
            np.sin(emb).astype(np.float32))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return (((xf - mu) * jax.lax.rsqrt(var + eps)) * w + b).astype(x.dtype)


def _rope(a, cos, sin):
    """rotate-half rope over the full head dim (HF
    apply_rotary_pos_emb_vision). a: [L, nh, hd]; cos/sin: [L, hd]."""
    hd = a.shape[-1]
    af = a.astype(jnp.float32)
    half = jnp.concatenate([-af[..., hd // 2:], af[..., :hd // 2]], axis=-1)
    return (af * cos[:, None, :] + half * sin[:, None, :]).astype(a.dtype)


def _frame_attention(bp, x, cos, sin, seg, cfg: VisionConfig3):
    """Global attention masked to frame segments, q-chunked above
    _FULL_DENSE_MAX tokens (same scheme as vision.py's full layers)."""
    L, H = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    qkv = x @ bp["qkv_w"] + bp["qkv_b"]
    q, k, v = [a.reshape(L, nh, hd) for a in jnp.split(qkv, 3, axis=-1)]
    q, k = _rope(q, cos, sin), _rope(k, cos, sin)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    def attend(qb, segb):
        scores = jnp.einsum("qhd,khd->hqk", qb.astype(jnp.float32),
                            kf) * hd ** -0.5
        mask = segb[:, None] == seg[None, :]
        scores = jnp.where(mask[None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("hqk,khd->qhd", probs, vf)

    if L <= _FULL_DENSE_MAX:
        out = attend(q, seg)
    else:
        pad = (-L) % _FULL_CHUNK
        qp = jnp.pad(q, ((0, pad), (0, 0), (0, 0)))
        segp = jnp.pad(seg, (0, pad), constant_values=-1)
        nb = qp.shape[0] // _FULL_CHUNK
        out = jax.lax.map(
            lambda args: attend(*args),
            (qp.reshape(nb, _FULL_CHUNK, nh, hd),
             segp.reshape(nb, _FULL_CHUNK)))
        out = out.reshape(-1, nh, hd)[:L]
    out = out.reshape(L, H).astype(x.dtype)
    return out @ bp["proj_w"] + bp["proj_b"]


def _merger(mp, x, cfg: VisionConfig3, postshuffle: bool):
    """Patch merger (HF Qwen3VLVisionPatchMerger): LayerNorm over H
    pre-shuffle (main) or over mu*H post-shuffle (deepstack), then
    fc1 → exact GELU → fc2."""
    mu = cfg.merge_unit
    if postshuffle:
        x = x.reshape(-1, mu * cfg.hidden_size)
        x = _layer_norm(x, mp["norm_w"], mp["norm_b"], cfg.norm_eps)
    else:
        x = _layer_norm(x, mp["norm_w"], mp["norm_b"], cfg.norm_eps)
        x = x.reshape(-1, mu * cfg.hidden_size)
    x = x @ mp["fc1_w"] + mp["fc1_b"]
    x = jax.nn.gelu(x.astype(jnp.float32), approximate=False).astype(x.dtype)
    return x @ mp["fc2_w"] + mp["fc2_b"]


def _vit_jit(params, pixels, pos_idx, pos_w, seg, cos, sin,
             cfg: VisionConfig3):
    x = pixels @ params["patch_embed"] + params["patch_bias"]     # [L, H]
    pos = (params["pos_embed"][pos_idx].astype(jnp.float32)
           * pos_w[:, :, None]).sum(0)
    x = x + pos.astype(x.dtype)

    if cfg.hidden_act == "silu":
        act = jax.nn.silu
    else:           # gelu_pytorch_tanh
        act = functools.partial(jax.nn.gelu, approximate=True)

    ds_feats = []
    for i in range(cfg.depth):
        bp = jax.tree.map(lambda a: a[i], params["blocks"])
        h = _layer_norm(x, bp["norm1_w"], bp["norm1_b"], cfg.norm_eps)
        x = x + _frame_attention(bp, h, cos, sin, seg, cfg)
        h = _layer_norm(x, bp["norm2_w"], bp["norm2_b"], cfg.norm_eps)
        h = h @ bp["fc1_w"] + bp["fc1_b"]
        h = act(h.astype(jnp.float32)).astype(x.dtype)
        x = x + (h @ bp["fc2_w"] + bp["fc2_b"])
        if i in cfg.deepstack_visual_indexes:
            di = cfg.deepstack_visual_indexes.index(i)
            ds_feats.append(_merger(params["deepstack"][di], x, cfg,
                                    postshuffle=True))

    main = _merger(params["merger"], x, cfg, postshuffle=False)
    return jnp.concatenate([main] + ds_feats, axis=1)  # [L/mu, out*(1+n)]


_vit_jit = jax.jit(_vit_jit, static_argnames=("cfg",))


def embed_single(params: Params, cfg: VisionConfig3, pixels,
                 grid_thw: Tuple[int, int, int]) -> jnp.ndarray:
    """One image/frame item: pixels [t*h*w, C*tps*ps*ps] → merged visual
    embeddings [t*h*w/mu, out*(1+n_deepstack)]."""
    t, h, w = (int(v) for v in grid_thw)
    pos_idx, pos_w, seg, cos, sin = _grid_precompute(
        t, h, w, cfg.spatial_merge_size, cfg.head_dim,
        cfg.num_grid_per_side)
    return _vit_jit(params, jnp.asarray(pixels), jnp.asarray(pos_idx),
                    jnp.asarray(pos_w), jnp.asarray(seg),
                    jnp.asarray(cos), jnp.asarray(sin), cfg)
