"""Multi-host serving: host-0 frontend + deterministic request broadcast.

The reference's master/slave launch keeps one frontend and fans requests to
worker processes over zmq (/root/reference/gllm/comm.py:191-319,
llm_engine.py:198-211). Under jax multi-process SPMD the equivalent
invariant is stronger: EVERY process must issue the SAME sequence of jit
computations with the same shapes. We get it the single-controller way:

- every host runs an identical engine loop over identical scheduler state;
- host 0 additionally runs the HTTP frontend; each engine tick it
  broadcasts the newly-arrived request descriptors (and aborts) to all
  hosts (two-phase fixed-shape broadcast over the jax collective layer);
- schedulers are deterministic, so identical intake → identical schedules
  → identical jit calls on every host. No lockstep barriers beyond the
  intake broadcast.
"""

from __future__ import annotations

import dataclasses
import logging
import pickle
import time
from typing import List, Optional

import numpy as np

logger = logging.getLogger(__name__)


def broadcast_payload(obj) -> object:
    """Broadcast a picklable object from process 0 to all processes.

    Two-phase (length, then padded payload) so every process presents
    matching shapes to the collective.
    """
    import jax
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return obj
    if jax.process_index() == 0:
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    else:
        payload = np.zeros(0, np.uint8)
    n = multihost_utils.broadcast_one_to_all(
        np.asarray([payload.size], np.int64))
    size = int(n[0])
    buf = np.zeros(size, np.uint8)
    buf[:payload.size] = payload
    out = multihost_utils.broadcast_one_to_all(buf)
    return pickle.loads(out.tobytes())


@dataclasses.dataclass
class RequestDesc:
    """Wire form of one request (frontend → every host)."""
    seq_id: int
    token_ids: List[int]
    sampling: dict                       # dataclasses.asdict(SamplingParams)
    mm: Optional[dict] = None            # raw mm_input (pixel arrays ride
                                         # the pickle broadcast; every host
                                         # rebuilds the same MM state)


@dataclasses.dataclass
class Tick:
    """One intake broadcast: requests + aborts + shutdown flag."""
    requests: List[RequestDesc]
    aborts: List[int]
    shutdown: bool = False


class MultihostEngine:
    """Runs the engine loop on every host; host 0 feeds it requests.

    Host 0: call ``submit``/``abort`` from frontend threads, run
    ``run_host0`` on the engine thread. Hosts > 0: call ``run_follower``.
    Outputs surface only on host 0 (``on_output`` callback).
    """

    def __init__(self, llm, on_output=None, tick_interval: float = 0.002):
        import jax
        self.llm = llm
        self.on_output = on_output or (lambda out: None)
        self.tick_interval = tick_interval
        self.is_host0 = jax.process_index() == 0
        self._pending: List[RequestDesc] = []
        self._pending_aborts: List[int] = []
        self._seqs: dict = {}          # host-0: seq_id → allocated Sequence
        self._shutdown = False
        import threading
        self._lock = threading.Lock()

    # ---- host-0 frontend side ---------------------------------------------

    def submit(self, token_ids: List[int], sampling_params,
               on_register=None, mm_input: Optional[dict] = None) -> int:
        """``on_register(seq_id)`` runs under the intake lock BEFORE the
        request becomes visible to the engine loop — callers register
        their output handles there so no chunk can be dropped."""
        assert self.is_host0
        mm_state = None
        if mm_input:
            from gllm_tpu.engine.mm import build_mm_state
            mm_state = build_mm_state(token_ids, self.llm.model_cfg,
                                      **mm_input)
        with self._lock:
            seq = self.llm._allocate_seq(list(token_ids), sampling_params)
            seq.mm = mm_state
            if on_register is not None:
                on_register(seq.seq_id)
            self._pending.append(RequestDesc(
                seq.seq_id, list(token_ids),
                dataclasses.asdict(sampling_params), mm=mm_input))
            self._seqs[seq.seq_id] = seq
        return seq.seq_id

    def abort(self, seq_id: int) -> None:
        with self._lock:
            self._pending_aborts.append(seq_id)

    def shutdown(self) -> None:
        self._shutdown = True

    # ---- engine loop (every host) -----------------------------------------

    def _apply_tick(self, tick: Tick) -> None:
        from gllm_tpu.sampling_params import SamplingParams
        llm = self.llm
        for rd in tick.requests:
            if self.is_host0:
                seq = self._seqs.pop(rd.seq_id, None)
            else:
                sp = SamplingParams(**rd.sampling)
                seq = llm._allocate_seq(rd.token_ids, sp)
                # keep seq-id allocation identical across hosts
                seq.seq_id = rd.seq_id
                if rd.mm:
                    from gllm_tpu.engine.mm import build_mm_state
                    seq.mm = build_mm_state(rd.token_ids, llm.model_cfg,
                                            **rd.mm)
            try:
                llm.add_seq(seq)
            except ValueError as e:
                # deterministic on every host (same validation) — only
                # host 0 reports
                if self.is_host0:
                    self.on_output(("error", rd.seq_id, str(e)))
        for sid in tick.aborts:
            llm.abort(sid)

    def _loop(self) -> None:
        llm = self.llm
        while True:
            if self.is_host0:
                with self._lock:
                    tick = Tick(self._pending, self._pending_aborts,
                                self._shutdown)
                    self._pending = []
                    self._pending_aborts = []
            else:
                tick = None
            tick = broadcast_payload(tick)
            if tick.shutdown:
                return
            self._apply_tick(tick)
            if llm.has_unfinished:
                try:
                    outs = llm.step()
                except Exception:
                    # deterministic loops fail identically on every host;
                    # report on host 0 and drain to a clean shutdown tick
                    logger.exception("engine step failed")
                    if self.is_host0:
                        self.on_output(("fail", None))
                        self._shutdown = True
                    continue
                if self.is_host0:
                    for out in outs:
                        self.on_output(("out", out))
            else:
                time.sleep(self.tick_interval)

    def run_host0(self) -> None:
        assert self.is_host0
        self._loop()

    def run_follower(self) -> None:
        assert not self.is_host0
        self._loop()


class MultihostServingEngine:
    """ServingEngine-compatible frontend over MultihostEngine (host 0).

    The HTTP handlers use the same submit/abort/shutdown surface and
    per-request chunk queues as the single-host ServingEngine.
    """

    def __init__(self, llm):
        import threading

        from gllm_tpu.engine.serving_engine import (RequestHandle,
                                                    deliver_output)
        self.llm = llm
        self._handles = {}
        self._emitted: dict = {}
        self._deliver = deliver_output
        self._make_handle = RequestHandle

        def on_output(evt):
            from gllm_tpu.engine.serving_engine import StreamChunk
            if evt[0] == "error":
                _, sid, reason = evt
                h = self._handles.pop(sid, None)
                if h is not None:
                    h.chunks.put(StreamChunk(None, "", reason or "error"))
                return
            if evt[0] == "fail":
                for h in list(self._handles.values()):
                    h.chunks.put(StreamChunk(None, "", "error"))
                self._handles.clear()
                self._emitted.clear()
                return
            out = evt[1]
            h = self._handles.get(out.seq.seq_id)
            if h is None:
                return
            self._deliver(self.llm, out, h, self._emitted)
            if out.finish_reason is not None:
                self._handles.pop(out.seq.seq_id, None)

        self.engine = MultihostEngine(llm, on_output=on_output)
        self._thread = threading.Thread(target=self.engine.run_host0,
                                        daemon=True, name="gllm-mh-engine")
        self._thread.start()

    def submit(self, token_ids, sampling_params, mm_input=None,
               disagg_items=None):
        if disagg_items:
            raise NotImplementedError(
                "encoder disaggregation over multi-host is not wired up "
                "yet (run the disagg coordinator single-host)")
        sampling_params.validate()
        box = {}

        def on_register(sid):
            # under the intake lock, before the engine loop can see the
            # request — no output chunk can race past the handle
            box["handle"] = self._make_handle(sid, len(token_ids))
            self._handles[sid] = box["handle"]

        self.engine.submit(token_ids, sampling_params,
                           on_register=on_register, mm_input=mm_input)
        return box["handle"]

    def abort(self, seq_id: int) -> None:
        self.engine.abort(seq_id)
        # aborted seqs produce no further SeqOutput — close the stream now
        h = self._handles.pop(seq_id, None)
        self._emitted.pop(seq_id, None)
        if h is not None:
            from gllm_tpu.engine.serving_engine import StreamChunk
            h.chunks.put(StreamChunk(None, "", "abort"))

    def shutdown(self) -> None:
        self.engine.shutdown()
        self._thread.join(timeout=10)
