"""sp (sequence/context parallelism) as a SERVING axis — VERDICT r03
missing #5.

The reference has no CP at all (SURVEY.md §2.2); here a long single-seq
from-position-0 prefill chunk routes through causal ring attention over
the ``sp`` mesh axis (parallel/ring_attention.py) while decode and mixed
batches keep the paged path. Oracle: greedy byte-identity vs the
single-device engine, through the full engine (prefill → ring, decode →
paged against the KV the ring step wrote).
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                             SchedulerConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(17)
    d = tmp_path_factory.mktemp("sp_model")
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=512, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    return str(d)


def make_llm(ckpt, sp=1, tp=1, threshold=16, maxp=128, prefix=False):
    return LLM(config=EngineConfig(
        model=ckpt, dtype="float32", max_model_len=256,
        sp_ring_threshold=threshold,
        scheduler=SchedulerConfig(max_prefill_tokens=maxp),
        cache=CacheConfig(page_size=4, num_pages=128,
                          enable_prefix_caching=prefix),
        parallel=ParallelConfig(sp=sp, tp=tp)))


def greedy(llm, prompts, n=8):
    sp = SamplingParams(temperature=0.0, max_tokens=n, ignore_eos=True)
    return [o.output_token_ids
            for o in llm.generate(prompt_token_ids=[list(p)
                                                    for p in prompts],
                                  sampling_params=sp)]


def test_sp2_long_prefill_byte_identity(ckpt):
    """One long prompt (ring prefill) then decode — matches sp=1."""
    prompt = [int(1 + (i * 11) % 120) for i in range(60)]
    want = greedy(make_llm(ckpt), [prompt])
    got = greedy(make_llm(ckpt, sp=2), [prompt])
    assert got == want


def test_sp2_tp2_composes(ckpt):
    prompt = [int(1 + (i * 13) % 120) for i in range(48)]
    want = greedy(make_llm(ckpt), [prompt])
    got = greedy(make_llm(ckpt, sp=2, tp=2), [prompt])
    assert got == want


def test_sp2_mixed_batch_falls_back(ckpt):
    """Several seqs (mixed batch → paged path, activations still sharded
    over the sp mesh) stay byte-identical."""
    rng = np.random.default_rng(4)
    prompts = [[int(x) for x in rng.integers(2, 120, size=int(n))]
               for n in (40, 7, 25)]
    want = greedy(make_llm(ckpt), prompts)
    got = greedy(make_llm(ckpt, sp=2), prompts)
    assert got == want


def test_sp2_chunked_prefill_later_chunks_paged(ckpt):
    """max_prefill_tokens smaller than the prompt: the first chunk rides
    the ring, later chunks attend the cached prefix via the paged path."""
    prompt = [int(1 + (i * 7) % 120) for i in range(100)]
    want = greedy(make_llm(ckpt), [prompt])
    got = greedy(make_llm(ckpt, sp=2, maxp=64), [prompt])
    assert got == want


def test_ring_routing_decision(ckpt):
    """_use_ring routes only single-seq from-0 long chunks."""
    llm = make_llm(ckpt, sp=2, threshold=16)
    runner = llm.runner
    llm1 = make_llm(ckpt)            # sp=1 engine: never rings

    class It:
        def __init__(self, before, new):
            self.computed_before = before
            self.num_new_tokens = new
            self.draft_tokens = ()

    class B:
        def __init__(self, items):
            self.items = items

    assert runner._use_ring(B([It(0, 64)]), 64)
    assert not runner._use_ring(B([It(0, 8)]), 8)          # below threshold
    assert not runner._use_ring(B([It(16, 64)]), 64)       # cached prefix
    assert not runner._use_ring(B([It(0, 64), It(0, 64)]), 128)  # mixed
    assert not runner._use_ring(B([It(0, 63)]), 63)        # pad not % sp
    assert not llm1.runner._use_ring(B([It(0, 64)]), 64)


def test_sp_requires_no_pp_dp():
    with pytest.raises(ValueError):
        EngineConfig(parallel=ParallelConfig(sp=2, dp=2)).validate()
    with pytest.raises(ValueError):
        EngineConfig(parallel=ParallelConfig(sp=2, pp=2)).validate()


def test_sp2_prefix_cache_cold_warm(ckpt):
    """Ring-prefill writes KV that the prefix cache registers; a warm
    re-run (cache hit → shorter from-nonzero chunk → paged path) stays
    byte-identical to sp=1."""
    prompt = [int(1 + (i * 11) % 120) for i in range(60)]
    want = greedy(make_llm(ckpt), [prompt])
    llm = make_llm(ckpt, sp=2, prefix=True)
    cold = greedy(llm, [prompt])
    warm = greedy(llm, [prompt])
    assert cold == want and warm == want
