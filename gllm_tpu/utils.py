"""Small shared helpers (shape bucketing, math).

The bucketing helpers implement the static-shape discipline XLA wants: every
jit-compiled step function sees only a small set of padded shapes, mirroring the
reference engine's power-of-two CUDA-graph buckets
(/root/reference/gllm/model_runner.py:471-489).
"""

from __future__ import annotations


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up(x: int, multiple: int) -> int:
    return cdiv(x, multiple) * multiple


def next_pow2(x: int, minimum: int = 1) -> int:
    """Smallest power of two >= max(x, minimum)."""
    v = max(x, minimum, 1)
    return 1 << (v - 1).bit_length()


def bucket_size(x: int, minimum: int, maximum: int) -> int:
    """Pad ``x`` to a power-of-two bucket, clamped to [minimum, maximum].

    Keeps the number of distinct compiled shapes logarithmic in the range —
    the XLA-compilation-cache analogue of the reference's CUDA-graph bucket
    table (/root/reference/gllm/model_runner.py:1525-1615).
    """
    if x > maximum:
        raise ValueError(f"size {x} exceeds maximum bucket {maximum}")
    return min(next_pow2(x, minimum), maximum)
