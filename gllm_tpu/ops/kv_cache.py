"""Paged KV cache device arrays + write path.

TPU-native equivalent of the reference's reshape_and_cache_flash Triton kernel
(/root/reference/gllm/layers/ops/cache_kernels.py): new K/V rows are scattered
into the paged cache at per-token flat slot indices. Under jit with buffer
donation the scatter lowers to an in-place dynamic-update — no cache copy
(SURVEY.md §7 hard part 4).

Layout: [num_pages, page_size, num_kv_heads, head_dim] per layer per K/V.
Flat slot = page_id * page_size + offset; slot 0..page_size-1 live in the
dummy page (page 0) and absorb writes from padded tokens.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# int8 KV quantization grid: symmetric, scale = absmax / QMAX, dequant
# x' = q * scale. -128 is never produced (clip to ±127) so the grid is
# symmetric and the rescale-on-grow pass cannot overflow.
QMAX = 127.0


def write_kv(k_cache: jnp.ndarray, v_cache: jnp.ndarray,
             k: jnp.ndarray, v: jnp.ndarray,
             slot_mapping: jnp.ndarray):
    """Scatter new K/V rows into the paged cache.

    k_cache/v_cache: [num_pages, page_size, Hkv, D]
    k/v:             [T, Hkv, D] (this step's projected keys/values, post-rope)
    slot_mapping:    [T] int32 flat slots (padding → dummy-page slots)
    """
    num_pages, page_size, hkv, d = k_cache.shape
    # Packed lane layout (runner kv_pack>1: cache is [P, ps, Hkv/pack,
    # D*pack] so Mosaic's 128-lane tiling holds for head_dim<128): the new
    # rows fold into the cache's trailing shape — row-major contiguity
    # makes the reshape exact.
    T = k.shape[0]
    flat_k = k_cache.reshape(num_pages * page_size, hkv, d)
    flat_v = v_cache.reshape(num_pages * page_size, hkv, d)
    flat_k = flat_k.at[slot_mapping].set(
        k.reshape(T, hkv, d).astype(flat_k.dtype))
    flat_v = flat_v.at[slot_mapping].set(
        v.reshape(T, hkv, d).astype(flat_v.dtype))
    return (flat_k.reshape(k_cache.shape), flat_v.reshape(v_cache.shape))


def _quant_write_one(cache, scale, rows, slot_mapping, pages):
    """Quantized scatter for one stream (K or V).

    cache: [num_pages, page_size, H, D] int8; scale: [num_pages, H] f32
    (scale s means a stored q dequantizes to q * s); rows: [T, H, D] f32.

    The per-page per-head scale is a RUNNING absmax: it only grows. When
    a write grows a page's scale, rows already stored in that page were
    quantized against the smaller scale, so the touched pages are
    re-quantized in place (gather → scale by old/new → round → scatter)
    before the new rows land. The rescale gather/scatter is wrapped in a
    ``lax.cond``: in steady-state decode scales almost never grow, so the
    hot path pays only the scatter-max and the row quantization.

    A never-written page has scale 0 and rescales by ratio 0 on its
    first write, which zero-fills the stale slots as a side effect.
    Recycled pages keep their old tenant's scale, so a new tenant
    quantizes against max(stale, own) — a bounded precision cost, never
    a correctness one (see docs/kv_quantization.md).
    """
    num_pages, ps, h, d = cache.shape
    amax = jnp.max(jnp.abs(rows), axis=-1) / QMAX            # [T, H]
    old = scale[pages]                                       # [T, H]
    new_scale = scale.at[pages].max(amax)
    new = new_scale[pages]                                   # [T, H]

    def rescale(c):
        # duplicate page ids gather/scatter identical values — exact
        blk = c[pages].astype(jnp.float32)                   # [T, ps, H, D]
        ratio = jnp.where(new > 0.0, old / jnp.maximum(new, 1e-30), 0.0)
        blk = jnp.round(blk * ratio[:, None, :, None])
        return c.at[pages].set(blk.astype(cache.dtype))

    cache = jax.lax.cond(jnp.any(new > old), rescale, lambda c: c, cache)
    q = jnp.round(rows / jnp.maximum(new, 1e-30)[:, :, None])
    q = jnp.clip(q, -QMAX, QMAX).astype(cache.dtype)
    flat = cache.reshape(num_pages * ps, h, d)
    flat = flat.at[slot_mapping].set(q)
    return flat.reshape(cache.shape), new_scale


def write_kv_quant(k_cache, v_cache, k_scale, v_scale,
                   k: jnp.ndarray, v: jnp.ndarray,
                   slot_mapping: jnp.ndarray, page_size: int):
    """Quantizing scatter into an int8 paged cache (kv_cache_dtype=int8).

    k_cache/v_cache: [num_pages, page_size, H, D] int8 (H/D are the
    CACHE's trailing dims — under kv_pack > 1 that is the packed layout,
    so the scale is shared by the packed head group).
    k_scale/v_scale: [num_pages, H] f32 running per-page per-head scales.
    k/v:             [T, Hkv, D'] new rows (any float dtype).
    slot_mapping:    [T] int32 flat slots (padding → dummy-page slots).

    Returns (k_cache, v_cache, k_scale, v_scale). Attention dequantizes
    in-kernel (ops/pallas/*) or on the gathered pages (the XLA oracle) —
    the full-precision cache never exists in HBM.
    """
    num_pages, ps, h, d = k_cache.shape
    T = k.shape[0]
    pages = slot_mapping // page_size
    k_cache, k_scale = _quant_write_one(
        k_cache, k_scale, k.reshape(T, h, d).astype(jnp.float32),
        slot_mapping, pages)
    v_cache, v_scale = _quant_write_one(
        v_cache, v_scale, v.reshape(T, h, d).astype(jnp.float32),
        slot_mapping, pages)
    return k_cache, v_cache, k_scale, v_scale
