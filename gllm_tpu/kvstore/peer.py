"""Cluster tier of the prefix KV store: digest-addressed page exchange.

Modeled on the multihost blob channel (docs/multihost_blob_channel.md):
the same content-addressed pull protocol, the same "a vanished peer
degrades the path, never fails it" posture. The difference is the payload — KV prefix
pages instead of media blobs — which adds two obligations:

- **geometry negotiation.** A fetched page is written straight into the
  local host pool, so both sides must agree on page size, per-leaf
  shapes, and kv dtype (an int8-KV replica's pages are half the bytes of
  a bf16 replica's and mean different numbers). The first exchange on a
  connection is ``hello`` → the server's ``pagefmt.pool_geometry``; any
  mismatch disables that peer for the life of the client.
- **verification at the trust boundary.** The server ships payloads
  unverified (it may be streaming straight off its disk tier); the
  CLIENT unpacks against its own geometry and checks digest + canary
  before anything touches the pool. A bad payload is a miss, never an
  exception on the scheduling path.

Probe-latency contract: ``fetch`` is bounded by ``timeout_s`` per live
peer (connect + request + response all under one socket deadline) and a
failing peer trips a real per-peer CIRCUIT BREAKER — exponential
backoff with jitter, half-open single-probe recovery, per-peer health
counters — so the scheduler's match_prefix walk can never stall on the
network and a FLAPPING peer costs one probe per backoff window instead
of a periodic stall-and-retry. The ``peer_prefix_timeout`` and
``peer_flap`` chaos points prove the degrade and breaker paths in
tests; knobs: ``GLLM_PREFIX_PEER_BACKOFF_S`` (base, default 30),
``GLLM_PREFIX_PEER_BACKOFF_MAX_S`` (cap, default 300),
``GLLM_PREFIX_PEER_FAILS`` (consecutive failures to trip, default 1),
``GLLM_PREFIX_PEER_JITTER`` (fraction, default 0.1).

Wire framing is deliberately NOT the pickle framing of
``disagg/wire.py`` (that plane runs between mutually trusting processes
of one deployment): control frames here are ``[u32 len][JSON utf-8]``
and page payloads are the raw ``pagefmt`` bytes — nothing received from
a peer is ever unpickled, so a hostile or compromised peer can feed us
at worst a payload that fails digest/canary/geometry verification.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import socketserver
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from gllm_tpu.faults import FAULTS
from gllm_tpu.kvstore import stats
from gllm_tpu.kvstore.pagefmt import verify_payload
from gllm_tpu.utils import CircuitBreaker

logger = logging.getLogger(__name__)

# Provider signature: digest -> packed payload (or None). The manager
# backs this with host pool + disk tier.
Provider = Callable[[bytes], Optional[bytes]]

_LEN = struct.Struct("!I")
_MAX_FRAME = 1 << 20            # control frames are tiny; cap hostile ones


def _send_frame(sock: socket.socket, obj: dict,
                raw: Optional[bytes] = None) -> None:
    """``[u32][json]`` control frame, optionally followed by
    ``[u32][raw bytes]`` (the pagefmt payload, shipped un-decoded)."""
    body = json.dumps(obj, separators=(",", ":")).encode()
    parts = [_LEN.pack(len(body)), body]
    if raw is not None:
        parts += [_LEN.pack(len(raw)), raw]
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None) -> Optional[bytes]:
    """Like ``disagg/wire._recv_exact`` but DEADLINE-aware: the per-op
    socket timeout alone lets a slow-dribbling peer stretch one logical
    read to (bytes / chunk) × timeout — here the remaining wall budget
    re-arms the socket timeout before every chunk, so the WHOLE read is
    bounded (the reason this is not shared with wire.py, whose trusted
    plane wants blocking reads)."""
    buf = bytearray()
    while len(buf) < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("peer read deadline exceeded")
            sock.settimeout(remaining)
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket, limit: int = _MAX_FRAME,
                deadline: Optional[float] = None) -> Optional[dict]:
    head = _recv_exact(sock, _LEN.size, deadline)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > limit:
        raise OSError(f"oversized peer frame ({n} B)")
    body = _recv_exact(sock, n, deadline)
    if body is None:
        return None
    obj = json.loads(body.decode())
    if not isinstance(obj, dict):
        raise OSError("peer frame is not an object")
    return obj


def _recv_payload(sock: socket.socket, limit: int,
                  deadline: Optional[float] = None) -> Optional[bytes]:
    head = _recv_exact(sock, _LEN.size, deadline)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > limit:
        raise OSError(f"oversized peer payload ({n} B)")
    return _recv_exact(sock, n, deadline)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        logger.warning("bad %s=%r; using %s", name,
                       os.environ.get(name), default)
        return default


# The per-peer circuit breaker is the shared gllm_tpu.utils ladder:
# the fleet front router (gllm_tpu/router/) runs the exact same
# closed → open (exponential backoff ± jitter) → half-open-single-probe
# state machine per serving replica, so the class lives where both
# planes can reach it. The PeerBreaker name stays as the kvstore-facing
# alias (docs/robustness.md#peer-breakers).
PeerBreaker = CircuitBreaker


def parse_peer_addr(addr: str) -> Tuple[str, int]:
    """``host:port`` → validated pair; raises ``ValueError`` on a
    malformed entry (checked at construction/config time so a typo in
    ``--prefix-peers`` fails startup, not the first scheduling probe)."""
    host, sep, port = addr.strip().rpartition(":")
    if not sep or not host:
        raise ValueError(f"peer address {addr!r} is not host:port")
    try:
        port_n = int(port)
    except ValueError:
        raise ValueError(f"peer address {addr!r} has a non-numeric port")
    if not 0 < port_n < 65536:
        raise ValueError(f"peer address {addr!r} port out of range")
    return host, port_n


class PeerPrefixServer:
    """Prefix-page endpoint over this replica's host + disk tiers. One
    of these per serving replica (``--prefix-serve-port``); other
    replicas point ``--prefix-peers`` at it. Pull ops (``has``/``get``)
    are read-only; the ``push`` op (pd-pool KV handoff,
    docs/pd_pools.md) accepts pages INTO the host pool through the
    owner-supplied ``accept`` callback, which verifies digest + canary
    against local geometry before a byte touches the pool."""

    IDLE_S = 60.0

    def __init__(self, provider: Provider, geometry: dict,
                 host: str = "0.0.0.0", port: int = 0,
                 contains: Optional[Callable[[bytes], bool]] = None,
                 accept: Optional[Callable[[bytes, list, bytes],
                                           bool]] = None):
        self._provider = provider
        # cheap membership for the ``has`` placement probe; falls back
        # to materializing via the provider when the owner has no index
        self._contains = contains
        # push sink: (digest, tokens, payload) -> accepted. None keeps
        # the endpoint pull-only (pushes are rejected, not errors).
        self._accept = accept
        self._geometry = geometry
        from gllm_tpu.kvstore.pagefmt import geometry_bytes
        try:
            self._push_limit = geometry_bytes(geometry) + 4096
        except (KeyError, TypeError):
            # hello only ever COMPARES geometry, so pull-only servers
            # (placement `has` probes) may run on an opaque dict; with
            # no page-size budget derivable, pushes stay frame-capped
            self._push_limit = _MAX_FRAME
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.request.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                # idle bound: a connection that sends nothing (port
                # scanner, wedged client) releases its handler thread
                # and fd instead of pinning them forever
                self.request.settimeout(PeerPrefixServer.IDLE_S)
                while True:
                    try:
                        msg = _recv_frame(self.request)
                        if msg is None:
                            return
                        outer._on_req(msg, self.request)
                    except (OSError, ValueError):
                        # idle timeout, hostile frame, or the client
                        # hanging up mid-reply (its fetch deadline is
                        # shorter than a slow send) — routine, not an
                        # error: just drop the connection
                        return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Server((host, port), _Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        logger.info("prefix peer server on port %d", self.port)

    def _on_req(self, msg: dict, sock) -> None:
        op = msg.get("op")
        if op == "hello":
            _send_frame(sock, {"geometry": self._geometry})
        elif op == "has":
            # membership probe (no payload): the front router's
            # prefix-affinity placement asks each candidate replica
            # which of a prompt's chained page digests it holds
            # (gllm_tpu/router/placement.py) — the item-4 digest-probe
            # placement skeleton. Index lookups only when the owner
            # supplied a ``contains`` callback (the manager does) —
            # this sits on the router's placement path and must never
            # export/pack a page or touch the disk payload.
            try:
                digest = bytes.fromhex(msg.get("digest", ""))
                if self._contains is not None:
                    hit = bool(self._contains(digest))
                else:
                    hit = self._provider(digest) is not None
            except Exception:
                hit = False
            _send_frame(sock, {"hit": hit})
        elif op == "get":
            try:
                digest = bytes.fromhex(msg.get("digest", ""))
            except (TypeError, ValueError):
                _send_frame(sock, {"hit": False}, raw=b"")
                return
            try:
                payload = self._provider(digest)
            except Exception:            # serving must never kill the conn
                logger.exception("prefix serve failed for %s",
                                 msg.get("digest"))
                payload = None
            if payload is not None:
                stats.PEER_SERVED.inc()
                stats.BYTES.inc(len(payload), tier="peer", dir="write")
            _send_frame(sock, {"hit": payload is not None},
                        raw=payload or b"")
        elif op == "push":
            # pd-pool KV handoff (docs/pd_pools.md): the control frame
            # carries digest + canary tokens, the raw frame the pagefmt
            # payload. The payload frame is consumed even when the
            # control frame is malformed — otherwise the byte stream
            # desynchronizes and every later op on this connection
            # parses garbage.
            payload = _recv_payload(sock, self._push_limit)
            if payload is None:
                raise OSError("push payload missing")
            ok = False
            try:
                digest = bytes.fromhex(msg.get("digest", ""))
                tokens = [int(t) for t in msg.get("tokens") or []]
                if digest and self._accept is not None:
                    ok = bool(self._accept(digest, tokens, payload))
            except Exception:      # accepting must never kill the conn
                logger.exception("prefix push accept failed for %s",
                                 msg.get("digest"))
                ok = False
            if ok:
                stats.PUSH_PAGES.inc()
                stats.PUSH_BYTES.inc(len(payload))
            else:
                stats.PUSH_REJECTS.inc()
            _send_frame(sock, {"ok": ok})

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class PrefixClient:
    """Fetch-by-digest against a list of peer replicas.

    Peers are tried in order; each attempt is deadline-bounded and a
    peer that times out / errors trips its :class:`PeerBreaker`
    (exponential backoff with jitter, half-open single-probe recovery;
    a geometry-mismatched peer is disabled permanently). Thread-safe
    for the single engine thread that probes it; sockets are cached per
    peer.
    """

    BACKOFF_S = 30.0      # default breaker base (GLLM_PREFIX_PEER_BACKOFF_S)

    def __init__(self, peers: Sequence[str], geometry: dict,
                 timeout_s: Optional[float] = None,
                 backoff_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None,
                 fail_threshold: Optional[int] = None,
                 jitter: Optional[float] = None):
        self.geometry = geometry
        # expected payload size: geometry is fixed, so anything larger
        # than the page bytes + header slack is hostile/corrupt
        from gllm_tpu.kvstore.pagefmt import geometry_bytes
        self._payload_limit = geometry_bytes(geometry) + 4096
        self.timeout_s = (timeout_s if timeout_s is not None else float(
            os.environ.get("GLLM_PREFIX_PEER_TIMEOUT_S", "2.0")))
        base = (backoff_s if backoff_s is not None
                else _env_f("GLLM_PREFIX_PEER_BACKOFF_S", self.BACKOFF_S))
        cap = (backoff_max_s if backoff_max_s is not None
               else _env_f("GLLM_PREFIX_PEER_BACKOFF_MAX_S",
                           max(300.0, base)))
        thresh = int(fail_threshold if fail_threshold is not None
                     else _env_f("GLLM_PREFIX_PEER_FAILS", 1))
        jit = (jitter if jitter is not None
               else _env_f("GLLM_PREFIX_PEER_JITTER", 0.1))
        # guards peer/socket state: fetch() runs on the engine thread,
        # close() on whatever thread drives shutdown
        self._lock = threading.Lock()
        self._closed = False
        # addr -> {sock, negotiated (None=not yet, False=refused),
        #          breaker}; parse up front so a malformed
        #          --prefix-peers entry fails construction, not the
        #          first scheduling probe
        self._peers: Dict[Tuple[str, int], dict] = {
            parse_peer_addr(a): {
                "sock": None, "negotiated": None,
                "breaker": PeerBreaker(base, cap, thresh, jit)}
            for a in peers if a.strip()}
        if not self._peers:
            raise ValueError("prefix client needs at least one peer")

    # ---- connection management -------------------------------------------

    def _connect(self, addr: Tuple[str, int]) -> socket.socket:
        sock = socket.create_connection(addr, timeout=self.timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _drop(self, addr, st: dict, backoff: bool = True) -> None:
        sock, st["sock"] = st["sock"], None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if backoff:
            br = st["breaker"]
            was_open = br.state == "open"
            br.failure()
            if br.state == "open" and not was_open:
                stats.PEER_BREAKER_OPENS.inc(peer=f"{addr[0]}:{addr[1]}")
                logger.warning(
                    "prefix peer %s breaker OPEN for %.1fs (%d "
                    "consecutive trips)", addr, br.down_for(), br.trips)
            self._set_open_gauge()

    def _set_open_gauge(self) -> None:
        stats.PEER_BREAKER_OPEN.set(sum(
            1 for st in self._peers.values()
            if st["breaker"].state == "open"))

    def peer_health(self) -> Dict[str, dict]:
        """Per-peer breaker/health counters (surfaced on /server_info
        and read by the chaos tests)."""
        with self._lock:
            return {f"{h}:{p}": dict(st["breaker"].health(),
                                     negotiated=st["negotiated"])
                    for (h, p), st in self._peers.items()}

    def _negotiate(self, addr, st: dict, sock: socket.socket,
                   deadline: Optional[float] = None) -> bool:
        """hello → geometry check, once per client lifetime per peer."""
        _send_frame(sock, {"op": "hello"})
        reply = _recv_frame(sock, deadline=deadline)
        if reply is None:
            raise OSError("bad hello reply")
        if reply.get("geometry") != self.geometry:
            logger.warning(
                "prefix peer %s refused: page geometry/kv-dtype mismatch "
                "(%s vs local %s) — peer disabled", addr,
                {k: reply.get("geometry", {}).get(k)
                 for k in ("page_size", "v")},
                {k: self.geometry[k] for k in ("page_size", "v")})
            st["negotiated"] = False
            self._drop(addr, st, backoff=False)
            return False
        st["negotiated"] = True
        return True

    # ---- fetch ------------------------------------------------------------

    def fetch(self, digest: bytes, tokens) -> Optional[
            Tuple[List[np.ndarray], Optional[bytes]]]:
        """``(leaves, parent)`` from the first peer that can serve this
        digest, canary-verified; None = every peer missed / was down.
        Bounded: one ``timeout_s`` deadline per live peer, no retries
        inside the call."""
        if FAULTS.fire("peer_prefix_timeout"):
            # chaos point (docs/robustness.md): the whole peer tier
            # behaves as a deadline expiry — the probe degrades to the
            # next tier (recompute) without stalling
            stats.PEER_TIMEOUTS.inc()
            stats.MISSES.inc(tier="peer")
            return None
        with self._lock:
            peers = list(self._peers.items())
        for addr, st in peers:
            if st["negotiated"] is False or not st["breaker"].allow():
                continue
            if FAULTS.fire("peer_flap"):
                # chaos point: this peer attempt behaves as a transport
                # failure — drives the breaker ladder (open → half-open
                # → closed) deterministically under test
                self._drop(addr, st)
                continue
            # ONE wall-clock budget covers connect + hello + request +
            # full response for this peer — a dribbling sender can't
            # stretch a probe past timeout_s by keeping each recv alive
            deadline = time.monotonic() + self.timeout_s
            hdr = raw = None
            for _retry in range(2):
                try:
                    # hold a LOCAL ref: a concurrent close() nulls
                    # st["sock"], and the closed socket must surface as
                    # the OSError below, never an AttributeError
                    with self._lock:
                        if self._closed:
                            return None
                        sock = st["sock"]
                        fresh = sock is None
                        if fresh:
                            sock = st["sock"] = self._connect(addr)
                    if st["negotiated"] is None and not self._negotiate(
                            addr, st, sock, deadline):
                        break
                    _send_frame(sock, {"op": "get",
                                       "digest": digest.hex()})
                    hdr = _recv_frame(sock, deadline=deadline)
                    raw = (None if hdr is None else
                           _recv_payload(sock, self._payload_limit,
                                         deadline))
                    if hdr is None or raw is None:
                        raise OSError("peer closed mid-reply")
                    break
                except (socket.timeout, TimeoutError):
                    stats.PEER_TIMEOUTS.inc()
                    logger.warning("prefix peer %s timed out (%.1fs); "
                                   "backing off", addr, self.timeout_s)
                    self._drop(addr, st)
                    break
                except (OSError, ConnectionError, ValueError):
                    # ValueError = garbled JSON control frame: same
                    # posture as a broken pipe. A CACHED socket may
                    # just have idled past the server's IDLE_S — retry
                    # once on a fresh connection before backing off.
                    hdr = raw = None
                    self._drop(addr, st, backoff=fresh)
                    if fresh:
                        break
            if hdr is not None:
                # ANY well-formed reply (hit or clean miss) is a healthy
                # peer: close the breaker and reset its backoff ladder
                br = st["breaker"]
                if br.state != "closed":
                    logger.info("prefix peer %s recovered (half-open "
                                "probe succeeded)", addr)
                br.success()
                self._set_open_gauge()
            if not (hdr and hdr.get("hit") and raw):
                continue        # clean miss or transport failure here
            try:
                leaves, parent = verify_payload(raw, self.geometry,
                                                digest, tokens)
            except (ValueError, KeyError):
                stats.POISON.inc(tier="peer")
                continue
            stats.HITS.inc(tier="peer")
            stats.BYTES.inc(len(raw), tier="peer", dir="read")
            return leaves, parent
        stats.MISSES.inc(tier="peer")
        return None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for addr, st in self._peers.items():
                self._drop(addr, st, backoff=False)


class PrefixPusher:
    """Push-by-digest toward a single target replica's prefix store
    (the pd-pool KV handoff, docs/pd_pools.md). Stateless per call:
    one fresh connection, one hello geometry negotiation, then the
    whole chain under ONE wall-clock deadline — a dead or slow decode
    target costs at most ``timeout_s`` and the push is simply dropped
    (the decode replica falls back to pull-then-recompute; a push
    failure must never stall or fail the stream that triggered it)."""

    def __init__(self, geometry: dict, timeout_s: Optional[float] = None):
        self.geometry = geometry
        self.timeout_s = (timeout_s if timeout_s is not None else _env_f(
            "GLLM_PREFIX_PEER_TIMEOUT_S", 2.0))

    def push(self, addr: str,
             pages: Sequence[Tuple[bytes, Sequence[int], bytes]]) -> int:
        """Ship ``(digest, canary_tokens, payload)`` pages to
        ``addr`` (``host:port`` of the target's prefix serve port).
        Returns how many the target ACCEPTED (verified + staged);
        any transport/negotiation failure returns the partial count."""
        if not pages:
            return 0
        if FAULTS.fire("kv_push_fail"):
            # chaos point (docs/robustness.md): the push plane is down —
            # the handoff degrades to re-prefill on the decode side,
            # the client stream is untouched
            return 0
        accepted = 0
        deadline = time.monotonic() + self.timeout_s
        try:
            host, port = parse_peer_addr(addr)
            with socket.create_connection(
                    (host, port), timeout=self.timeout_s) as sock:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
                _send_frame(sock, {"op": "hello"})
                reply = _recv_frame(sock, deadline=deadline)
                if reply is None or reply.get("geometry") != self.geometry:
                    logger.warning(
                        "prefix push target %s refused: geometry "
                        "mismatch — push dropped", addr)
                    return 0
                for digest, tokens, payload in pages:
                    _send_frame(sock, {"op": "push",
                                       "digest": digest.hex(),
                                       "tokens": [int(t)
                                                  for t in tokens]},
                                raw=payload)
                    ack = _recv_frame(sock, deadline=deadline)
                    if ack is None:
                        raise OSError("push target closed mid-reply")
                    if ack.get("ok"):
                        accepted += 1
        except (OSError, ValueError):
            logger.warning("prefix push to %s failed after %d/%d pages",
                           addr, accepted, len(pages))
        return accepted
