"""Pallas TPU kernels — the hot data-plane ops.

These replace the prebuilt CUDA kernels the reference consumes
(sgl_kernel flash_attn_with_kvcache etc., SURVEY.md §2.6). Each kernel has an
XLA reference implementation in gllm_tpu/ops/ used as its correctness oracle
(interpret-mode tests run on CPU).
"""
