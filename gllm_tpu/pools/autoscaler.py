"""SLO-driven autoscaling signals for the prefill/decode pools.

Jax-free and replica-passive: the autoscaler never commands anything —
it derives per-pool **scale verdicts** from surfaces every replica
already exposes (``/readyz`` state via the router's poller,
``/server_info`` queue depths, and the ``gllm_request_ttft_seconds`` /
``gllm_request_tpot_seconds`` histograms on ``/metrics``) and publishes
them on ``/router_info``. An external operator (or a human) reads the
verdicts and adds/drains replicas; scale-DOWN goes through the router's
``drain_replica`` so in-flight decode streams migrate with zero lost
tokens (docs/pd_pools.md#autoscaling).

Signal definitions (per pool):

- ``queue_depth``   Σ waiting sequences across the pool's ready replicas
- ``ttft_mean_s``   windowed mean of the TTFT histogram deltas — the
                    prefill pool's SLO axis (a prompt burst shows up
                    here first)
- ``tpot_mean_s``   windowed mean of the TPOT histogram deltas — the
                    decode pool's SLO axis (a decode pool at capacity
                    stretches inter-token latency before anything else)
- ``slo_headroom``  ``1 - latency/slo`` on the pool's axis, in [-inf, 1]

Verdict rules, in order: no ready replica → ``scale_up``; SLO headroom
< 0 or queue depth per ready replica above ``queue_high`` →
``scale_up``; pool idle (no queue, no running work) with more than
``min_replicas`` ready and headroom > 0.5 → ``scale_down``; otherwise
``hold``.
"""

from __future__ import annotations

import http.client
import logging
import re
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

POOL_ROLES = ("prefill", "decode", "mixed")

# prom text sample: name{labels} value  — labels optional; the TTFT/
# TPOT families are unlabeled but the parser tolerates labels so a
# future label add cannot silently zero the autoscaler's signals.
_SAMPLE_RE = re.compile(
    r"^(gllm_request_(?:ttft|tpot)_seconds_(?:sum|count))"
    r"(?:\{[^}]*\})?\s+([0-9.eE+-]+|NaN)\s*$")


def replica_role(rep) -> str:
    """The pool role a router-side ``Replica`` last advertised on
    ``/server_info`` (``mixed`` until the first probe lands — an
    unknown replica must stay eligible for every pool)."""
    role = (rep.info or {}).get("pool_role")
    return role if role in POOL_ROLES else "mixed"


def _fetch_metrics_text(host: str, port: int,
                        timeout: float) -> Optional[str]:
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        raw = resp.read()
        if resp.status != 200:
            return None
        return raw.decode("utf-8", "replace")
    except (OSError, http.client.HTTPException):
        return None
    finally:
        conn.close()


def parse_latency_samples(text: str) -> Dict[str, float]:
    """``{ttft_sum, ttft_count, tpot_sum, tpot_count}`` out of a
    Prometheus text exposition (missing families read as 0)."""
    out = {"ttft_sum": 0.0, "ttft_count": 0.0,
           "tpot_sum": 0.0, "tpot_count": 0.0}
    for line in text.splitlines():
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, val = m.group(1), m.group(2)
        try:
            v = float(val)
        except ValueError:
            continue
        axis = "ttft" if "_ttft_" in name else "tpot"
        kind = "sum" if name.endswith("_sum") else "count"
        out[f"{axis}_{kind}"] += v
    return out


class PoolAutoscaler:
    """Per-pool scale verdicts from the fleet's health surfaces.

    ``observe(rep)`` is wired as the ReplicaSet's ``info_hook`` — it
    runs on the poller's probe threads right after each replica's
    ``/server_info`` refresh, scraping ``/metrics`` at most once per
    ``interval_s`` per replica and keeping windowed histogram deltas.
    ``verdicts(replicas)`` is called by handler threads serving
    ``/router_info``; it only reads the latest snapshots.
    """

    def __init__(self, *,
                 slo_ttft_s: float = 2.0,
                 slo_tpot_s: float = 0.5,
                 queue_high: float = 4.0,
                 min_replicas: int = 1,
                 interval_s: float = 5.0,
                 scrape_timeout_s: float = 2.0):
        self.slo_ttft_s = float(slo_ttft_s)
        self.slo_tpot_s = float(slo_tpot_s)
        self.queue_high = float(queue_high)
        self.min_replicas = max(0, int(min_replicas))
        self.interval_s = float(interval_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self._lock = threading.Lock()
        # addr -> {t, totals, window} — totals are the last scrape's
        # cumulative samples, window the delta means derived from them
        self._seen: Dict[str, dict] = {}

    # ---- scraping (poller probe threads) ----------------------------------

    def observe(self, rep) -> None:
        now = time.monotonic()
        with self._lock:
            st = self._seen.setdefault(rep.addr, {
                "t": 0.0, "totals": None,
                "window": {"ttft_mean_s": None, "tpot_mean_s": None}})
            if now - st["t"] < self.interval_s:
                return
            st["t"] = now
        text = _fetch_metrics_text(rep.host, rep.port,
                                   self.scrape_timeout_s)
        if text is None:
            return
        totals = parse_latency_samples(text)
        with self._lock:
            prev = st["totals"]
            st["totals"] = totals
            if prev is None:
                return
            window = {}
            for axis in ("ttft", "tpot"):
                dc = totals[f"{axis}_count"] - prev[f"{axis}_count"]
                ds = totals[f"{axis}_sum"] - prev[f"{axis}_sum"]
                if dc < 0 or ds < 0:       # replica restarted: resync
                    window[f"{axis}_mean_s"] = None
                elif dc > 0:
                    window[f"{axis}_mean_s"] = ds / dc
                else:
                    window[f"{axis}_mean_s"] = None
            st["window"] = window

    def window_means(self, addr: str) -> dict:
        with self._lock:
            st = self._seen.get(addr)
            return dict(st["window"]) if st else {
                "ttft_mean_s": None, "tpot_mean_s": None}

    # ---- verdicts (handler threads, read-only) ----------------------------

    def verdicts(self, replicas) -> Dict[str, dict]:
        """``{pool: signals+verdict}`` over the current replica list.
        Mixed replicas count toward BOTH pools (they serve either
        phase), so a mixed-only fleet reports two healthy pools rather
        than two empty ones."""
        out: Dict[str, dict] = {}
        for pool in ("prefill", "decode"):
            members = [r for r in replicas
                       if replica_role(r) in (pool, "mixed")]
            if not members:
                continue
            ready = [r for r in members if r.in_rotation]
            queue = sum(int((r.info or {}).get("waiting") or 0)
                        for r in ready)
            running = sum(int((r.info or {}).get("running") or 0)
                          for r in ready)
            streams = sum(r.active_streams for r in members)
            axis = "ttft" if pool == "prefill" else "tpot"
            slo = self.slo_ttft_s if pool == "prefill" else self.slo_tpot_s
            means = [self.window_means(r.addr)[f"{axis}_mean_s"]
                     for r in ready]
            means = [m for m in means if m is not None]
            lat = max(means) if means else None
            headroom = None if lat is None else 1.0 - lat / slo
            verdict, why = "hold", "within SLO and queue bounds"
            if not ready:
                verdict, why = "scale_up", "no ready replica in pool"
            elif headroom is not None and headroom < 0.0:
                verdict = "scale_up"
                why = (f"{axis} {lat:.3f}s over SLO {slo:.3f}s")
            elif queue / max(1, len(ready)) > self.queue_high:
                verdict = "scale_up"
                why = (f"queue depth {queue} over "
                       f"{self.queue_high:g}/replica")
            elif (queue == 0 and running == 0 and streams == 0
                  and len(ready) > self.min_replicas
                  and (headroom is None or headroom > 0.5)):
                verdict, why = "scale_down", "pool idle above min size"
            out[pool] = {
                "replicas": len(members),
                "ready": len(ready),
                "queue_depth": queue,
                "running": running,
                "active_streams": streams,
                "ttft_mean_s": (max(
                    (m for m in (self.window_means(r.addr)["ttft_mean_s"]
                                 for r in ready) if m is not None),
                    default=None) if ready else None),
                "tpot_mean_s": (max(
                    (m for m in (self.window_means(r.addr)["tpot_mean_s"]
                                 for r in ready) if m is not None),
                    default=None) if ready else None),
                "slo_s": slo,
                "slo_headroom": headroom,
                "verdict": verdict,
                "why": why,
            }
        return out
