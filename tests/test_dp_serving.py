"""DP-attention serving: multi-replica engines in one jit program.

dp=2 greedy output must be byte-identical to dp=1 (the reference's DP
validation discipline, docs/dp_attention_design.md), with idle replicas
riding as in-program dummy batches instead of lockstep barriers.
"""

import numpy as np
import pytest
import torch

from gllm_tpu.config import (CacheConfig, EngineConfig, ParallelConfig,
                             SchedulerConfig)
from gllm_tpu.engine.llm import LLM
from gllm_tpu.sampling_params import SamplingParams


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(6)
    d = tmp_path_factory.mktemp("dp_model")
    LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        max_position_embeddings=256, eos_token_id=0,
        attention_bias=False)).save_pretrained(d, safe_serialization=True)
    return str(d)


def make_llm(ckpt, dp=1, tp=1, attention_impl="auto", **sched):
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128,
        attention_impl=attention_impl,
        scheduler=SchedulerConfig(**sched) if sched else SchedulerConfig(),
        cache=CacheConfig(page_size=4, num_pages=64),
        parallel=ParallelConfig(dp=dp, tp=tp))
    return LLM(config=cfg)


def test_dp2_greedy_byte_identity(ckpt):
    rng = np.random.default_rng(0)
    prompts = [[int(x) for x in rng.integers(2, 120, size=int(n))]
               for n in rng.integers(2, 30, size=5)]
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    base = [o.output_token_ids
            for o in make_llm(ckpt).generate(prompt_token_ids=prompts,
                                             sampling_params=sp)]
    dp2 = [o.output_token_ids
           for o in make_llm(ckpt, dp=2).generate(prompt_token_ids=prompts,
                                                  sampling_params=sp)]
    assert base == dp2


def test_dp2_uneven_load_and_idle_replica(ckpt):
    """One request → replica 0 busy, replica 1 idle (dummy batches); and a
    second wave lands on replica 1 (round robin)."""
    llm = make_llm(ckpt, dp=2)
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    out1 = llm.generate(prompt_token_ids=[[5, 9, 23]],
                        sampling_params=sp)[0]
    out2 = llm.generate(prompt_token_ids=[[5, 9, 23]],
                        sampling_params=sp)[0]
    # same prompt, different replicas → identical greedy output
    assert out1.output_token_ids == out2.output_token_ids
    assert llm._rr == 2                      # round-robined over replicas
    assert not llm._seq_replica              # routing entries cleaned up
    # all pages released on both replicas
    for mm in llm.memory_managers:
        assert mm.num_free_pages == mm.allocator.num_total


def test_dp2_chunked_prefill_matches_dp1(ckpt):
    rng = np.random.default_rng(3)
    long_prompt = [int(x) for x in rng.integers(2, 120, size=40)]
    sp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)
    a = make_llm(ckpt, max_prefill_tokens=8, min_prefill_tokens=4).generate(
        prompt_token_ids=[long_prompt], sampling_params=sp)[0]
    b = make_llm(ckpt, dp=2, max_prefill_tokens=8,
                 min_prefill_tokens=4).generate(
        prompt_token_ids=[long_prompt, long_prompt],
        sampling_params=sp)
    assert b[0].output_token_ids == a.output_token_ids
    assert b[1].output_token_ids == a.output_token_ids


def test_dp2_moe_ep(ckpt, tmp_path):
    """MoE under DP: experts shard over tp within each replica; outputs
    must match dp=1."""
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM
    torch.manual_seed(8)
    Qwen2MoeForCausalLM(Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
        moe_intermediate_size=32, shared_expert_intermediate_size=48,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=256, eos_token_id=0)).save_pretrained(
        tmp_path, safe_serialization=True)
    prompts = [[7, 3, 56], [99, 14, 2, 8]]
    sp = SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True)

    def run(dp):
        cfg = EngineConfig(
            model=str(tmp_path), dtype="float32", max_model_len=128,
            cache=CacheConfig(page_size=4, num_pages=64),
            parallel=ParallelConfig(dp=dp, tp=2, enable_ep=True))
        return [o.output_token_ids for o in LLM(config=cfg).generate(
            prompt_token_ids=prompts, sampling_params=sp)]

    assert run(2) == run(1)


def test_dp2_pallas_matches_dp1_xla(ckpt):
    """dp=2 with attention_impl='pallas' (shard_map manual over the dp
    axis, kernels in interpret mode on CPU) is byte-identical to dp=1
    XLA — the reference runs FA3 in every DP replica
    (worker.py:750-829)."""
    rng = np.random.default_rng(7)
    prompts = [[int(x) for x in rng.integers(2, 120, size=int(n))]
               for n in rng.integers(2, 30, size=5)]
    sp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    base = [o.output_token_ids
            for o in make_llm(ckpt, attention_impl="xla").generate(
                prompt_token_ids=prompts, sampling_params=sp)]
    dp2 = [o.output_token_ids
           for o in make_llm(ckpt, dp=2, attention_impl="pallas").generate(
               prompt_token_ids=prompts, sampling_params=sp)]
    assert base == dp2


def test_dp2_tp2_pallas_matches_dp1_xla(ckpt):
    """dp=2 × tp=2 with Pallas attention: the dp axis is manual
    (shard_map), tp stays auto inside and the attention dispatch nests
    its tp shard_map over the context mesh."""
    import jax
    if not hasattr(jax, "shard_map"):
        # jax 0.4.x cannot nest the partial-manual tp shard_map inside
        # the dp-manual region (the runner raises NotImplementedError,
        # runner.py _pick_attn_impl) — a version gap, not a regression:
        # tier-1 must report it as a skip, not a failure, on old-jax
        # images
        pytest.skip("dp>1 x tp>1 pallas needs jax.shard_map (jax >= 0.5)")
    rng = np.random.default_rng(9)
    prompts = [[int(x) for x in rng.integers(2, 120, size=int(n))]
               for n in rng.integers(2, 30, size=4)]
    sp = SamplingParams(temperature=0.0, max_tokens=7, ignore_eos=True)

    base = [o.output_token_ids
            for o in make_llm(ckpt, attention_impl="xla").generate(
                prompt_token_ids=prompts, sampling_params=sp)]
    dp2 = [o.output_token_ids
           for o in make_llm(ckpt, dp=2, tp=2,
                             attention_impl="pallas").generate(
               prompt_token_ids=prompts, sampling_params=sp)]
    assert base == dp2


def test_dp2_logprobs_match_dp1(ckpt):
    """Output + prompt logprobs under dp=2 (reference computes logprobs
    from every worker, sampler.py:71-91) match dp=1 numerically."""
    rng = np.random.default_rng(5)
    prompts = [[int(x) for x in rng.integers(2, 120, size=int(n))]
               for n in rng.integers(4, 24, size=4)]
    sps = [SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True,
                          logprobs=3, prompt_logprobs=2),
           SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True,
                          logprobs=2),
           SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True),
           SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True,
                          prompt_logprobs=1)]

    def run(dp):
        return make_llm(ckpt, dp=dp).generate(prompt_token_ids=prompts,
                                              sampling_params=sps)

    base, dp2 = run(1), run(2)
    for a, b in zip(base, dp2):
        assert a.output_token_ids == b.output_token_ids
        assert (a.logprobs is None) == (b.logprobs is None)
        if a.logprobs is not None:
            for (ca, ia, la), (cb, ib, lb) in zip(a.logprobs, b.logprobs):
                assert ia == ib
                np.testing.assert_allclose([ca] + la, [cb] + lb,
                                           rtol=1e-5, atol=1e-6)
        assert (a.prompt_logprobs is None) == (b.prompt_logprobs is None)
        if a.prompt_logprobs is not None:
            for pa, pb in zip(a.prompt_logprobs, b.prompt_logprobs):
                assert (pa is None) == (pb is None)
                if pa is not None:
                    assert pa[1] == pb[1]
                    np.testing.assert_allclose(
                        [pa[0]] + pa[2], [pb[0]] + pb[2],
                        rtol=1e-5, atol=1e-6)


def test_dp2_penalties_match_dp1(ckpt):
    """Penalty requests under dp (stacked PenaltyTokens with a shared
    length bucket, one replica penalized + one idle/plain)."""
    rng = np.random.default_rng(1)
    prompts = [[int(x) for x in rng.integers(2, 120, size=int(n))]
               for n in rng.integers(4, 40, size=4)]
    sps = [SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True,
                          repetition_penalty=1.5, presence_penalty=0.4,
                          frequency_penalty=0.2),
           SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True),
           SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True,
                          repetition_penalty=2.0),
           SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)]

    base = [o.output_token_ids
            for o in make_llm(ckpt).generate(prompt_token_ids=prompts,
                                             sampling_params=sps)]
    dp2 = [o.output_token_ids
           for o in make_llm(ckpt, dp=2).generate(prompt_token_ids=prompts,
                                                  sampling_params=sps)]
    assert base == dp2


# ---- per-DP-replica endpoints / request pinning ---------------------------

def _prefix_llm(ckpt, dp):
    cfg = EngineConfig(
        model=ckpt, dtype="float32", max_model_len=128,
        cache=CacheConfig(page_size=4, num_pages=64,
                          enable_prefix_caching=True),
        parallel=ParallelConfig(dp=dp))
    return LLM(config=cfg)


def test_dp_pinning_keeps_prefix_cache_warm(ckpt):
    """target_dp pins a seq to one replica; a multi-turn conversation's
    second turn warm-hits that replica's prefix cache. Round-robin sends
    turn 2 to the OTHER replica: no hit (reference --endpoint-per-dp
    rationale, llm_engine.py:121-133)."""
    from gllm_tpu.sampling_params import SamplingParams
    prompt = list(range(1, 25))             # 6 full pages of prefix
    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)

    llm = _prefix_llm(ckpt, dp=2)
    for _ in range(2):                      # two turns, pinned to dp0
        seq = llm._allocate_seq(list(prompt), sp)
        seq.target_dp = 0
        llm.add_seq(seq)
        while llm.schedulers[0].has_unfinished:
            llm.step()
    pinned_hits = llm.schedulers[0].mm.hit_tokens

    # control: force turn 2 onto the OTHER replica → its cache is cold.
    # (Without any pin, cache-aware routing would follow the cache — see
    # test_dp_cache_aware_routing.)
    rr = _prefix_llm(ckpt, dp=2)
    for pin in (0, 1):
        seq = rr._allocate_seq(list(prompt), sp)
        seq.target_dp = pin
        rr.add_seq(seq)
        while any(s.has_unfinished for s in rr.schedulers):
            rr.step()
    assert rr.schedulers[0].mm.hit_tokens == 0
    assert rr.schedulers[1].mm.hit_tokens == 0
    assert pinned_hits > 0


def test_endpoint_per_dp_http_pins_requests(ckpt):
    """serve_per_dp: one listener per replica over ONE shared engine;
    requests to listener d land on scheduler d."""
    import http.client
    import json as _json
    import threading

    from gllm_tpu.entrypoints.api_server import serve_per_dp
    llm = _prefix_llm(ckpt, dp=2)
    servers = serve_per_dp(llm, "127.0.0.1", [0, 0])
    ports = [s.server_address[1] for s in servers]
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    try:
        for d, port in enumerate(ports):
            for _ in range(2):
                c = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=60)
                c.request("POST", "/v1/completions", body=_json.dumps({
                    "prompt": [5, 6, 7, 8] * 5, "max_tokens": 3,
                    "temperature": 0, "ignore_eos": True}),
                    headers={"Content-Type": "application/json"})
                r = c.getresponse()
                assert r.status == 200, r.read()
                r.read()
                c.close()
        # each endpoint pinned its two requests to its own replica:
        # turn 2 warm-hits the same replica's prefix cache on BOTH
        assert llm.schedulers[0].mm.hit_tokens > 0
        assert llm.schedulers[1].mm.hit_tokens > 0
    finally:
        for s in servers:
            s.shutdown()
        servers[0].state.engine.shutdown()


def test_dp_cache_aware_routing(ckpt):
    """Without endpoint pinning, an UNPINNED second turn routes to the
    replica holding its prefix (cache-aware routing, beyond the
    reference's round-robin) — but a request with no substantial match
    still round-robins."""
    from gllm_tpu.sampling_params import SamplingParams
    llm = _prefix_llm(ckpt, dp=2)
    sp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    convo = list(range(1, 25))              # 6 full pages

    def run(prompt):
        seq = llm._allocate_seq(list(prompt), sp)
        llm.add_seq(seq)
        replica = llm._seq_replica[seq.seq_id]
        while any(s.has_unfinished for s in llm.schedulers):
            llm.step()
        return replica

    r1 = run(convo)                         # lands by round-robin
    # turn 2 shares the whole turn-1 prompt → must follow the cache
    r2 = run(convo + [90, 91, 92, 93])
    assert r2 == r1, (r1, r2)
    assert llm.schedulers[r1].mm.hit_tokens > 0
    # unrelated prompt: no match → round-robin continues across replicas
    seen = {run([100 + i for i in range(20)]),
            run([60 + i for i in range(20)])}
    assert len(seen) == 2, seen


def test_dp_cache_routing_short_shared_prefix_balances(ckpt):
    """A SHORT shared prefix (under half the prompt) must not funnel all
    traffic to one replica."""
    from gllm_tpu.sampling_params import SamplingParams
    llm = _prefix_llm(ckpt, dp=2)
    sp = SamplingParams(temperature=0.0, max_tokens=3, ignore_eos=True)
    sys_prompt = [7, 8, 9, 10]              # one page of shared prefix
    replicas = []
    for i in range(4):
        body = [20 + 5 * i + j for j in range(20)]  # 5 distinct pages
        seq = llm._allocate_seq(sys_prompt + body, sp)
        llm.add_seq(seq)
        replicas.append(llm._seq_replica[seq.seq_id])
        while any(s.has_unfinished for s in llm.schedulers):
            llm.step()
    assert len(set(replicas)) == 2, replicas
