"""Multi-host serving: host-0 frontend + deterministic request broadcast.

The reference's master/slave launch keeps one frontend and fans requests to
worker processes over zmq (/root/reference/gllm/comm.py:191-319,
llm_engine.py:198-211). Under jax multi-process SPMD the equivalent
invariant is stronger: EVERY process must issue the SAME sequence of jit
computations with the same shapes. We get it the single-controller way:

- every host runs an identical engine loop over identical scheduler state;
- host 0 additionally runs the HTTP frontend; each engine tick it
  broadcasts the newly-arrived request descriptors (and aborts) to all
  hosts (two-phase fixed-shape broadcast over the jax collective layer);
- schedulers are deterministic, so identical intake → identical schedules
  → identical jit calls on every host. No lockstep barriers beyond the
  intake broadcast.
"""

from __future__ import annotations

import dataclasses
import logging
import pickle
import time
from typing import List, Optional

import numpy as np

logger = logging.getLogger(__name__)


def outbound_ip(target_host: str = "10.255.255.255") -> Optional[str]:
    """IP of the local interface that routes toward ``target_host`` —
    a UDP connect performs no traffic but binds the socket to the
    outbound interface. ``gethostbyname(gethostname())`` commonly
    resolves to loopback in containers, so every advertised address
    goes through this scheme instead. Returns None when no route
    exists (isolated host)."""
    import socket
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect((target_host, 1))
            return probe.getsockname()[0]
        finally:
            probe.close()
    except OSError:
        return None


def broadcast_payload(obj) -> object:
    """Broadcast a picklable object from process 0 to all processes.

    Two-phase (length, then padded payload) so every process presents
    matching shapes to the collective.
    """
    import jax
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return obj
    if jax.process_index() == 0:
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    else:
        payload = np.zeros(0, np.uint8)
    n = multihost_utils.broadcast_one_to_all(
        np.asarray([payload.size], np.int64))
    size = int(n[0])
    buf = np.zeros(size, np.uint8)
    buf[:payload.size] = payload
    out = multihost_utils.broadcast_one_to_all(buf)
    return pickle.loads(out.tobytes())


def allgather_payload(obj) -> list:
    """All-gather one picklable object per process; returns the list
    indexed by process id. Two-phase (lengths, then padded payloads) like
    broadcast_payload."""
    import jax
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return [obj]
    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = multihost_utils.process_allgather(
        np.asarray([payload.size], np.int64))
    size = int(sizes.max())
    buf = np.zeros(size, np.uint8)
    buf[:payload.size] = payload
    bufs = multihost_utils.process_allgather(buf)
    return [pickle.loads(bufs[i, :int(sizes[i, 0])].tobytes())
            for i in range(bufs.shape[0])]


@dataclasses.dataclass
class BlobRef:
    """Placeholder for a bulk ndarray lifted out of the tick broadcast."""
    key: str                             # content hash (hex)
    shape: tuple
    dtype: str


# Arrays below this ride the pickle broadcast directly; above it they move
# through the host-0 blob server instead, so one big video can't serialize
# the whole intake collective (the concern the reference answers with
# per-DP zmq endpoints, comm.py:436-524). Env-overridable for tests.
import os as _os

BLOB_MIN_BYTES = int(_os.environ.get("GLLM_TPU_BLOB_MIN_BYTES", 1 << 16))


def _lift_array(arr, blobs: dict):
    """BlobRef (bytes added to ``blobs``) if large, else the array."""
    import hashlib
    if arr is None or arr.nbytes < BLOB_MIN_BYTES:
        return arr
    raw = np.ascontiguousarray(arr).tobytes()
    key = hashlib.blake2b(raw, digest_size=16).hexdigest()
    blobs[key] = raw
    return BlobRef(key, tuple(arr.shape), str(arr.dtype))


def _lift_blobs(mm: Optional[dict]):
    """(mm with BlobRefs, {key: bytes}) — large ndarrays only."""
    if not mm:
        return mm, {}
    out, blobs = {}, {}
    for k, v in mm.items():
        out[k] = _lift_array(np.asarray(v) if v is not None else None,
                             blobs)
    return out, blobs


def _resolve_array(v, fetch):
    if isinstance(v, BlobRef):
        return np.frombuffer(fetch(v.key), dtype=v.dtype).reshape(v.shape)
    return v


def _resolve_blobs(mm: Optional[dict], fetch):
    if not mm:
        return mm
    return {k: _resolve_array(v, fetch) for k, v in mm.items()}


@dataclasses.dataclass
class RequestDesc:
    """Wire form of one request (frontend → every host)."""
    seq_id: int
    token_ids: List[int]
    sampling: dict                       # dataclasses.asdict(SamplingParams)
    mm: Optional[dict] = None            # mm_input; arrays >= BLOB_MIN_BYTES
                                         # are BlobRefs served by host 0's
                                         # blob server (content-addressed),
                                         # the rest rides the broadcast


@dataclasses.dataclass
class DisaggAdmit:
    """Coordinator admit (gate A) replicated to every host: the fully
    expanded sequence state, by value (followers run NO coordinator —
    the reference's LM-side disagg state machine stays rank-0-only and
    workers receive derived state, lm_manager admit path)."""
    seq_id: int
    token_ids: List[int]                 # expanded (sentinels → runs)
    sampling: dict
    mrope_positions: object              # [3, L] np / BlobRef
    mrope_delta: int
    vis_index: object                    # [L] np / BlobRef
    num_vis_tokens: int
    hash_token_ids: List[int]
    item_span: List[tuple]
    vis_span: List[tuple]


@dataclasses.dataclass
class DisaggReady:
    """Gate-B flip for one item: its embedding rows, by value."""
    seq_id: int
    k: int                               # ordered-item index
    lo: int                              # vis-row span
    hi: int
    rows: object                         # np [n, H] / BlobRef


@dataclasses.dataclass
class DisaggAbort:
    seq_id: int


@dataclasses.dataclass
class Tick:
    """One intake broadcast: requests + aborts + shutdown flag."""
    requests: List[RequestDesc]
    aborts: List[int]
    shutdown: bool = False
    # coordinator events (host 0's disagg state machine), applied in
    # order on every host
    disagg: List[object] = dataclasses.field(default_factory=list)


class BlobStore:
    """Host-0 side: content-addressed bytes + a TCP server for followers.

    Lifecycle: blobs published with tick T are guaranteed fetched once the
    tick T+1 broadcast completes (every follower fully applies T — fetches
    included — before entering the next collective), so host 0 retires
    them then. No acks needed; the collective IS the barrier."""

    def __init__(self, host: str = "0.0.0.0"):
        from gllm_tpu.disagg.wire import MsgServer, send_msg
        self._data = {}
        self._send = send_msg
        self._srv = MsgServer(host, 0, self._on_req).start()
        self.port = self._srv.port

    def _on_req(self, msg, sock):
        raw = self._data.get(msg)
        # empty bytes = unknown key (follower treats as fatal; it means
        # the retire barrier was violated)
        self._send(sock, None, raw=raw if raw is not None else b"")

    def put(self, blobs: dict) -> None:
        self._data.update(blobs)

    def retire(self, keys) -> None:
        for k in keys:
            self._data.pop(k, None)

    def close(self) -> None:
        self._srv.stop()


class BlobClient:
    """Follower side: fetch-by-key with a content-addressed LRU, so a
    media item repeated across requests crosses the wire once per host.

    Fan-out (VERDICT r03 weak #5): a pure host-0 star serializes every
    ≥BLOB_MIN_BYTES payload on host-0 egress — N followers × blob size
    per tick. With a parent CHAIN (follower p fetches from follower p-1's
    peer server, follower 1 from host 0), host-0 egress is one stream per
    blob regardless of pod size, at the cost of worst-case linear cold
    latency down the chain. Every follower applies the same tick, so the
    parent is fetching the same blob concurrently; a parent-side miss is
    "not yet", retried with backoff, with host 0 as the bounded-deadline
    fallback (host 0 retires a tick's blobs only after the NEXT tick
    collective, which no follower enters before finishing its fetches —
    the fallback window is safe by construction)."""

    PEER_DEADLINE_S = 2.0

    def __init__(self, addr: str, parent: Optional[str] = None):
        from gllm_tpu.utils import LRUBytesCache
        self._addr = addr                     # host 0 (authoritative)
        self._parent = parent                 # chain parent (may be None)
        self._socks = {}                      # addr -> socket
        self._cache = LRUBytesCache(max_entries=128, max_mb=512.0)
        self.stats = {"lru": 0, "peer": 0, "host0": 0}

    def set_parent(self, parent: Optional[str]) -> None:
        self._parent = parent

    def serve_from_cache(self, key: str):
        """Peer-server handler → (payload, header): bytes on LRU hit;
        b'' with header "never" when the value was rejected as oversize
        (a downstream fetcher should stop polling and go to host 0);
        b'' with header None = not (yet) here."""
        cached = self._cache.get(key)
        if cached is not None:
            return cached, None
        if key in self._cache.oversize:
            return b"", "never"
        return b"", None

    def _fetch_from(self, addr: str, key: str):
        from gllm_tpu.disagg.wire import connect, recv_msg, recv_raw, \
            send_msg
        sock = self._socks.get(addr)
        if sock is None:
            host, _, port = addr.rpartition(":")
            sock = self._socks[addr] = connect((host, int(port)))
        send_msg(sock, key)
        hdr = recv_msg(sock)                  # None | "never"
        return recv_raw(sock), hdr

    def fetch(self, key: str) -> bytes:
        cached = self._cache.get(key)
        if cached is not None:
            self.stats["lru"] += 1
            return cached
        if self._parent is not None:
            deadline = time.monotonic() + self.PEER_DEADLINE_S
            delay = 0.005
            while time.monotonic() < deadline:
                try:
                    raw, hdr = self._fetch_from(self._parent, key)
                except OSError:
                    self._socks.pop(self._parent, None)
                    break                      # parent gone → host 0
                if raw:
                    self.stats["peer"] += 1
                    self._cache.put(key, raw)
                    return raw
                if hdr == "never":
                    break  # parent can never serve it (oversize) → host 0
                time.sleep(delay)
                delay = min(delay * 2, 0.2)
        raw, _ = self._fetch_from(self._addr, key)
        if not raw:
            raise RuntimeError(f"blob {key} unavailable on host 0")
        self.stats["host0"] += 1
        self._cache.put(key, raw)             # bytes on both paths
        return raw


class PeerBlobServer:
    """Follower-side read-only blob server over the follower's own LRU —
    the chain parent endpoint for the next follower."""

    def __init__(self, client: BlobClient, host: str = "0.0.0.0"):
        from gllm_tpu.disagg.wire import MsgServer, send_msg
        self._send = send_msg
        self._client = client
        self._srv = MsgServer(host, 0, self._on_req).start()
        self.port = self._srv.port

    def _on_req(self, msg, sock):
        raw, hdr = self._client.serve_from_cache(msg)
        self._send(sock, hdr, raw=raw)

    def close(self) -> None:
        self._srv.stop()


class MultihostEngine:
    """Runs the engine loop on every host; host 0 feeds it requests.

    Host 0: call ``submit``/``abort`` from frontend threads, run
    ``run_host0`` on the engine thread. Hosts > 0: call ``run_follower``.
    Outputs surface only on host 0 (``on_output`` callback).
    """

    def __init__(self, llm, on_output=None, tick_interval: float = 0.002,
                 advertise_host: Optional[str] = None):
        import jax
        self.llm = llm
        self.on_output = on_output or (lambda out: None)
        self.tick_interval = tick_interval
        self.is_host0 = jax.process_index() == 0
        self._pending: List[RequestDesc] = []
        self._pending_aborts: List[int] = []
        self._seqs: dict = {}          # host-0: seq_id → allocated Sequence
        self._shutdown = False
        import threading
        self._lock = threading.Lock()
        # Encoder disaggregation: the coordinator (encoder fleet, slot
        # pool, two-gate state machine) runs on HOST 0 ONLY — this engine
        # polls it itself (events must ride the tick broadcast), so
        # llm.step() skips its local poll via the flag; the coordinator
        # stays attached (api_server's disagg detection and lm_server's
        # close read llm.disagg_coordinator).
        self.coord = getattr(llm, "disagg_coordinator", None)
        if self.coord is not None:
            llm.disagg_external_poll = True
        # seq_id → (Sequence, shadow-ready list) for in-flight disagg seqs
        self._disagg_seqs: dict = {}
        # host 0: registry entries whose events are fully emitted — popped
        # at the NEXT drain, never before the admit tick was applied (a
        # fully-ready-at-admit seq would otherwise vanish from the
        # registry before _apply_tick reads it)
        self._disagg_done: List[int] = []
        # host 0: user aborts to surface as DisaggAbort events (the
        # coordinator's own abort path frees state without emitting)
        self._disagg_aborts: List[int] = []
        # bulk-payload side channel (host 0 serves, followers fetch)
        self._blob_store: Optional[BlobStore] = None
        self._blob_client: Optional[BlobClient] = None
        self._inflight_keys: List[str] = []    # published with last tick
        if self.is_host0 and jax.process_count() > 1:
            self._blob_store = BlobStore()
            if advertise_host is None:
                # default-route interface via the getsockname() scheme
                # (same as the follower peer-advertise path below);
                # gethostbyname(gethostname()) is loopback on many
                # container /etc/hosts layouts and followers on other
                # machines could never reach it
                advertise_host = outbound_ip()
            if advertise_host is None:
                import socket as _s
                try:
                    advertise_host = _s.gethostbyname(_s.gethostname())
                except OSError:
                    advertise_host = "127.0.0.1"
            self._blob_addr = f"{advertise_host}:{self._blob_store.port}"
        else:
            self._blob_addr = None

    # ---- host-0 frontend side ---------------------------------------------

    def submit(self, token_ids: List[int], sampling_params,
               on_register=None, mm_input: Optional[dict] = None) -> int:
        """``on_register(seq_id)`` runs under the intake lock BEFORE the
        request becomes visible to the engine loop — callers register
        their output handles there so no chunk can be dropped."""
        assert self.is_host0
        mm_state = None
        if mm_input:
            from gllm_tpu.engine.mm import build_mm_state
            mm_state = build_mm_state(token_ids, self.llm.model_cfg,
                                      **mm_input)
        mm_wire, blobs = _lift_blobs(mm_input)
        with self._lock:
            if blobs and self._blob_store is not None:
                self._blob_store.put(blobs)
            seq = self.llm._allocate_seq(list(token_ids), sampling_params)
            seq.mm = mm_state
            if on_register is not None:
                on_register(seq.seq_id)
            self._pending.append(RequestDesc(
                seq.seq_id, list(token_ids),
                dataclasses.asdict(sampling_params), mm=mm_wire))
            self._seqs[seq.seq_id] = seq
        return seq.seq_id

    def submit_disagg(self, seq, raw_items) -> None:
        """Host 0: hand a skeleton-tokenized MM request to the
        coordinator; the admit reaches every host as a tick event."""
        assert self.is_host0 and self.coord is not None
        self.coord.submit(seq, raw_items)

    def _drain_disagg_host0(self, blobs: dict) -> List[object]:
        """Run one coordinator poll and serialize its effects: new admits
        (expanded state by value), gate-B ready flips since the last poll
        (diffed against a shadow — the coordinator mutates seq.mm in
        place), failures. Embedding rows >= BLOB_MIN_BYTES ride the blob
        channel."""
        evts: List[object] = []
        # retire fully-emitted entries from the PREVIOUS drain (their
        # admit tick has been applied by now)
        for sid in self._disagg_done:
            self._disagg_seqs.pop(sid, None)
        self._disagg_done = []
        devents = self.coord.poll()
        # user aborts recorded by abort(): the coordinator has processed
        # them in the poll above (slot frees); emit the events so every
        # host drops registry + scheduler state
        with self._lock:
            user_aborts, self._disagg_aborts = self._disagg_aborts, []
        for seq in devents.admits:
            st = seq.disagg
            self._disagg_seqs[seq.seq_id] = (seq, [False] * len(st.ready))
            mm = seq.mm
            evts.append(DisaggAdmit(
                seq_id=seq.seq_id, token_ids=list(seq.token_ids),
                sampling=dataclasses.asdict(seq.sampling_params),
                mrope_positions=_lift_array(
                    np.asarray(mm.mrope_positions), blobs),
                mrope_delta=mm.mrope_delta,
                vis_index=_lift_array(np.asarray(mm.vis_index), blobs),
                num_vis_tokens=mm.num_vis_tokens,
                hash_token_ids=list(mm.hash_token_ids),
                item_span=list(st.item_span), vis_span=list(st.vis_span)))
        abort_sids = {seq.seq_id for seq in devents.aborts} | \
            set(user_aborts)
        for sid in abort_sids:
            evts.append(DisaggAbort(sid))
            self._disagg_seqs.pop(sid, None)
        # ready diffs (including items already ready at admit time);
        # fully-emitted entries retire at the NEXT drain (see above)
        for sid, (seq, shadow) in self._disagg_seqs.items():
            st = seq.disagg
            for k, r in enumerate(st.ready):
                if r and not shadow[k]:
                    lo, hi = st.vis_span[k]
                    evts.append(DisaggReady(
                        sid, k, lo, hi,
                        _lift_array(seq.mm.vis_embeds[lo:hi].copy(),
                                    blobs)))
                    shadow[k] = True
            if all(shadow):
                self._disagg_done.append(sid)
        return evts

    def _apply_disagg_event(self, ev) -> None:
        from gllm_tpu.sequence import SequenceStatus
        llm = self.llm
        if isinstance(ev, DisaggAdmit):
            if self.is_host0:
                seq = self._disagg_seqs[ev.seq_id][0]
            else:
                from gllm_tpu.disagg.lm_manager import DisaggSeqState
                from gllm_tpu.engine.mm import MMState
                from gllm_tpu.sampling_params import SamplingParams
                fetch = self._blob_client.fetch
                # Sequence.__init__ derives prompt_len / raw_prompt_len /
                # detok offsets from the (already expanded) token list —
                # no re-assignment needed here
                seq = llm._allocate_seq(list(ev.token_ids),
                                        SamplingParams(**ev.sampling))
                seq.seq_id = ev.seq_id
                seq.mm = MMState(
                    items=[],
                    mrope_positions=_resolve_array(ev.mrope_positions,
                                                   fetch),
                    mrope_delta=ev.mrope_delta,
                    vis_index=_resolve_array(ev.vis_index, fetch),
                    num_vis_tokens=ev.num_vis_tokens,
                    hash_token_ids=list(ev.hash_token_ids),
                    vis_embeds=np.zeros(
                        (ev.num_vis_tokens, llm.model_cfg.mm_embed_dim),
                        np.float32))
                seq.disagg = DisaggSeqState(
                    item_span=list(ev.item_span),
                    vis_span=list(ev.vis_span),
                    ready=[False] * len(ev.vis_span))
                self._disagg_seqs[seq.seq_id] = (seq, None)
            try:
                llm.add_seq(seq)
            except ValueError as e:
                # deterministic on every host (same validation); host 0
                # additionally releases coordinator state + reports
                self._disagg_seqs.pop(ev.seq_id, None)
                seq.status = SequenceStatus.ABORTED
                seq.finish_reason = "abort"
                if self.is_host0:
                    self.coord.abort([ev.seq_id])
                    self.on_output(("error", ev.seq_id, str(e)))
            return
        if isinstance(ev, DisaggReady):
            if self.is_host0:
                return                      # coordinator already applied
            entry = self._disagg_seqs.get(ev.seq_id)
            if entry is None:
                return                      # admit failed / aborted
            seq = entry[0]
            seq.mm.vis_embeds[ev.lo:ev.hi] = _resolve_array(
                ev.rows, self._blob_client.fetch)
            seq.disagg.ready[ev.k] = True
            if seq.disagg.all_ready:
                self._disagg_seqs.pop(ev.seq_id, None)
            return
        if isinstance(ev, DisaggAbort):
            self._disagg_seqs.pop(ev.seq_id, None)
            if ev.seq_id in llm._seq_replica:    # reached a scheduler
                llm.abort(ev.seq_id)
            if self.is_host0:
                self.on_output(("error", ev.seq_id, "abort"))

    def abort(self, seq_id: int) -> None:
        with self._lock:
            self._pending_aborts.append(seq_id)
            if self.is_host0 and self.coord is not None:
                self._disagg_aborts.append(seq_id)
        if self.is_host0 and self.coord is not None:
            self.coord.abort([seq_id])

    def shutdown(self) -> None:
        self._shutdown = True

    # ---- engine loop (every host) -----------------------------------------

    def _apply_tick(self, tick: Tick) -> None:
        from gllm_tpu.sampling_params import SamplingParams
        llm = self.llm
        for rd in tick.requests:
            if self.is_host0:
                seq = self._seqs.pop(rd.seq_id, None)
            else:
                sp = SamplingParams(**rd.sampling)
                seq = llm._allocate_seq(rd.token_ids, sp)
                # keep seq-id allocation identical across hosts
                seq.seq_id = rd.seq_id
                if rd.mm:
                    from gllm_tpu.engine.mm import build_mm_state
                    mm = _resolve_blobs(rd.mm, self._blob_client.fetch)
                    seq.mm = build_mm_state(rd.token_ids, llm.model_cfg,
                                            **mm)
            try:
                llm.add_seq(seq)
            except ValueError as e:
                # deterministic on every host (same validation) — only
                # host 0 reports
                if self.is_host0:
                    self.on_output(("error", rd.seq_id, str(e)))
        for sid in tick.aborts:
            llm.abort(sid)
        for ev in tick.disagg:
            self._apply_disagg_event(ev)

    def _loop(self) -> None:
        import jax
        llm = self.llm
        # startup handshake: followers learn the blob-server address
        addr = broadcast_payload(self._blob_addr)
        peer_srv = None
        if not self.is_host0 and addr:
            self._blob_client = BlobClient(addr)
        if addr and jax.process_count() > 2:
            # chain fan-out: every follower serves its LRU to the next
            # process; allgather the peer addresses and point follower p
            # at follower p-1 (follower 1 keeps host 0)
            my_peer = None
            if not self.is_host0:
                peer_srv = PeerBlobServer(self._blob_client)
                # Advertise the IP of the interface that actually routes
                # to host 0 (gethostbyname(hostname) commonly resolves to
                # loopback in containers). A UDP connect performs no
                # traffic but binds the socket to the outbound interface.
                host0_ip = addr.rpartition(":")[0]
                my_ip = outbound_ip(host0_ip)
                # Loopback is only usable when host 0 itself is loopback
                # (single-machine topology); across machines it would point
                # the child at itself.
                host0_local = (host0_ip == "localhost"
                               or host0_ip.startswith("127."))
                if my_ip and (host0_local
                              or not my_ip.startswith("127.")):
                    my_peer = f"{my_ip}:{peer_srv.port}"
                # else: advertise None — children skip an unusable parent
                # and keep host 0, instead of burning retries on a wrong
                # endpoint.
            peers = allgather_payload(my_peer)
            p = jax.process_index()
            if p >= 2 and peers[p - 1]:
                self._blob_client.set_parent(peers[p - 1])
        while True:
            if self.is_host0:
                dblobs: dict = {}
                devts = (self._drain_disagg_host0(dblobs)
                         if self.coord is not None else [])
                if dblobs and self._blob_store is not None:
                    self._blob_store.put(dblobs)
                with self._lock:
                    tick = Tick(self._pending, self._pending_aborts,
                                self._shutdown, disagg=devts)
                    self._pending = []
                    self._pending_aborts = []
            else:
                tick = None
            tick = broadcast_payload(tick)
            if self._blob_store is not None:
                # this broadcast completing means every follower fully
                # applied the PREVIOUS tick (blob fetches included) —
                # its blobs can retire now
                def keys_of(tick_):
                    ks = {v.key for rd in tick_.requests if rd.mm
                          for v in rd.mm.values()
                          if isinstance(v, BlobRef)}
                    for ev in tick_.disagg:
                        for v in vars(ev).values():
                            if isinstance(v, BlobRef):
                                ks.add(v.key)
                    return ks

                new_keys = keys_of(tick)
                with self._lock:
                    # keep alive: this tick's keys AND keys of requests
                    # already submitted for the next tick (same content
                    # re-submitted must not lose its bytes to the retire
                    # of an older tick)
                    live = new_keys | {
                        v.key for rd in self._pending if rd.mm
                        for v in rd.mm.values() if isinstance(v, BlobRef)}
                    self._blob_store.retire(
                        set(self._inflight_keys) - live)
                self._inflight_keys = list(new_keys)
            if tick.shutdown:
                if self._blob_store is not None:
                    self._blob_store.close()
                if peer_srv is not None:
                    peer_srv.close()
                return
            self._apply_tick(tick)
            if llm.has_unfinished:
                try:
                    outs = llm.step()
                except Exception:
                    # deterministic loops fail identically on every host;
                    # report on host 0 and drain to a clean shutdown tick
                    logger.exception("engine step failed")
                    if self.is_host0:
                        self.on_output(("fail", None))
                        self._shutdown = True
                    continue
                if self.is_host0:
                    for out in outs:
                        self.on_output(("out", out))
            else:
                time.sleep(self.tick_interval)

    def run_host0(self) -> None:
        assert self.is_host0
        self._loop()

    def run_follower(self) -> None:
        assert not self.is_host0
        self._loop()


class MultihostServingEngine:
    """ServingEngine-compatible frontend over MultihostEngine (host 0).

    The HTTP handlers use the same submit/abort/shutdown surface and
    per-request chunk queues as the single-host ServingEngine.
    """

    def __init__(self, llm, advertise_host: Optional[str] = None):
        import threading

        from gllm_tpu.engine.serving_engine import (RequestHandle,
                                                    deliver_output)
        self.llm = llm
        self._handles = {}
        self._emitted: dict = {}
        self._deliver = deliver_output
        self._make_handle = RequestHandle

        def on_output(evt):
            from gllm_tpu.engine.serving_engine import StreamChunk
            if evt[0] == "error":
                _, sid, reason = evt
                h = self._handles.pop(sid, None)
                if h is not None:
                    h.chunks.put(StreamChunk(None, "", reason or "error"))
                return
            if evt[0] == "fail":
                for h in list(self._handles.values()):
                    h.chunks.put(StreamChunk(None, "", "error"))
                self._handles.clear()
                self._emitted.clear()
                return
            out = evt[1]
            h = self._handles.get(out.seq.seq_id)
            if h is None:
                return
            self._deliver(self.llm, out, h, self._emitted)
            if out.finish_reason is not None:
                self._handles.pop(out.seq.seq_id, None)

        self.engine = MultihostEngine(llm, on_output=on_output,
                                      advertise_host=advertise_host)
        self._thread = threading.Thread(target=self.engine.run_host0,
                                        daemon=True, name="gllm-mh-engine")
        self._thread.start()

    def submit(self, token_ids, sampling_params, mm_input=None,
               disagg_items=None, target_dp=None):
        # target_dp (per-DP-endpoint pinning) is accepted for interface
        # parity with ServingEngine but ignored: the multihost plane runs
        # dp=1 per host group (replica routing happens in the engine loop)
        if disagg_items:
            # coordinator runs on host 0; the admit reaches every host as
            # a tick event (gate-B flips ride the blob channel)
            if self.engine.coord is None:
                raise ValueError("this engine is not a disagg LM node "
                                 "(no coordinator initialized)")
            sampling_params.validate()
            with self.engine._lock:      # seq-id allocation is shared
                seq = self.llm._allocate_seq(list(token_ids),
                                             sampling_params)
                handle = self._make_handle(seq.seq_id, len(token_ids))
                self._handles[seq.seq_id] = handle
            try:
                self.engine.submit_disagg(seq, disagg_items)
            except Exception:
                self._handles.pop(seq.seq_id, None)
                raise
            return handle
        sampling_params.validate()
        box = {}

        def on_register(sid):
            # under the intake lock, before the engine loop can see the
            # request — no output chunk can race past the handle
            box["handle"] = self._make_handle(sid, len(token_ids))
            self._handles[sid] = box["handle"]

        self.engine.submit(token_ids, sampling_params,
                           on_register=on_register, mm_input=mm_input)
        return box["handle"]

    def abort(self, seq_id: int) -> None:
        self.engine.abort(seq_id)
        # aborted seqs produce no further SeqOutput — close the stream now
        h = self._handles.pop(seq_id, None)
        self._emitted.pop(seq_id, None)
        if h is not None:
            from gllm_tpu.engine.serving_engine import StreamChunk
            h.chunks.put(StreamChunk(None, "", "abort"))

    def shutdown(self) -> None:
        self.engine.shutdown()
        self._thread.join(timeout=10)
