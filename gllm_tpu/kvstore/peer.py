"""Cluster tier of the prefix KV store: digest-addressed page exchange.

Modeled on the multihost blob channel (docs/multihost_blob_channel.md):
the same content-addressed pull protocol, the same "a vanished peer
degrades the path, never fails it" posture. The difference is the payload — KV prefix
pages instead of media blobs — which adds two obligations:

- **geometry negotiation.** A fetched page is written straight into the
  local host pool, so both sides must agree on page size, per-leaf
  shapes, and kv dtype (an int8-KV replica's pages are half the bytes of
  a bf16 replica's and mean different numbers). The first exchange on a
  connection is ``hello`` → the server's ``pagefmt.pool_geometry``; any
  mismatch disables that peer for the life of the client.
- **verification at the trust boundary.** The server ships payloads
  unverified (it may be streaming straight off its disk tier); the
  CLIENT unpacks against its own geometry and checks digest + canary
  before anything touches the pool. A bad payload is a miss, never an
  exception on the scheduling path.

Probe-latency contract: ``fetch`` is bounded by ``timeout_s`` per live
peer (connect + request + response all under one socket deadline) and a
failed/slow peer backs off, so the scheduler's match_prefix walk can
never stall on the network — the ``peer_prefix_timeout`` chaos point
proves the degrade path in tests.

Wire framing is deliberately NOT the pickle framing of
``disagg/wire.py`` (that plane runs between mutually trusting processes
of one deployment): control frames here are ``[u32 len][JSON utf-8]``
and page payloads are the raw ``pagefmt`` bytes — nothing received from
a peer is ever unpickled, so a hostile or compromised peer can feed us
at worst a payload that fails digest/canary/geometry verification.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import socketserver
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from gllm_tpu.faults import FAULTS
from gllm_tpu.kvstore import stats
from gllm_tpu.kvstore.pagefmt import verify_payload

logger = logging.getLogger(__name__)

# Provider signature: digest -> packed payload (or None). The manager
# backs this with host pool + disk tier.
Provider = Callable[[bytes], Optional[bytes]]

_LEN = struct.Struct("!I")
_MAX_FRAME = 1 << 20            # control frames are tiny; cap hostile ones


def _send_frame(sock: socket.socket, obj: dict,
                raw: Optional[bytes] = None) -> None:
    """``[u32][json]`` control frame, optionally followed by
    ``[u32][raw bytes]`` (the pagefmt payload, shipped un-decoded)."""
    body = json.dumps(obj, separators=(",", ":")).encode()
    parts = [_LEN.pack(len(body)), body]
    if raw is not None:
        parts += [_LEN.pack(len(raw)), raw]
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None) -> Optional[bytes]:
    """Like ``disagg/wire._recv_exact`` but DEADLINE-aware: the per-op
    socket timeout alone lets a slow-dribbling peer stretch one logical
    read to (bytes / chunk) × timeout — here the remaining wall budget
    re-arms the socket timeout before every chunk, so the WHOLE read is
    bounded (the reason this is not shared with wire.py, whose trusted
    plane wants blocking reads)."""
    buf = bytearray()
    while len(buf) < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("peer read deadline exceeded")
            sock.settimeout(remaining)
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket, limit: int = _MAX_FRAME,
                deadline: Optional[float] = None) -> Optional[dict]:
    head = _recv_exact(sock, _LEN.size, deadline)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > limit:
        raise OSError(f"oversized peer frame ({n} B)")
    body = _recv_exact(sock, n, deadline)
    if body is None:
        return None
    obj = json.loads(body.decode())
    if not isinstance(obj, dict):
        raise OSError("peer frame is not an object")
    return obj


def _recv_payload(sock: socket.socket, limit: int,
                  deadline: Optional[float] = None) -> Optional[bytes]:
    head = _recv_exact(sock, _LEN.size, deadline)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > limit:
        raise OSError(f"oversized peer payload ({n} B)")
    return _recv_exact(sock, n, deadline)


def parse_peer_addr(addr: str) -> Tuple[str, int]:
    """``host:port`` → validated pair; raises ``ValueError`` on a
    malformed entry (checked at construction/config time so a typo in
    ``--prefix-peers`` fails startup, not the first scheduling probe)."""
    host, sep, port = addr.strip().rpartition(":")
    if not sep or not host:
        raise ValueError(f"peer address {addr!r} is not host:port")
    try:
        port_n = int(port)
    except ValueError:
        raise ValueError(f"peer address {addr!r} has a non-numeric port")
    if not 0 < port_n < 65536:
        raise ValueError(f"peer address {addr!r} port out of range")
    return host, port_n


class PeerPrefixServer:
    """Read-only prefix-page endpoint over this replica's host + disk
    tiers. One of these per serving replica (``--prefix-serve-port``);
    other replicas point ``--prefix-peers`` at it."""

    IDLE_S = 60.0

    def __init__(self, provider: Provider, geometry: dict,
                 host: str = "0.0.0.0", port: int = 0):
        self._provider = provider
        self._geometry = geometry
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.request.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                # idle bound: a connection that sends nothing (port
                # scanner, wedged client) releases its handler thread
                # and fd instead of pinning them forever
                self.request.settimeout(PeerPrefixServer.IDLE_S)
                while True:
                    try:
                        msg = _recv_frame(self.request)
                        if msg is None:
                            return
                        outer._on_req(msg, self.request)
                    except (OSError, ValueError):
                        # idle timeout, hostile frame, or the client
                        # hanging up mid-reply (its fetch deadline is
                        # shorter than a slow send) — routine, not an
                        # error: just drop the connection
                        return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Server((host, port), _Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        logger.info("prefix peer server on port %d", self.port)

    def _on_req(self, msg: dict, sock) -> None:
        op = msg.get("op")
        if op == "hello":
            _send_frame(sock, {"geometry": self._geometry})
        elif op == "get":
            try:
                digest = bytes.fromhex(msg.get("digest", ""))
            except (TypeError, ValueError):
                _send_frame(sock, {"hit": False}, raw=b"")
                return
            try:
                payload = self._provider(digest)
            except Exception:            # serving must never kill the conn
                logger.exception("prefix serve failed for %s",
                                 msg.get("digest"))
                payload = None
            if payload is not None:
                stats.PEER_SERVED.inc()
                stats.BYTES.inc(len(payload), tier="peer", dir="write")
            _send_frame(sock, {"hit": payload is not None},
                        raw=payload or b"")

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class PrefixClient:
    """Fetch-by-digest against a list of peer replicas.

    Peers are tried in order; each attempt is deadline-bounded and a
    peer that times out / errors backs off for ``BACKOFF_S`` (a
    geometry-mismatched peer is disabled permanently). Thread-safe for
    the single engine thread that probes it; sockets are cached per
    peer.
    """

    BACKOFF_S = 30.0

    def __init__(self, peers: Sequence[str], geometry: dict,
                 timeout_s: Optional[float] = None):
        self.geometry = geometry
        # expected payload size: geometry is fixed, so anything larger
        # than the page bytes + header slack is hostile/corrupt
        from gllm_tpu.kvstore.pagefmt import geometry_bytes
        self._payload_limit = geometry_bytes(geometry) + 4096
        self.timeout_s = (timeout_s if timeout_s is not None else float(
            os.environ.get("GLLM_PREFIX_PEER_TIMEOUT_S", "2.0")))
        # guards peer/socket state: fetch() runs on the engine thread,
        # close() on whatever thread drives shutdown
        self._lock = threading.Lock()
        self._closed = False
        # addr -> {sock, negotiated (None=not yet, False=refused),
        #          down_until}; parse up front so a malformed
        #          --prefix-peers entry fails construction, not the
        #          first scheduling probe
        self._peers: Dict[Tuple[str, int], dict] = {
            parse_peer_addr(a): {"sock": None, "negotiated": None,
                                 "down_until": 0.0}
            for a in peers if a.strip()}
        if not self._peers:
            raise ValueError("prefix client needs at least one peer")

    # ---- connection management -------------------------------------------

    def _connect(self, addr: Tuple[str, int]) -> socket.socket:
        sock = socket.create_connection(addr, timeout=self.timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _drop(self, addr, st: dict, backoff: bool = True) -> None:
        sock, st["sock"] = st["sock"], None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if backoff:
            st["down_until"] = time.monotonic() + self.BACKOFF_S

    def _negotiate(self, addr, st: dict, sock: socket.socket,
                   deadline: Optional[float] = None) -> bool:
        """hello → geometry check, once per client lifetime per peer."""
        _send_frame(sock, {"op": "hello"})
        reply = _recv_frame(sock, deadline=deadline)
        if reply is None:
            raise OSError("bad hello reply")
        if reply.get("geometry") != self.geometry:
            logger.warning(
                "prefix peer %s refused: page geometry/kv-dtype mismatch "
                "(%s vs local %s) — peer disabled", addr,
                {k: reply.get("geometry", {}).get(k)
                 for k in ("page_size", "v")},
                {k: self.geometry[k] for k in ("page_size", "v")})
            st["negotiated"] = False
            self._drop(addr, st, backoff=False)
            return False
        st["negotiated"] = True
        return True

    # ---- fetch ------------------------------------------------------------

    def fetch(self, digest: bytes, tokens) -> Optional[
            Tuple[List[np.ndarray], Optional[bytes]]]:
        """``(leaves, parent)`` from the first peer that can serve this
        digest, canary-verified; None = every peer missed / was down.
        Bounded: one ``timeout_s`` deadline per live peer, no retries
        inside the call."""
        if FAULTS.fire("peer_prefix_timeout"):
            # chaos point (docs/robustness.md): the whole peer tier
            # behaves as a deadline expiry — the probe degrades to the
            # next tier (recompute) without stalling
            stats.PEER_TIMEOUTS.inc()
            stats.MISSES.inc(tier="peer")
            return None
        now = time.monotonic()
        with self._lock:
            peers = list(self._peers.items())
        for addr, st in peers:
            if st["negotiated"] is False or now < st["down_until"]:
                continue
            # ONE wall-clock budget covers connect + hello + request +
            # full response for this peer — a dribbling sender can't
            # stretch a probe past timeout_s by keeping each recv alive
            deadline = time.monotonic() + self.timeout_s
            hdr = raw = None
            for _retry in range(2):
                try:
                    # hold a LOCAL ref: a concurrent close() nulls
                    # st["sock"], and the closed socket must surface as
                    # the OSError below, never an AttributeError
                    with self._lock:
                        if self._closed:
                            return None
                        sock = st["sock"]
                        fresh = sock is None
                        if fresh:
                            sock = st["sock"] = self._connect(addr)
                    if st["negotiated"] is None and not self._negotiate(
                            addr, st, sock, deadline):
                        break
                    _send_frame(sock, {"op": "get",
                                       "digest": digest.hex()})
                    hdr = _recv_frame(sock, deadline=deadline)
                    raw = (None if hdr is None else
                           _recv_payload(sock, self._payload_limit,
                                         deadline))
                    if hdr is None or raw is None:
                        raise OSError("peer closed mid-reply")
                    break
                except (socket.timeout, TimeoutError):
                    stats.PEER_TIMEOUTS.inc()
                    logger.warning("prefix peer %s timed out (%.1fs); "
                                   "backing off", addr, self.timeout_s)
                    self._drop(addr, st)
                    break
                except (OSError, ConnectionError, ValueError):
                    # ValueError = garbled JSON control frame: same
                    # posture as a broken pipe. A CACHED socket may
                    # just have idled past the server's IDLE_S — retry
                    # once on a fresh connection before backing off.
                    hdr = raw = None
                    self._drop(addr, st, backoff=fresh)
                    if fresh:
                        break
            if not (hdr and hdr.get("hit") and raw):
                continue        # clean miss or transport failure here
            try:
                leaves, parent = verify_payload(raw, self.geometry,
                                                digest, tokens)
            except (ValueError, KeyError):
                stats.POISON.inc(tier="peer")
                continue
            stats.HITS.inc(tier="peer")
            stats.BYTES.inc(len(raw), tier="peer", dir="read")
            return leaves, parent
        stats.MISSES.inc(tier="peer")
        return None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for addr, st in self._peers.items():
                self._drop(addr, st, backoff=False)
