"""Model-native tool-call markup → OpenAI ``tool_calls``.

Covers the reference's tool-parser suite
(/root/reference/gllm/tokenizers/tool_parsers.py, 673 LoC): per-model-family
parsers that extract tool invocations from generated text, with
schema-driven argument type coercion, plus auto-detection from the model
name (reference api_server.py:543-575).

Formats:
- ``qwen`` (hermes-style, Qwen/Qwen2.5/Qwen3):
  ``<tool_call>\\n{"name": ..., "arguments": {...}}\\n</tool_call>``
- ``qwen3.5`` (XML form the Qwen3.5 hybrids natively emit):
  ``<tool_call><function=NAME><parameter=ARG>VALUE</parameter>...
  </function></tool_call>``
- ``deepseek`` (DeepSeek V3-family unicode-fenced sections):
  ``<｜tool▁calls▁begin｜><｜tool▁call▁begin｜>NAME<｜tool▁sep｜>JSON
  <｜tool▁call▁end｜>...<｜tool▁calls▁end｜>``
"""

from __future__ import annotations

import dataclasses
import json
import re
import uuid
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class ToolCall:
    name: str
    arguments: str              # JSON-encoded string (OpenAI wire format)
    id: str = ""

    def to_openai(self) -> dict:
        return {
            "id": self.id or f"call_{uuid.uuid4().hex[:24]}",
            "type": "function",
            "function": {"name": self.name, "arguments": self.arguments},
        }


def coerce_arguments(args: Dict[str, Any],
                     schema: Optional[dict]) -> Dict[str, Any]:
    """Schema-driven argument type coercion (reference tool_parsers.py):
    models emit numbers/bools as strings; fix them up against the declared
    parameter types."""
    if not schema:
        return args
    props = schema.get("properties", {})
    out = {}
    for k, v in args.items():
        typ = props.get(k, {}).get("type")
        try:
            if typ == "integer" and isinstance(v, str):
                v = int(v)
            elif typ == "number" and isinstance(v, str):
                v = float(v)
            elif typ == "boolean" and isinstance(v, str):
                v = v.strip().lower() in ("true", "1", "yes")
            elif typ in ("object", "array") and isinstance(v, str):
                v = json.loads(v)
        except (ValueError, json.JSONDecodeError):
            pass
        out[k] = v
    return out


class ToolParser:
    """Base: no tool support — everything is content."""

    #: literal strings whose appearance means tool markup is starting;
    #: the streaming adapter holds back only potential-marker suffixes.
    STREAM_MARKERS: Tuple[str, ...] = ()
    #: literal strings that terminate one call unit; the streaming adapter
    #: only re-parses when a NEW end marker arrives, so per-call work is
    #: O(unit) once instead of O(unit) per token.
    END_MARKERS: Tuple[str, ...] = ()

    def parse(self, text: str,
              schemas: Optional[Dict[str, dict]] = None
              ) -> Tuple[str, List[ToolCall]]:
        return text, []

    def completed_calls(self, text: str,
                        schemas: Optional[Dict[str, dict]] = None
                        ) -> Tuple[List[ToolCall], int]:
        """(calls, consumed) for the streaming adapter: calls whose markup
        is COMPLETE in ``text`` (which may end mid-markup), plus the char
        offset past the last complete unit so the caller never re-parses
        emitted markup. Default: a full parse, nothing consumed."""
        return self.parse(text, schemas)[1], 0


class QwenToolParser(ToolParser):
    _RE = re.compile(r"<tool_call>\s*(\{.*?\})\s*</tool_call>", re.DOTALL)
    STREAM_MARKERS = ("<tool_call>",)
    END_MARKERS = ("</tool_call>",)

    def parse(self, text, schemas=None):
        calls: List[ToolCall] = []

        def repl(match):
            try:
                obj = json.loads(match.group(1))
            except json.JSONDecodeError:
                return match.group(0)  # leave malformed markup as content
            name = obj.get("name", "")
            args = obj.get("arguments", {})
            if isinstance(args, dict) and schemas:
                args = coerce_arguments(args, schemas.get(name))
            calls.append(ToolCall(name=name, arguments=json.dumps(
                args, ensure_ascii=False)))
            return ""

        content = self._RE.sub(repl, text).strip()
        return content, calls

    def completed_calls(self, text, schemas=None):
        calls, end = [], 0
        for m in self._RE.finditer(text):
            try:
                obj = json.loads(m.group(1))
            except json.JSONDecodeError:
                # Malformed unit: stop consuming HERE so `end` never
                # advances past it — the markup stays in the buffer for
                # finish() to surface as content, matching the
                # non-streaming parse() (a later valid unit must not
                # swallow it).
                break
            args = obj.get("arguments", {})
            name = obj.get("name", "")
            if isinstance(args, dict) and schemas:
                args = coerce_arguments(args, schemas.get(name))
            calls.append(ToolCall(name=name, arguments=json.dumps(
                args, ensure_ascii=False)))
            end = m.end()
        return calls, end


class Qwen3XmlToolParser(ToolParser):
    """Qwen3.5 native XML tool markup (reference tool_parsers.py:346-425):

    ``<tool_call>\\n<function=NAME>\\n<parameter=ARG>\\nVALUE\\n</parameter>
    ...\\n</function>\\n</tool_call>``

    Parameter values arrive as raw text with no type information; they are
    type-corrected against the declared JSON schema via
    :func:`coerce_arguments` — ``string`` params stay strings (schema-less
    ``json.loads`` on every value would break BFCL's string-typed
    categories), everything else is coerced to its declared type.

    Robustness choices mirrored from the reference: a value runs until its
    ``</parameter>``, the next ``<parameter=``, or the end of the function
    body (the model sometimes drops the final closing tag); ``<function=``
    blocks are scanned in the whole text so a garbled ``</tool_call>``
    does not hide calls. Deviation: we also treat a bare ``<function=``
    (no enclosing ``<tool_call>``) as tool markup, so the streaming
    adapter never leaks half a call as content."""

    _FUNC = re.compile(r"<function=(?P<name>[^>\n]+)>(?P<body>.*?)"
                       r"</function>", re.DOTALL)
    _PARAM = re.compile(r"<parameter=(?P<key>[^>\n]+)>(?P<val>.*?)"
                        r"(?:</parameter>|(?=<parameter=)|\Z)", re.DOTALL)
    STREAM_MARKERS = ("<tool_call>", "<function=")
    # One call unit is complete at </function> — before the trailing
    # </tool_call> ever arrives, so streamed calls surface a token early.
    END_MARKERS = ("</function>",)

    def _call_from(self, m: "re.Match", schemas) -> Optional[ToolCall]:
        name = m.group("name").strip()
        if not name:
            return None
        args = {k: v for k, v in
                ((pm.group("key").strip(), pm.group("val").strip())
                 for pm in self._PARAM.finditer(m.group("body"))) if k}
        if schemas:
            args = coerce_arguments(args, schemas.get(name))
        return ToolCall(name=name,
                        arguments=json.dumps(args, ensure_ascii=False))

    _BLOCK = re.compile(r"<tool_call>\s*(?:<function=.*?</function>\s*)*"
                        r"(?:</tool_call>)?|<function=.*?</function>"
                        r"|</tool_call>",   # orphaned closer (interleaved
                        re.DOTALL)          # text split it from its opener)

    def parse(self, text, schemas=None):
        calls = [c for c in (self._call_from(m, schemas)
                             for m in self._FUNC.finditer(text)) if c]
        if not calls:
            # Prose that merely mentions the markup (or malformed markup)
            # passes through untouched, like the hermes parser.
            return text, []
        # Remove only the matched markup; assistant text before, between,
        # and after the calls survives (the reference keeps only the
        # prefix — ours deliberately preserves trailing text too, matching
        # our hermes behavior and its streaming finish() contract).
        return self._BLOCK.sub("", text).strip(), calls

    def completed_calls(self, text, schemas=None):
        calls, end = [], 0
        for m in self._FUNC.finditer(text):
            c = self._call_from(m, schemas)
            if c:
                calls.append(c)
            end = m.end()
        return calls, end


class DeepSeekToolParser(ToolParser):
    _BLOCK = re.compile(
        r"<｜tool▁calls▁begin｜>(.*?)<｜tool▁calls▁end｜>", re.DOTALL)
    _CALL = re.compile(
        r"<｜tool▁call▁begin｜>(.*?)<｜tool▁sep｜>(.*?)<｜tool▁call▁end｜>",
        re.DOTALL)
    STREAM_MARKERS = ("<｜tool▁calls▁begin｜>", "<｜tool▁call▁begin｜>")
    END_MARKERS = ("<｜tool▁call▁end｜>",)

    @staticmethod
    def _strip_fence(payload: str) -> str:
        payload = payload.strip()
        if payload.startswith("```json"):
            payload = payload[7:]
        elif payload.startswith("```"):
            payload = payload[3:]
        return payload.strip().rstrip("`").strip()

    def _parse_call(self, head: str, body: str, schemas) -> ToolCall:
        head = head.strip()
        body = body.strip()
        # Two layouts in the wild:
        #   stock V3/R1 template: head == "function",
        #     body == "NAME\n```json\nARGS\n```"
        #   simplified:           head == NAME, body == ARGS-json
        if head == "function" or "```" in body:
            name, _, fenced = body.partition("\n")
            name = name.strip()
            payload = self._strip_fence(fenced)
        else:
            name = head
            payload = self._strip_fence(body)
        try:
            args = json.loads(payload) if payload else {}
        except json.JSONDecodeError:
            args = {}
        if schemas:
            args = coerce_arguments(args, schemas.get(name))
        return ToolCall(name=name,
                        arguments=json.dumps(args, ensure_ascii=False))

    def parse(self, text, schemas=None):
        calls: List[ToolCall] = []

        def repl(match):
            for head, body in self._CALL.findall(match.group(1)):
                calls.append(self._parse_call(head, body, schemas))
            return ""

        content = self._BLOCK.sub(repl, text).strip()
        return content, calls

    def completed_calls(self, text, schemas=None):
        # Per-call units complete before the section end marker arrives.
        calls, end = [], 0
        for m in self._CALL.finditer(text):
            calls.append(self._parse_call(m.group(1), m.group(2), schemas))
            end = m.end()
        return calls, end


class KimiToolParser(ToolParser):
    """Kimi K2/K2.5 markup (reference tool_parsers.py:429-481):

    ``<|tool_calls_section_begin|>`` wraps the calls; each call is
    ``<|tool_call_begin|>functions.NAME:IDX<|tool_call_argument_begin|>
    JSON<|tool_call_end|>``."""

    _SECTION = "<|tool_calls_section_begin|>"
    _CALL = re.compile(
        r"<\|tool_call_begin\|>\s*([^\s<]+?)\s*"
        r"<\|tool_call_argument_begin\|>\s*(.*?)\s*<\|tool_call_end\|>",
        re.DOTALL)
    STREAM_MARKERS = (_SECTION,)
    END_MARKERS = ("<|tool_call_end|>",)

    @staticmethod
    def _name_from_id(fid: str) -> str:
        # ids look like "functions.get_weather:0"
        fid = fid.split(":", 1)[0]
        return fid[len("functions."):] if fid.startswith("functions.") \
            else fid

    def parse(self, text, schemas=None):
        if self._SECTION not in text:
            return text, []
        calls: List[ToolCall] = []
        for fid, payload in self._CALL.findall(text):
            name = self._name_from_id(fid.strip())
            if not name:
                continue
            try:
                args = json.loads(payload) if payload.strip() else {}
            except json.JSONDecodeError:
                args = {}
            if isinstance(args, dict) and schemas:
                args = coerce_arguments(args, schemas.get(name))
            calls.append(ToolCall(name=name, arguments=json.dumps(
                args, ensure_ascii=False)))
        content = text.split(self._SECTION, 1)[0].strip()
        return content, calls

    def completed_calls(self, text, schemas=None):
        calls, end = [], 0
        for m in self._CALL.finditer(text):
            name = self._name_from_id(m.group(1).strip())
            if not name:
                continue
            payload = m.group(2)
            try:
                args = json.loads(payload) if payload.strip() else {}
            except json.JSONDecodeError:
                args = {}
            if isinstance(args, dict) and schemas:
                args = coerce_arguments(args, schemas.get(name))
            calls.append(ToolCall(name=name, arguments=json.dumps(
                args, ensure_ascii=False)))
            end = m.end()
        return calls, end


class StreamingToolCalls:
    """Incremental SSE adapter over a ToolParser (role of the reference's
    streaming tool parsers, tool_parsers.py — ours completes per call-unit
    rather than per argument token). Text deltas pass through immediately;
    only a trailing fragment that could begin tool markup is held back.
    Once markup starts, each completed call is emitted as the standard
    OpenAI delta pair (id+name, then the full arguments string)."""

    def __init__(self, parser: ToolParser,
                 schemas: Optional[Dict[str, dict]] = None):
        self.parser = parser
        self.schemas = schemas or {}
        self.buf = ""
        self.in_tool = False
        self.n_emitted = 0
        self._done = 0       # buf offset past already-emitted call units
        self._scanned = 0    # buf offset end-marker search has covered

    def _held_suffix_len(self) -> int:
        """Longest buffer suffix that is a proper prefix of a marker."""
        best = 0
        for m in self.parser.STREAM_MARKERS:
            for k in range(min(len(m) - 1, len(self.buf)), 0, -1):
                if self.buf.endswith(m[:k]):
                    best = max(best, k)
                    break
        return best

    def _emit_new(self, calls: List[ToolCall]) -> List[dict]:
        """OpenAI streamed tool_call delta pair per NEW call (indices
        continue from what was already emitted)."""
        deltas = []
        for call in calls:
            i, c = self.n_emitted, call.to_openai()
            deltas.append({"index": i, "id": c["id"], "type": "function",
                           "function": {"name": c["function"]["name"],
                                        "arguments": ""}})
            deltas.append({"index": i, "function": {
                "arguments": c["function"]["arguments"]}})
            self.n_emitted += 1
        return deltas

    def feed(self, delta: str) -> Tuple[str, List[dict]]:
        """Returns (text_delta_to_emit, tool_call_deltas)."""
        self.buf += delta
        if not self.parser.STREAM_MARKERS:
            out, self.buf = self.buf, ""
            return out, []
        text = ""
        if not self.in_tool:
            hits = [i for i in (self.buf.find(m)
                                for m in self.parser.STREAM_MARKERS)
                    if i >= 0]
            if hits:
                cut = min(hits)
                text, self.buf = self.buf[:cut], self.buf[cut:]
                self.in_tool = True
            else:
                keep = self._held_suffix_len()
                cut = len(self.buf) - keep
                text, self.buf = self.buf[:cut], self.buf[cut:]
        deltas = []
        if self.in_tool and self._new_unit_ended():
            # only the unconsumed tail is re-parsed, and only when a NEW
            # end marker arrived — O(unit) per completed call, not per token
            calls, end = self.parser.completed_calls(self.buf[self._done:],
                                                     self.schemas)
            deltas = self._emit_new(calls)
            self._done += end
        return text, deltas

    def _new_unit_ended(self) -> bool:
        ends = self.parser.END_MARKERS
        if not ends:
            return True     # no marker info → parse every feed
        overlap = max(len(m) for m in ends) - 1
        start = max(self._done, self._scanned - overlap)
        window = self.buf[start:]
        self._scanned = len(self.buf)
        return any(m in window for m in ends)

    def finish(self) -> Tuple[str, List[dict]]:
        """Flush: full parse of the held buffer. Content surviving the
        parse (trailing / interleaved assistant text, malformed markup) is
        returned as a final text delta; not-yet-emitted calls as deltas."""
        content, calls = self.parser.parse(self.buf, self.schemas)
        if self.in_tool:
            # A stream can end mid-section (e.g. length-capped before the
            # section-end marker): recover the complete per-unit calls and
            # drop the raw markup remnant instead of leaking it as content.
            unit_calls, _ = self.parser.completed_calls(self.buf,
                                                        self.schemas)
            if len(unit_calls) > len(calls):
                calls = unit_calls
                content = ""
        self.buf = ""
        return content, self._emit_new(calls[self.n_emitted:])

    @property
    def saw_tool_calls(self) -> bool:
        return self.n_emitted > 0


_PARSERS = {
    "qwen": QwenToolParser,
    "hermes": QwenToolParser,
    "qwen3.5": Qwen3XmlToolParser,
    "qwen3_5": Qwen3XmlToolParser,
    "qwen_xml": Qwen3XmlToolParser,
    "deepseek": DeepSeekToolParser,
    "kimi": KimiToolParser,
    "none": ToolParser,
}


def _is_qwen35(s: str) -> bool:
    return "qwen3.5" in s or "qwen3_5" in s or "qwen3-5" in s


def get_tool_parser(name: Optional[str] = None,
                    model_name: str = "",
                    architecture: str = "") -> ToolParser:
    """Explicit name, or auto-detect from the model id / architecture
    (reference api_server.py:543-575 + tool_parsers.py:616-623: Qwen3.5
    switched from Hermes JSON to the ``<function=..>`` XML form, so the
    qwen-family resolves on the architecture string)."""
    if name:
        if name not in _PARSERS:
            raise ValueError(f"unknown tool parser {name!r}; "
                             f"choices: {sorted(_PARSERS)}")
        if name == "qwen" and _is_qwen35(architecture.lower()):
            return Qwen3XmlToolParser()
        return _PARSERS[name]()
    m = model_name.lower()
    arch = architecture.lower()
    if _is_qwen35(m) or _is_qwen35(arch):
        return Qwen3XmlToolParser()
    if "qwen" in m or "qwen" in arch:
        return QwenToolParser()
    if "deepseek" in m or "deepseek" in arch:
        return DeepSeekToolParser()
    if "kimi" in m or "kimi" in arch:
        return KimiToolParser()
    return ToolParser()


def schemas_from_tools(tools: Optional[List[dict]]) -> Dict[str, dict]:
    """OpenAI `tools` request field → {name: parameters-schema}."""
    out: Dict[str, dict] = {}
    for t in tools or []:
        fn = t.get("function", {})
        if fn.get("name"):
            out[fn["name"]] = fn.get("parameters", {})
    return out
