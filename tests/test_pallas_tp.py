"""Pallas attention under TP: shard_map wiring vs the XLA oracle.

The reference runs its attention kernel per TP rank with head-sliced q/KV
(/root/reference/gllm/layers/attention.py + dist_utils head division); here
the same partitioning happens via shard_map around the Pallas kernels
(gllm_tpu/ops/attention.py::_pallas_sharded) on the 8-virtual-device CPU
mesh (interpret mode — kernel-vs-oracle numerics, SURVEY.md §4).
"""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from gllm_tpu.ops import attention as attn_mod
from gllm_tpu.ops.attention import (AttentionMetadata, paged_attention,
                                    pallas_tp_compatible)
from gllm_tpu.parallel.mesh import make_mesh


def make_case(rng, *, S, max_q_len, Hq, Hkv, D, v_dim=None, page_size=4,
              max_pages=8):
    """Random mixed batch: half prefill-ish rows, half decode rows."""
    num_pages = S * max_pages + 1
    q_lens = [max(1, int(rng.integers(1, max_q_len + 1))) for _ in range(S)]
    if max_q_len == 1:
        q_lens = [1] * S
    cu = np.zeros(S + 1, np.int32)
    cu[1:] = np.cumsum(q_lens)
    T = int(cu[-1])
    kv_lens = np.array(
        [ql + int(rng.integers(0, max_pages * page_size - max_q_len))
         for ql in q_lens], np.int32)
    kv_lens = np.minimum(kv_lens, max_pages * page_size)
    pt = np.zeros((S, max_pages), np.int32)
    nxt = 1
    for s in range(S):
        n = -(-int(kv_lens[s]) // page_size)
        pt[s, :n] = np.arange(nxt, nxt + n)
        nxt += n
    q = rng.standard_normal((T, Hq, D), np.float32)
    kc = rng.standard_normal((num_pages, page_size, Hkv, D), np.float32)
    vd = v_dim or D
    vc = (None if v_dim is not None
          else rng.standard_normal((num_pages, page_size, Hkv, D),
                                   np.float32))
    md = AttentionMetadata(jnp.asarray(cu), jnp.asarray(kv_lens),
                           jnp.asarray(pt), jnp.int32(S))
    return (jnp.asarray(q), jnp.asarray(kc),
            None if vc is None else jnp.asarray(vc), md, vd)


@pytest.fixture(autouse=True)
def clear_ctx():
    yield
    attn_mod.set_shard_context(None)


@pytest.mark.parametrize("tp,Hq,Hkv,max_q_len", [
    (2, 8, 4, 1),    # heads-sharded decode
    (2, 8, 4, 6),    # heads-sharded mixed/prefill
    (4, 8, 2, 1),    # kv-replicated decode (Hkv % tp != 0)
    (4, 8, 2, 5),    # kv-replicated mixed
])
def test_sharded_pallas_matches_xla(tp, Hq, Hkv, max_q_len):
    rng = np.random.default_rng(0)
    q, kc, vc, md, _ = make_case(rng, S=4, max_q_len=max_q_len, Hq=Hq,
                                 Hkv=Hkv, D=16)
    scale = 16 ** -0.5
    ref = paged_attention(q, kc, vc, md, scale=scale, max_q_len=max_q_len,
                          impl="xla")
    mesh = make_mesh(dp=1, tp=tp)
    attn_mod.set_shard_context(mesh, "tp")
    out = paged_attention(q, kc, vc, md, scale=scale, max_q_len=max_q_len,
                          impl="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("max_q_len", [1, 4])
def test_sharded_pallas_mla_shared_kv(max_q_len):
    """MLA absorbed mode: MQA latent cache replicated over tp, q sharded."""
    rng = np.random.default_rng(1)
    q, kc, _, md, v_dim = make_case(rng, S=3, max_q_len=max_q_len, Hq=8,
                                    Hkv=1, D=32, v_dim=16)
    scale = 32 ** -0.5
    ref = paged_attention(q, kc, None, md, scale=scale, max_q_len=max_q_len,
                          impl="xla", v_dim=v_dim)
    mesh = make_mesh(dp=1, tp=4)
    attn_mod.set_shard_context(mesh, "tp")
    out = paged_attention(q, kc, None, md, scale=scale, max_q_len=max_q_len,
                          impl="pallas", v_dim=v_dim)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_tp_compatibility_matrix():
    assert pallas_tp_compatible(8, 4, 2)
    assert pallas_tp_compatible(8, 2, 4)      # kv replicated, whole groups
    assert pallas_tp_compatible(8, 1, 8)      # MQA
    assert not pallas_tp_compatible(6, 3, 4)  # Hq % tp != 0
    assert not pallas_tp_compatible(8, 3, 4)  # shard straddles kv heads


def test_engine_tp2_pallas_matches_tp1_xla(tmp_path):
    """End-to-end: tp=2 with attention_impl='pallas' (shard_map + interpret
    kernels) generates byte-identical greedy output to tp=1 XLA."""
    from transformers import LlamaConfig, LlamaForCausalLM

    from gllm_tpu.config import CacheConfig, EngineConfig, ParallelConfig
    from gllm_tpu.engine.llm import LLM
    from gllm_tpu.sampling_params import SamplingParams

    tiny = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=8, num_key_value_heads=4,
                intermediate_size=96, max_position_embeddings=256,
                rope_theta=10000.0, tie_word_embeddings=False,
                eos_token_id=0)
    torch.manual_seed(5)
    LlamaForCausalLM(LlamaConfig(**tiny)).save_pretrained(
        tmp_path, safe_serialization=True)

    def run(tp, impl):
        cfg = EngineConfig(
            model=str(tmp_path), dtype="float32", max_model_len=128,
            attention_impl=impl,
            cache=CacheConfig(page_size=4, num_pages=64),
            parallel=ParallelConfig(tp=tp))
        llm = LLM(config=cfg)
        outs = llm.generate(
            prompt_token_ids=[[3, 14, 15, 92, 65], [6, 53]],
            sampling_params=SamplingParams(temperature=0.0, max_tokens=6,
                                           ignore_eos=True))
        return [o.output_token_ids for o in outs]

    assert run(2, "pallas") == run(1, "xla")
