"""GDN ops vs the HF Qwen3Next torch reference math."""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from gllm_tpu.ops import gdn

hf = pytest.importorskip(
    "transformers.models.qwen3_next.modeling_qwen3_next")


def rand(rng, *shape):
    return rng.standard_normal(shape).astype(np.float32)


@pytest.mark.parametrize("T,chunk", [(1, 16), (7, 4), (64, 16), (100, 32)])
def test_chunk_rule_matches_hf(T, chunk):
    rng = np.random.default_rng(0)
    S, H, Dk, Dv = 2, 3, 8, 16
    q, k = rand(rng, S, T, H, Dk), rand(rng, S, T, H, Dk)
    v = rand(rng, S, T, H, Dv)
    g = -np.abs(rand(rng, S, T, H))
    beta = 1 / (1 + np.exp(-rand(rng, S, T, H)))
    init = rand(rng, S, H, Dk, Dv)

    want, want_state = hf.torch_chunk_gated_delta_rule(
        torch.tensor(q), torch.tensor(k), torch.tensor(v),
        torch.tensor(g), torch.tensor(beta), chunk_size=chunk,
        initial_state=torch.tensor(init), output_final_state=True,
        use_qk_l2norm_in_kernel=True)

    got, got_state = gdn.chunk_gated_delta_rule(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(g),
        jnp.asarray(beta), initial_state=jnp.asarray(init),
        chunk_size=chunk)
    np.testing.assert_allclose(np.asarray(got), want.numpy(),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got_state), want_state.numpy(),
                               rtol=2e-4, atol=2e-4)


def test_recurrent_step_matches_hf():
    rng = np.random.default_rng(1)
    S, H, Dk, Dv = 3, 2, 8, 16
    q, k = rand(rng, S, 1, H, Dk), rand(rng, S, 1, H, Dk)
    v = rand(rng, S, 1, H, Dv)
    g = -np.abs(rand(rng, S, 1, H))
    beta = 1 / (1 + np.exp(-rand(rng, S, 1, H)))
    init = rand(rng, S, H, Dk, Dv)

    want, want_state = hf.torch_recurrent_gated_delta_rule(
        torch.tensor(q), torch.tensor(k), torch.tensor(v),
        torch.tensor(g), torch.tensor(beta),
        initial_state=torch.tensor(init), output_final_state=True,
        use_qk_l2norm_in_kernel=True)

    got, got_state = gdn.recurrent_gated_delta_step(
        jnp.asarray(q[:, 0]), jnp.asarray(k[:, 0]), jnp.asarray(v[:, 0]),
        jnp.asarray(g[:, 0]), jnp.asarray(beta[:, 0]), jnp.asarray(init))
    np.testing.assert_allclose(np.asarray(got), want.numpy()[:, 0],
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got_state), want_state.numpy(),
                               rtol=2e-4, atol=2e-4)


def test_chunk_then_recurrent_continuation():
    """State handoff: chunked prefill followed by recurrent decode steps
    equals one chunked pass over the whole sequence."""
    rng = np.random.default_rng(2)
    S, T, H, Dk, Dv = 2, 20, 2, 8, 8
    q, k = rand(rng, S, T, H, Dk), rand(rng, S, T, H, Dk)
    v = rand(rng, S, T, H, Dv)
    g = -np.abs(rand(rng, S, T, H))
    beta = 1 / (1 + np.exp(-rand(rng, S, T, H)))

    full, full_state = gdn.chunk_gated_delta_rule(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(g),
        jnp.asarray(beta), chunk_size=8)

    split = 15
    part, state = gdn.chunk_gated_delta_rule(
        jnp.asarray(q[:, :split]), jnp.asarray(k[:, :split]),
        jnp.asarray(v[:, :split]), jnp.asarray(g[:, :split]),
        jnp.asarray(beta[:, :split]), chunk_size=8)
    outs = [np.asarray(part)]
    for t in range(split, T):
        o, state = gdn.recurrent_gated_delta_step(
            jnp.asarray(q[:, t]), jnp.asarray(k[:, t]),
            jnp.asarray(v[:, t]), jnp.asarray(g[:, t]),
            jnp.asarray(beta[:, t]), state)
        outs.append(np.asarray(o)[:, None])
    got = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(got, np.asarray(full), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(state), np.asarray(full_state),
                               rtol=2e-4, atol=2e-4)


def test_padded_tokens_are_identity():
    """g = 0, beta = 0 rows leave the state unchanged (ragged batching)."""
    rng = np.random.default_rng(3)
    S, T, H, Dk, Dv = 1, 12, 2, 8, 8
    q, k = rand(rng, S, T, H, Dk), rand(rng, S, T, H, Dk)
    v = rand(rng, S, T, H, Dv)
    g = -np.abs(rand(rng, S, T, H))
    beta = 1 / (1 + np.exp(-rand(rng, S, T, H)))
    valid = 7
    g2 = g.copy()
    beta2 = beta.copy()
    g2[:, valid:] = 0.0
    beta2[:, valid:] = 0.0

    _, state_padded = gdn.chunk_gated_delta_rule(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(g2),
        jnp.asarray(beta2), chunk_size=4)
    _, state_exact = gdn.chunk_gated_delta_rule(
        jnp.asarray(q[:, :valid]), jnp.asarray(k[:, :valid]),
        jnp.asarray(v[:, :valid]), jnp.asarray(g[:, :valid]),
        jnp.asarray(beta[:, :valid]), chunk_size=4)
    np.testing.assert_allclose(np.asarray(state_padded),
                               np.asarray(state_exact),
                               rtol=2e-4, atol=2e-4)


def test_causal_conv1d_state_handoff():
    rng = np.random.default_rng(4)
    S, T, C, K = 2, 10, 6, 4
    x = rand(rng, S, T, C)
    w = rand(rng, C, K)
    state0 = np.zeros((S, C, K - 1), np.float32)
    q_lens = np.asarray([T, 7], np.int32)

    out, new_state = gdn.causal_conv1d(jnp.asarray(x), jnp.asarray(state0),
                                       jnp.asarray(w),
                                       jnp.asarray(q_lens))
    # torch oracle per seq (full conv over valid prefix)
    import torch.nn.functional as F
    for s, L in enumerate(q_lens):
        xs = torch.tensor(x[s, :L].T[None])           # [1, C, L]
        ref = F.conv1d(F.pad(xs, (K - 1, 0)), torch.tensor(w)[:, None, :],
                       groups=C)
        ref = F.silu(ref)[0].T.numpy()
        np.testing.assert_allclose(np.asarray(out)[s, :L], ref,
                                   rtol=1e-5, atol=1e-5)
        # state = last K-1 valid inputs
        want_state = x[s, L - (K - 1):L].T
        np.testing.assert_allclose(np.asarray(new_state)[s], want_state,
                                   rtol=1e-6, atol=1e-6)

    # continuation: feed next chunk with carried state == full-seq conv
    x2 = rand(rng, S, 5, C)
    out2, _ = gdn.causal_conv1d(jnp.asarray(x2), new_state, jnp.asarray(w),
                                jnp.asarray([5, 5], np.int32))
    full = np.concatenate([x[1:2, :7], x2[1:2]], axis=1)
    ref_full = F.silu(F.conv1d(
        F.pad(torch.tensor(full.transpose(0, 2, 1)), (K - 1, 0)),
        torch.tensor(w)[:, None, :], groups=C))[0].T.numpy()
    np.testing.assert_allclose(np.asarray(out2)[1], ref_full[7:],
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("T,chunk", [(7, 4), (64, 16), (100, 32)])
def test_pallas_scan_matches_xla(T, chunk):
    """The fused VMEM-scan kernel (ops/pallas/gdn_scan.py, interpret mode
    on CPU) is numerically the XLA chunk scan."""
    rng = np.random.default_rng(3)
    S, H, Dk, Dv = 2, 3, 8, 16
    q, k = rand(rng, S, T, H, Dk), rand(rng, S, T, H, Dk)
    v = rand(rng, S, T, H, Dv)
    g = -np.abs(rand(rng, S, T, H))
    beta = 1 / (1 + np.exp(-rand(rng, S, T, H)))
    init = rand(rng, S, H, Dk, Dv)

    args = (jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(g),
            jnp.asarray(beta))
    ref, ref_state = gdn.chunk_gated_delta_rule(
        *args, initial_state=jnp.asarray(init), chunk_size=chunk)
    got, got_state = gdn.chunk_gated_delta_rule(
        *args, initial_state=jnp.asarray(init), chunk_size=chunk,
        impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_state), np.asarray(ref_state),
                               rtol=1e-5, atol=1e-5)


def test_pallas_scan_ragged_padding():
    """Padded tokens (g=0, beta=0) are identity on the state through the
    kernel, matching the batched-ragged contract."""
    rng = np.random.default_rng(4)
    S, T, H, Dk, Dv = 2, 20, 2, 8, 8
    q, k = rand(rng, S, T, H, Dk), rand(rng, S, T, H, Dk)
    v = rand(rng, S, T, H, Dv)
    g = -np.abs(rand(rng, S, T, H))
    beta = 1 / (1 + np.exp(-rand(rng, S, T, H)))
    q_lens = [20, 13]
    for s, ql in enumerate(q_lens):
        g[s, ql:] = 0.0
        beta[s, ql:] = 0.0
    ref, ref_state = gdn.chunk_gated_delta_rule(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(g),
        jnp.asarray(beta), chunk_size=8)
    got, got_state = gdn.chunk_gated_delta_rule(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(g),
        jnp.asarray(beta), chunk_size=8, impl="pallas")
    np.testing.assert_allclose(np.asarray(got_state),
                               np.asarray(ref_state), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
