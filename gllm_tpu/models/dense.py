"""Generic dense GQA decoder (Llama / Qwen2 / Qwen3 / ChatGLM-class).

The TPU-native re-design of the reference's canonical model shape
(/root/reference/gllm/models/qwen2.py:186-270, from which llama.py and
qwen3.py derive). Differences by design:

- **Functional**: params are a pytree; `forward` is a pure function traced
  once per shape bucket. No modules, no mutable state.
- **Stacked layers + lax.scan**: per-layer weights are stacked on a leading
  [L, ...] axis and the decoder runs as one `lax.scan`, so compile time and
  HLO size are O(1) in depth (a 32- vs 80-layer model compiles equally fast).
  The KV caches ride in the scan carry and are updated in place per layer —
  XLA aliases carry buffers, so there is no cache copy.
- **Rank-aware**: `first_layer:last_layer` selects this PP stage's slice;
  embeddings exist only on the first stage, final norm + head only on the
  last (mirrors the reference's per-stage builds).

Weight layout is [in, out] (x @ W), transposed from HF's [out, in] at load
time (gllm_tpu/models/loader.py).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from gllm_tpu.batching import StepBatch
from gllm_tpu.models.config import ModelConfig
from gllm_tpu.ops import (apply_rope, compute_rope_cos_sin,
                          fused_add_rms_norm, paged_attention, rms_norm,
                          silu_and_mul, write_kv, write_kv_quant)
from gllm_tpu.ops.rope import apply_mrope, apply_rope_interleaved
from gllm_tpu.ops.quant import qmm
from gllm_tpu.parallel.mesh import shard_hint

Params = Dict[str, Any]


class KVCache(NamedTuple):
    """Stacked per-stage KV cache: [L, num_pages, page_size, Hkv, D].

    ``kv_cache_dtype=int8`` stores k/v as int8 and adds the running
    per-page per-kv-head f32 scales ([L, num_pages, Hkv]; dequant is
    q * scale — ops/kv_cache.write_kv_quant owns the write-side
    contract). The scale leaves keep the page axis at position 1 like
    every other leaf, so the kvswap host tier and the DP stacking treat
    them as ordinary cache payload. None = full-precision legacy cache.
    """
    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None


def init_kv_cache(cfg: ModelConfig, num_pages: int, page_size: int,
                  dtype=jnp.bfloat16, kv_pack: int = 1) -> KVCache:
    """kv_pack > 1 packs that many adjacent kv heads into the lane dim
    ([.., Hkv/pack, D*pack]) so head_dim < 128 models meet Mosaic's
    128-lane tiling on the Pallas path (ops/attention.py pack handling).
    An int8 ``dtype`` builds the quantized cache (scales ride along; a
    zero scale marks a never-written page)."""
    assert cfg.num_kv_heads % kv_pack == 0
    shape = (cfg.num_stage_layers, num_pages, page_size,
             cfg.num_kv_heads // kv_pack, cfg.head_dim * kv_pack)
    if jnp.dtype(dtype) == jnp.int8:
        sshape = shape[:2] + (shape[3],)     # [L, P, Hkv/pack]
        return KVCache(jnp.zeros(shape, jnp.int8),
                       jnp.zeros(shape, jnp.int8),
                       jnp.zeros(sshape, jnp.float32),
                       jnp.zeros(sshape, jnp.float32))
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# Parameter initialization (dummy-load path, reference --load-format dummy)
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, seed: int = 0,
                dtype=jnp.bfloat16) -> Params:
    """Random params with sane scales (for weight-less bring-up and tests)."""
    L = cfg.num_stage_layers
    H, D = cfg.hidden_size, cfg.head_dim
    Hq, Hkv, I = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size
    key = jax.random.key(seed)
    ks = iter(jax.random.split(key, 16))

    def w(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32)
                * scale).astype(dtype)

    params: Params = {}
    scale = H ** -0.5
    layers = {
        "input_norm": jnp.ones((L, H), dtype),
        "q_proj": w(next(ks), (L, H, Hq * D), scale),
        "k_proj": w(next(ks), (L, H, Hkv * D), scale),
        "v_proj": w(next(ks), (L, H, Hkv * D), scale),
        "o_proj": w(next(ks), (L, Hq * D, H), (Hq * D) ** -0.5),
        "post_attn_norm": jnp.ones((L, H), dtype),
        "gate_proj": w(next(ks), (L, H, I), scale),
        "up_proj": w(next(ks), (L, H, I), scale),
        "down_proj": w(next(ks), (L, I, H), I ** -0.5),
    }
    if cfg.attention_bias:
        layers["q_bias"] = jnp.zeros((L, Hq * D), dtype)
        layers["k_bias"] = jnp.zeros((L, Hkv * D), dtype)
        layers["v_bias"] = jnp.zeros((L, Hkv * D), dtype)
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, D), dtype)
        layers["k_norm"] = jnp.ones((L, D), dtype)
    if cfg.sandwich_norms:
        # GLM4 normalizes each sublayer OUTPUT before the residual add
        layers["post_self_attn_norm"] = jnp.ones((L, H), dtype)
        layers["post_mlp_norm"] = jnp.ones((L, H), dtype)
    params["layers"] = layers
    if cfg.is_first_stage:
        params["embed"] = w(next(ks), (cfg.vocab_size, H), 1.0)
    if cfg.is_last_stage:
        params["final_norm"] = jnp.ones((H,), dtype)
        if not cfg.tie_word_embeddings:
            params["lm_head"] = w(next(ks), (H, cfg.vocab_size), scale)
    return params


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _attention(lp, x, batch: StepBatch, k_all, v_all, cfg: ModelConfig,
               cos_sin, *, attn_impl: str, max_q_len: int, li,
               ks_all=None, vs_all=None):
    """One layer's attention against the STACKED [L, P, ...] cache.

    The cache is addressed through a flat [L*P, ...] view with the layer
    offset folded into the page table (+ li*P) and slot mapping
    (+ li*P*page): the scan carry is only ever touched by a sparse
    scatter (in-place under donation) and the kernels' page DMAs — the
    earlier per-layer dynamic_index/dynamic_update_index round-trip
    materialized TWO full layer-slice copies per layer per step (~26 ms
    of a ~38 ms decode step on the r5 chip). Page 0 of every layer is
    that layer's dummy page, so offset padding entries stay harmless.

    ``ks_all``/``vs_all`` present marks the int8 quantized cache
    (kv_cache_dtype=int8): new rows quantize at write time against the
    running per-page absmax scale and the kernels dequantize in VMEM —
    the flat [L*P, Hkv] scale view is indexed by the same offset page
    ids as the cache itself."""
    T = x.shape[0]
    Hq, Hkv, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    L, P, page_size = k_all.shape[0], k_all.shape[1], k_all.shape[2]
    quant = ks_all is not None
    k_cache = k_all.reshape((L * P,) + k_all.shape[2:])
    v_cache = v_all.reshape((L * P,) + v_all.shape[2:])
    k_scale = ks_all.reshape((L * P,) + ks_all.shape[2:]) if quant else None
    v_scale = vs_all.reshape((L * P,) + vs_all.shape[2:]) if quant else None

    q = qmm(x, lp["q_proj"])
    k = qmm(x, lp["k_proj"])
    v = qmm(x, lp["v_proj"])
    if "q_bias" in lp:
        q = q + lp["q_bias"]
        k = k + lp["k_bias"]
        v = v + lp["v_bias"]
    q = shard_hint(q.reshape(T, Hq, D), None, "tp", None)
    k = shard_hint(k.reshape(T, Hkv, D), None, "tp", None)
    v = shard_hint(v.reshape(T, Hkv, D), None, "tp", None)
    if cfg.qk_norm:
        # per-head RMSNorm over D (reference qwen3.py adds q/k norms)
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    if cfg.mrope_section and batch.mrope_positions is not None:
        q, k = apply_mrope(q, k, batch.mrope_positions, cos_sin,
                           cfg.mrope_section,
                           interleaved=cfg.mrope_interleaved)
    else:
        rope_fn = (apply_rope_interleaved if cfg.rope_interleaved
                   else apply_rope)
        q, k = rope_fn(q, k, batch.positions, cos_sin)
    if quant:
        k_cache, v_cache, k_scale, v_scale = write_kv_quant(
            k_cache, v_cache, k_scale, v_scale, k, v,
            batch.slot_mapping + li * (P * page_size), page_size)
    else:
        k_cache, v_cache = write_kv(
            k_cache, v_cache, k, v,
            batch.slot_mapping + li * (P * page_size))
    if attn_impl == "ring":
        # Sequence-parallel prefill (sp mesh axis): the runner routes a
        # single-seq from-position-0 chunk here — self-attention over the
        # fresh k/v runs as causal ring attention (ICI neighbor
        # exchanges), no paged gather at all. KV was still written above
        # for the decode steps that follow. Bucketed padding rows are
        # masked via kv_valid (padded KEYS must not leak into real rows).
        from gllm_tpu.parallel.mesh import AXIS_SP
        from gllm_tpu.parallel.ring_attention import ring_attention_sharded
        attn = ring_attention_sharded(q, k, v, axis_name=AXIS_SP,
                                      scale=D ** -0.5,
                                      kv_valid=batch.attn.kv_lens[0])
    else:
        md = batch.attn._replace(
            page_table=batch.attn.page_table + li * P)
        attn = paged_attention(q, k_cache, v_cache, md,
                               scale=D ** -0.5, max_q_len=max_q_len,
                               impl=attn_impl,
                               k_scale=k_scale, v_scale=v_scale)
    out = qmm(attn.reshape(T, Hq * D), lp["o_proj"])
    return (out, k_cache.reshape(k_all.shape),
            v_cache.reshape(v_all.shape),
            k_scale.reshape(ks_all.shape) if quant else None,
            v_scale.reshape(vs_all.shape) if quant else None)


def _mlp(lp, x):
    gate = shard_hint(qmm(x, lp["gate_proj"]), None, "tp")
    up = shard_hint(qmm(x, lp["up_proj"]), None, "tp")
    fused = silu_and_mul(jnp.concatenate([gate, up], axis=-1))
    return qmm(fused, lp["down_proj"])


def forward(
    params: Params,
    kv: KVCache,
    batch: StepBatch,
    cfg: ModelConfig,
    *,
    cos_sin: jnp.ndarray,
    attn_impl: str = "xla",
    max_q_len: int,
    hidden_in: Optional[jnp.ndarray] = None,
    residual_in: Optional[jnp.ndarray] = None,
    mlp_fn=None,
    deepstack: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, KVCache]:
    """Run this stage's layers. Returns (hidden, residual, new_kv).

    First stage embeds `batch.token_ids`; later PP stages take
    (hidden_in, residual_in) received from the previous stage. ``mlp_fn``
    swaps the MLP half of each block (MoE models pass their routed-expert
    MLP); the attention half and scan plumbing are shared. ``deepstack``
    is [n_levels, T, H] visual residuals: level i is added to the hidden
    stream after global layer i (Qwen3-VL; reference qwen3_vl.py:436-469).
    """
    if mlp_fn is None:
        mlp_fn = _mlp
    if cfg.is_first_stage:
        hidden = params["embed"][batch.token_ids]
        if batch.mm_embeds is not None:
            # Visual rows come pre-embedded by the vision tower; splice
            # them over the placeholder-token embeddings (reference
            # embed_input_ids merge, qwen2_5_vl.py:972-996).
            mm_main = batch.mm_embeds[:, :cfg.hidden_size]
            hidden = jnp.where(batch.mm_mask[:, None],
                               mm_main.astype(hidden.dtype), hidden)
        residual = jnp.zeros_like(hidden)
    else:
        hidden, residual = hidden_in, residual_in

    def layer_step(carry, lp):
        h, res, k_all, v_all, ks_all, vs_all, li = carry
        normed, res = fused_add_rms_norm(h, res, lp["input_norm"],
                                         cfg.rms_norm_eps)
        attn_out, k_all, v_all, ks_all, vs_all = _attention(
            lp, normed, batch, k_all, v_all, cfg, cos_sin,
            attn_impl=attn_impl, max_q_len=max_q_len, li=li,
            ks_all=ks_all, vs_all=vs_all)
        if cfg.sandwich_norms:
            attn_out = rms_norm(attn_out, lp["post_self_attn_norm"],
                                cfg.rms_norm_eps)
        normed2, res = fused_add_rms_norm(attn_out, res,
                                         lp["post_attn_norm"],
                                         cfg.rms_norm_eps)
        mlp_out = mlp_fn(lp, normed2)
        if cfg.sandwich_norms:
            mlp_out = rms_norm(mlp_out, lp["post_mlp_norm"],
                               cfg.rms_norm_eps)
        if deepstack is not None:
            # residual stream after this layer = mlp_out + res; adding the
            # level-indexed visual delta to mlp_out is equivalent to HF's
            # hidden_states += deepstack_input_embeds[layer_idx].
            nds = deepstack.shape[0]
            gl = li + cfg.first_layer
            ds = jax.lax.dynamic_index_in_dim(
                deepstack, jnp.minimum(gl, nds - 1), 0, keepdims=False)
            mlp_out = mlp_out + jnp.where(gl < nds, ds,
                                          jnp.zeros_like(ds))
        return (mlp_out, res, k_all, v_all, ks_all, vs_all, li + 1), None

    init = (hidden, residual, kv.k, kv.v, kv.k_scale, kv.v_scale,
            jnp.int32(0))
    (hidden, residual, k_all, v_all, ks_all, vs_all, _), _ = jax.lax.scan(
        layer_step, init, params["layers"])
    return hidden, residual, KVCache(k_all, v_all, ks_all, vs_all)


def compute_full_logits(params: Params, hidden: jnp.ndarray,
                        residual: jnp.ndarray,
                        cfg: ModelConfig) -> jnp.ndarray:
    """Logits for EVERY token row [T, V] (prompt-logprob path). Single
    source of truth for the final-norm + head projection; compute_logits
    is the [S]-row gather specialization of the same math."""
    final = hidden + residual
    normed = rms_norm(final, params["final_norm"], cfg.rms_norm_eps)
    head = (params["embed"].T if cfg.tie_word_embeddings
            else params["lm_head"])
    return shard_hint((normed @ head).astype(jnp.float32), None, None)


def compute_logits(params: Params, hidden: jnp.ndarray,
                   residual: jnp.ndarray, batch: StepBatch,
                   cfg: ModelConfig) -> jnp.ndarray:
    """Gather last-token hidden per sequence, final-norm, project to vocab.

    Mirrors the reference compute_logits (gather at query_start_loc-1 then
    head, qwen2.py): gathering [S, H] *before* the vocab matmul keeps the
    head GEMM at S rows instead of T.
    """
    final = hidden + residual
    sel = final[batch.logits_indices]                       # [S, H]
    sel = rms_norm(sel, params["final_norm"], cfg.rms_norm_eps)
    head = (params["embed"].T if cfg.tie_word_embeddings
            else params["lm_head"])
    # All-gather the vocab-sharded logits before sampling (the reference's
    # logits all-gather, vocab_parallel_embedding.py): the sampler sorts over
    # the full vocab per row.
    return shard_hint((sel @ head).astype(jnp.float32), None, None)


def make_rope_table(cfg: ModelConfig) -> jnp.ndarray:
    rot_dim = int(cfg.head_dim * cfg.partial_rotary_factor)
    return compute_rope_cos_sin(rot_dim, cfg.max_position,
                                cfg.rope_theta, cfg.rope_scaling)
